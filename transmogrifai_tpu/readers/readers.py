"""Data readers: records → FeatureTable.

Mirrors the reference reader layer (reference:
readers/src/main/scala/com/salesforce/op/readers/DataReader.scala:57-198,
DataReaders.scala:44-278, CSVAutoReaders.scala) re-designed columnar: instead of
mapping every record through every raw feature's ``extractFn`` into Spark Rows
(DataReader.generateDataFrame:173-197), readers ingest whole columns (pandas /
pyarrow on host) and only fall back to the row loop for features with custom
extract functions. Field-name extractors — the overwhelmingly common case — hit
a vectorized numpy path, so a 1M-row CSV ingests in milliseconds rather than
through a million Python calls per feature.
"""
from __future__ import annotations

import abc
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Type

import numpy as np

from ..features import Feature
from ..table import Column, FeatureTable
from ..types import (
    Binary, Date, DateTime, FeatureType, Integral, Real, Text,
)


class Reader(abc.ABC):
    """Base reader (reference Reader.scala)."""

    def __init__(self, key_fn: Optional[Callable[[Any], str]] = None,
                 key_field: Optional[str] = None):
        self.key_fn = key_fn
        self.key_field = key_field

    @abc.abstractmethod
    def read(self, params: Optional[dict] = None):
        """Return the raw data as a pandas DataFrame (host-side)."""

    def generate_table(self, raw_features: Sequence[Feature],
                       params: Optional[dict] = None) -> FeatureTable:
        """Materialize the raw FeatureTable for these features (the analog of
        reference DataReader.generateDataFrame:173)."""
        df = self.read(params)
        return dataframe_to_table(df, raw_features, key_field=self.key_field,
                                  key_fn=self.key_fn)


class DataReader(Reader):
    """Simple (non-aggregating) reader over a record source."""


def _field_name_of(extract_fn: Callable) -> Optional[str]:
    """Detect the builder's field extractor so ingestion can vectorize."""
    name = getattr(extract_fn, "__name__", "")
    if name.startswith("extract_"):
        return name[len("extract_"):]
    return None


def series_to_column(feature_type: Type[FeatureType], series) -> Column:
    """Vectorized pandas Series → Column conversion (hot ingestion path)."""
    import pandas as pd

    kind = feature_type.column_kind
    if kind in ("real", "binary", "integral", "date"):
        num = pd.to_numeric(series, errors="coerce")
        arr = num.to_numpy(dtype=np.float64, na_value=np.nan)
        mask = ~np.isnan(arr)
        filled = np.where(mask, arr, 0.0)
        if kind == "real":
            return Column(feature_type, filled.astype(np.float32), mask)
        if kind == "binary":
            return Column(feature_type, (filled != 0.0).astype(np.float32), mask)
        return Column(feature_type, filled.astype(np.int64), mask)
    if kind == "text":
        vals = series.to_numpy(dtype=object)
        mask = np.array([isinstance(v, str) and v != "" for v in vals], dtype=bool)
        out = np.empty(len(vals), dtype=object)
        for i, (v, m) in enumerate(zip(vals, mask)):
            out[i] = v if m else None
        return Column(feature_type, out, mask)
    # lists/maps/geolocation arrive as python objects in the frame
    return Column.of_values(feature_type, list(series))


def dataframe_to_table(df, raw_features: Sequence[Feature],
                       key_field: Optional[str] = None,
                       key_fn: Optional[Callable[[Any], str]] = None,
                       ) -> FeatureTable:
    """pandas DataFrame → FeatureTable, vectorizing field extractors and
    falling back to the record loop for custom extract functions."""
    cols: Dict[str, Column] = {}
    slow_feats: List[Feature] = []
    missing: List[str] = []
    for f in raw_features:
        stage = f.origin_stage
        field = _field_name_of(stage.extract_fn)
        if field is not None:
            if field in df.columns:
                cols[f.name] = series_to_column(f.feature_type, df[field])
            else:
                missing.append(field)  # silent all-null columns poison scoring
        else:
            slow_feats.append(f)
    if missing:
        raise ValueError(
            f"raw feature field(s) {missing} not present in the data "
            f"(columns: {list(df.columns)})")
    if slow_feats:
        records = df.to_dict("records")
        for f in slow_feats:
            stage = f.origin_stage
            vals = [stage.extract(r) for r in records]
            cols[f.name] = Column.of_values(f.feature_type, vals)
    key = None
    if key_field is not None and key_field in df.columns:
        key = df[key_field].astype(str).to_numpy(dtype=object)
    elif key_fn is not None:
        key = np.array([key_fn(r) for r in df.to_dict("records")], dtype=object)
    return FeatureTable(cols, len(df), key)


class DataFrameReader(DataReader):
    """Reader over an in-memory pandas DataFrame (the analog of
    setInputDataset, reference OpWorkflowCore.scala:146-170)."""

    def __init__(self, df, **kw):
        super().__init__(**kw)
        self.df = df

    def read(self, params: Optional[dict] = None):
        return self.df


class CSVReader(DataReader):
    """CSV with an explicit schema (reference CSVReaders.scala)."""

    def __init__(self, path: str, schema: Optional[Sequence[str]] = None,
                 header: bool = True, **kw):
        super().__init__(**kw)
        self.path = path
        self.schema = list(schema) if schema else None
        self.header = header

    def read(self, params: Optional[dict] = None):
        import pandas as pd
        path = (params or {}).get("path", self.path)
        if self.header:
            return pd.read_csv(path)
        return pd.read_csv(path, header=None, names=self.schema)


class CSVAutoReader(CSVReader):
    """CSV with inferred schema (reference CSVAutoReaders.scala:142)."""


class ParquetReader(DataReader):
    """Parquet files (reference ParquetProductReader.scala)."""

    def __init__(self, path: str, **kw):
        super().__init__(**kw)
        self.path = path

    def read(self, params: Optional[dict] = None):
        import pandas as pd
        return pd.read_parquet((params or {}).get("path", self.path))


class AvroReader(DataReader):
    """Avro container files (reference AvroReaders.scala:134; decoding via
    the in-repo pure-python container codec, utils/avro.py). Nested record
    fields flatten dotted (a.b) to match FeatureBuilder field extraction."""

    def __init__(self, path: str, **kw):
        super().__init__(**kw)
        self.path = path

    @staticmethod
    def _flatten(rec, prefix=""):
        out = {}
        for k, v in rec.items():
            key = f"{prefix}{k}"
            if isinstance(v, dict) and v and all(
                    isinstance(x, (dict, str, int, float, bool, type(None),
                                   list)) for x in v.values()) \
                    and any(isinstance(x, dict) for x in v.values()):
                out.update(AvroReader._flatten(v, f"{key}."))
            else:
                out[key] = v
        return out

    def read(self, params: Optional[dict] = None):
        import pandas as pd
        from ..utils.avro import read_avro
        path = (params or {}).get("path", self.path)
        return pd.DataFrame([self._flatten(r) for r in read_avro(path)])


class StreamingDataReader(Reader):
    """Micro-batch scoring input (reference StreamingReaders.scala — DStream
    micro-batches become an iterator of DataFrames; each batch materializes
    one FeatureTable for the device, SURVEY §2.10 P4)."""

    def __init__(self, batches: Iterable, **kw):
        super().__init__(**kw)
        self.batches = batches

    def read(self, params: Optional[dict] = None):
        raise ValueError("streaming readers produce batches; use stream_tables")

    def generate_table(self, raw_features: Sequence[Feature],
                       params: Optional[dict] = None) -> FeatureTable:
        raise ValueError("streaming readers produce batches; use stream_tables")

    def stream_tables(self, raw_features: Sequence[Feature]):
        for df in self.batches:
            yield dataframe_to_table(df, raw_features,
                                     key_field=self.key_field,
                                     key_fn=self.key_fn)


class DataReaders:
    """Factory namespace (reference DataReaders.scala:44-278)."""

    class Simple:
        @staticmethod
        def csv(path: str, schema: Optional[Sequence[str]] = None,
                header: bool = True, key_field: Optional[str] = None) -> CSVReader:
            return CSVReader(path, schema=schema, header=header, key_field=key_field)

        @staticmethod
        def csv_auto(path: str, key_field: Optional[str] = None) -> CSVAutoReader:
            return CSVAutoReader(path, key_field=key_field)

        @staticmethod
        def parquet(path: str, key_field: Optional[str] = None) -> ParquetReader:
            return ParquetReader(path, key_field=key_field)

        @staticmethod
        def dataframe(df, key_field: Optional[str] = None) -> DataFrameReader:
            return DataFrameReader(df, key_field=key_field)

        @staticmethod
        def avro(path: str, key_field: Optional[str] = None) -> AvroReader:
            return AvroReader(path, key_field=key_field)

    class Aggregate:
        """Event-aggregating variants (reference DataReaders.Aggregate)."""

        @staticmethod
        def csv(path: str, aggregate_params, key_field: str,
                schema: Optional[Sequence[str]] = None, header: bool = True):
            from .aggregates import AggregateDataReader
            return AggregateDataReader(
                CSVReader(path, schema=schema, header=header),
                aggregate_params, key_field=key_field)

        @staticmethod
        def avro(path: str, aggregate_params, key_field: str):
            from .aggregates import AggregateDataReader
            return AggregateDataReader(AvroReader(path), aggregate_params,
                                       key_field=key_field)

        @staticmethod
        def dataframe(df, aggregate_params, key_field: str):
            from .aggregates import AggregateDataReader
            return AggregateDataReader(DataFrameReader(df), aggregate_params,
                                       key_field=key_field)

    class Conditional:
        """Conditional-aggregation variants (reference DataReaders.Conditional)."""

        @staticmethod
        def csv(path: str, conditional_params, key_field: str,
                schema: Optional[Sequence[str]] = None, header: bool = True):
            from .aggregates import ConditionalDataReader
            return ConditionalDataReader(
                CSVReader(path, schema=schema, header=header),
                conditional_params, key_field=key_field)

        @staticmethod
        def dataframe(df, conditional_params, key_field: str):
            from .aggregates import ConditionalDataReader
            return ConditionalDataReader(DataFrameReader(df), conditional_params,
                                         key_field=key_field)

        @staticmethod
        def avro(path: str, conditional_params, key_field: str):
            from .aggregates import ConditionalDataReader
            return ConditionalDataReader(AvroReader(path), conditional_params,
                                         key_field=key_field)

    class Streaming:
        @staticmethod
        def batches(batches: Iterable, key_field: Optional[str] = None):
            return StreamingDataReader(batches, key_field=key_field)
