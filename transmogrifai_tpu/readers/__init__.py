from .readers import (
    DataReader, CSVReader, CSVAutoReader, ParquetReader, DataFrameReader,
    DataReaders,
)
