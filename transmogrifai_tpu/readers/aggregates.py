"""Aggregating / conditional / joined readers.

Mirrors the reference event readers (reference:
readers/src/main/scala/com/salesforce/op/readers/DataReader.scala:206-368 —
AggregateDataReader groups events by key and monoid-aggregates predictors
before the cutoff and responses after; ConditionalDataReader finds per-key
times where a target condition fires and aggregates windows around them;
JoinedDataReader.scala joins readers on keys).

Aggregation is host work (irregular, string-keyed grouping) producing one
columnar FeatureTable whose arrays then move to the device — the analog of
the reference's executor-side reduceByKey before the DataFrame materializes.
"""
from __future__ import annotations

import random as _random
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..aggregators import CutOffTime, MonoidAggregator, default_aggregator
from ..features import Feature
from ..table import Column, FeatureTable
from .readers import DataReader, Reader, dataframe_to_table


def _timestamp_getter(timestamp_field: Optional[str],
                      timestamp_fn: Optional[Callable[[Any], Optional[int]]]
                      ) -> Callable[[dict], Optional[int]]:
    if timestamp_fn is not None:
        return lambda r: timestamp_fn(r)
    if timestamp_field is not None:
        def get(r):
            v = r.get(timestamp_field)
            return None if v is None else int(v)
        return get
    return lambda r: None


class AggregateParams:
    """(reference AggregateParams: timeStampFn + cutOffTime)."""

    def __init__(self, cutoff: CutOffTime,
                 timestamp_field: Optional[str] = None,
                 timestamp_fn: Optional[Callable[[Any], Optional[int]]] = None):
        self.cutoff = cutoff
        self.timestamp = _timestamp_getter(timestamp_field, timestamp_fn)


def _aggregate_groups(groups: "Dict[str, List[Tuple[Optional[int], dict]]]",
                      raw_features: Sequence[Feature],
                      cutoff_of: Callable[[str], Optional[int]],
                      response_window_default: Optional[int] = None,
                      strict_predictor: bool = False,
                      ) -> FeatureTable:
    """Fold each key's time-sorted events into one row (reference
    FeatureAggregator.extract: predictors aggregate the trailing window
    (cutoff−window, cutoff]; responses the leading window
    (cutoff, cutoff+window]; windowless features take everything on their
    side of the cutoff). ``strict_predictor`` excludes events AT the cutoff
    from predictors — conditional readers use it so the condition-firing
    event itself is neither predictor nor response (reference
    ConditionalDataReader: predictors strictly before the target event)."""
    keys = sorted(groups)
    cols: Dict[str, Column] = {}
    for f in raw_features:
        gen = f.origin_stage
        agg: MonoidAggregator = gen.aggregator or default_aggregator(f.feature_type)
        window = gen.aggregate_window
        if f.is_response and window is None:
            window = response_window_default
        out_vals: List[Any] = []
        for k in keys:
            events = groups[k]   # sorted by time (None times first)
            cutoff = cutoff_of(k)
            vals = []
            for t, rec in events:
                if cutoff is not None:
                    if f.is_response:
                        if t is None or t <= cutoff:
                            continue
                        # leading window: (cutoff, cutoff + window]
                        if window is not None and t > cutoff + window:
                            continue
                    else:
                        if t is not None and (t > cutoff
                                              or (strict_predictor
                                                  and t == cutoff)):
                            continue
                        # trailing window is half-open: (cutoff-window, cutoff]
                        if (window is not None and t is not None
                                and t <= cutoff - window):
                            continue
                elif f.is_response:
                    pass  # no cutoff: responses aggregate over everything too
                vals.append(gen.extract(rec))
            out_vals.append(agg.aggregate(vals))
        cols[f.name] = Column.of_values(f.feature_type, out_vals)
    return FeatureTable(cols, len(keys),
                        np.array(keys, dtype=object) if keys else None)


def _group_records(df, key_field: Optional[str],
                   key_fn: Optional[Callable[[Any], str]],
                   timestamp: Callable[[dict], Optional[int]],
                   ) -> "Dict[str, List[Tuple[Optional[int], dict]]]":
    records = df.to_dict("records")
    groups: Dict[str, List[Tuple[Optional[int], dict]]] = {}
    for r in records:
        if key_fn is not None:
            k = str(key_fn(r))
        elif key_field is not None:
            k = str(r.get(key_field))
        else:
            raise ValueError("aggregating readers need key_field or key_fn")
        groups.setdefault(k, []).append((timestamp(r), r))
    for k in groups:
        groups[k].sort(key=lambda tr: (tr[0] is not None, tr[0] or 0))
    return groups


class AggregateDataReader(Reader):
    """Event reader: one training row per key (reference
    AggregateDataReader, DataReader.scala:206-279)."""

    def __init__(self, inner: Reader, aggregate_params: AggregateParams,
                 key_field: Optional[str] = None,
                 key_fn: Optional[Callable[[Any], str]] = None):
        super().__init__(key_fn=key_fn, key_field=key_field or inner.key_field)
        self.inner = inner
        self.aggregate_params = aggregate_params

    def read(self, params: Optional[dict] = None):
        return self.inner.read(params)

    def generate_table(self, raw_features: Sequence[Feature],
                       params: Optional[dict] = None) -> FeatureTable:
        df = self.read(params)
        ap = self.aggregate_params
        groups = _group_records(df, self.key_field, self.key_fn, ap.timestamp)
        cutoff = ap.cutoff.cutoff_ms
        return _aggregate_groups(groups, raw_features, lambda k: cutoff)


class ConditionalParams:
    """(reference ConditionalParams: targetCondition, timeStampToKeep,
    dropIfTargetConditionNotMet, response/predictor windows)."""

    def __init__(self, target_condition: Callable[[dict], bool],
                 timestamp_field: Optional[str] = None,
                 timestamp_fn: Optional[Callable[[Any], Optional[int]]] = None,
                 timestamp_to_keep: str = "min",
                 drop_if_target_condition_not_met: bool = True,
                 response_window: Optional[int] = None,
                 seed: int = 42):
        if timestamp_to_keep not in ("min", "max", "random"):
            raise ValueError("timestamp_to_keep must be min|max|random")
        self.target_condition = target_condition
        self.timestamp = _timestamp_getter(timestamp_field, timestamp_fn)
        self.timestamp_to_keep = timestamp_to_keep
        self.drop_if_target_condition_not_met = drop_if_target_condition_not_met
        #: default leading window for response features that set none
        #: (reference ConditionalParams.responseWindow)
        self.response_window = response_window
        self.seed = seed


class ConditionalDataReader(Reader):
    """Conditional-probability reader: per key, the cutoff is a time where
    ``target_condition`` fired; predictors aggregate before it, responses
    after (reference ConditionalDataReader, DataReader.scala:288-368)."""

    def __init__(self, inner: Reader, conditional_params: ConditionalParams,
                 key_field: Optional[str] = None,
                 key_fn: Optional[Callable[[Any], str]] = None):
        super().__init__(key_fn=key_fn, key_field=key_field or inner.key_field)
        self.inner = inner
        self.conditional_params = conditional_params

    def read(self, params: Optional[dict] = None):
        return self.inner.read(params)

    def generate_table(self, raw_features: Sequence[Feature],
                       params: Optional[dict] = None) -> FeatureTable:
        df = self.read(params)
        cp = self.conditional_params
        groups = _group_records(df, self.key_field, self.key_fn, cp.timestamp)
        rng = _random.Random(cp.seed)
        cutoffs: Dict[str, Optional[int]] = {}
        for k, events in groups.items():
            fired = [t for t, r in events
                     if t is not None and cp.target_condition(r)]
            if not fired:
                cutoffs[k] = None
            elif cp.timestamp_to_keep == "min":
                cutoffs[k] = min(fired)
            elif cp.timestamp_to_keep == "max":
                cutoffs[k] = max(fired)
            else:
                cutoffs[k] = rng.choice(sorted(fired))
        if cp.drop_if_target_condition_not_met:
            groups = {k: v for k, v in groups.items() if cutoffs[k] is not None}
        return _aggregate_groups(
            groups, raw_features, lambda k: cutoffs[k],
            response_window_default=cp.response_window,
            strict_predictor=True)


class JoinedDataReader(Reader):
    """Typed join of two readers on their keys (reference
    JoinedDataReader.scala, JoinTypes.scala). Features are routed to the side
    whose frame carries their field (or via ``feature_sides``:
    {feature name: 'left'|'right'})."""

    def __init__(self, left: Reader, right: Reader, join_type: str = "inner",
                 feature_sides: Optional[Dict[str, str]] = None):
        super().__init__(key_field=left.key_field)
        if join_type not in ("inner", "left", "outer"):
            raise ValueError("join_type must be inner|left|outer")
        self.left = left
        self.right = right
        self.join_type = join_type
        self.feature_sides = dict(feature_sides or {})

    def read(self, params: Optional[dict] = None):
        return self.left.read(params)

    def _route(self, raw_features: Sequence[Feature], params
               ) -> Tuple[List[Feature], List[Feature]]:
        ldf = self.left.read(params)
        rdf = self.right.read(params)
        lcols, rcols = set(ldf.columns), set(rdf.columns)
        lefts: List[Feature] = []
        rights: List[Feature] = []
        from .readers import _field_name_of
        for f in raw_features:
            side = self.feature_sides.get(f.name)
            if side is None:
                field = _field_name_of(f.origin_stage.extract_fn)
                if field is not None and field in lcols:
                    side = "left"
                elif field is not None and field in rcols:
                    side = "right"
                else:
                    raise ValueError(
                        f"cannot route feature '{f.name}' to a join side; "
                        f"pass feature_sides")
            (lefts if side == "left" else rights).append(f)
        return lefts, rights

    def generate_table(self, raw_features: Sequence[Feature],
                       params: Optional[dict] = None) -> FeatureTable:
        lefts, rights = self._route(raw_features, params)
        lt = self.left.generate_table(lefts, params)
        rt = self.right.generate_table(rights, params)
        if lt.key is None or rt.key is None:
            raise ValueError("joined readers need keys on both sides")
        lk = [str(k) for k in lt.key]
        rk = [str(k) for k in rt.key]
        l_index: Dict[str, int] = {}
        for i, k in enumerate(lk):
            l_index.setdefault(k, i)
        r_index: Dict[str, int] = {}
        for i, k in enumerate(rk):
            r_index.setdefault(k, i)
        if self.join_type == "inner":
            keys = [k for k in dict.fromkeys(lk) if k in r_index]
        elif self.join_type == "left":
            keys = list(dict.fromkeys(lk))
        else:
            keys = list(dict.fromkeys(lk + rk))

        def side_cols(tbl: FeatureTable, feats: Sequence[Feature],
                      index: Dict[str, int]) -> Dict[str, Column]:
            out: Dict[str, Column] = {}
            rows = [index.get(k) for k in keys]
            for f in feats:
                col = tbl[f.name]
                vals = [None if i is None else _cell(col, i) for i in rows]
                out[f.name] = Column.of_values(f.feature_type, vals)
            return out

        cols = side_cols(lt, lefts, l_index)
        cols.update(side_cols(rt, rights, r_index))
        return FeatureTable(cols, len(keys), np.array(keys, dtype=object))


def _cell(col: Column, i: int) -> Any:
    valid = col.mask is None or bool(np.asarray(col.mask)[i])
    if not valid:
        return None
    v = np.asarray(col.values)[i]
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v.item() if isinstance(v, np.generic) else v
