"""Typed feature value hierarchy.

TPU-native re-design of the reference type system
(reference: features/src/main/scala/com/salesforce/op/features/types/FeatureType.scala:44-171,
Numerics.scala, Text.scala, Maps.scala, Lists.scala, Sets.scala, Geolocation.scala,
OPVector.scala).

Differences from the reference, by design:

* In the reference every cell of data is boxed into a ``FeatureType`` instance and
  rows flow through Spark. Here the *columnar* representation is primary: a whole
  column of a type lives as one (or a few) device arrays plus a validity mask
  (see ``transmogrifai_tpu.table``). The value classes below exist for
  row-level local scoring, the testkit, and user-facing APIs; they are
  intentionally tiny.
* Each class carries a class-level ``column_kind`` describing its columnar
  storage so readers/vectorizers can be generic over types.

The concrete type registry matches the reference registry 1:1
(FeatureType.scala:265-324): 52 concrete types.
"""
from __future__ import annotations

import math
import numbers
from typing import Any, ClassVar, Dict, Iterable, List, Mapping, Optional, Tuple, Type

__all__ = [
    # abstract
    "FeatureType", "OPNumeric", "OPCollection", "OPList", "OPSet", "OPMap", "Location",
    "NonNullable", "SingleResponse", "MultiResponse",
    # vector
    "OPVector",
    # lists
    "TextList", "DateList", "DateTimeList", "Geolocation",
    # numerics
    "Real", "RealNN", "Binary", "Integral", "Date", "DateTime", "Currency", "Percent",
    # sets
    "MultiPickList",
    # text
    "Text", "Email", "Base64", "Phone", "ID", "URL", "TextArea", "PickList", "ComboBox",
    "Country", "State", "City", "PostalCode", "Street",
    # maps
    "TextMap", "EmailMap", "Base64Map", "PhoneMap", "IDMap", "URLMap", "TextAreaMap",
    "PickListMap", "ComboBoxMap", "CountryMap", "StateMap", "CityMap", "PostalCodeMap",
    "StreetMap", "GeolocationMap", "BinaryMap", "IntegralMap", "RealMap", "CurrencyMap",
    "PercentMap", "DateMap", "DateTimeMap", "MultiPickListMap", "Prediction",
    # registry / factory
    "FEATURE_TYPES", "feature_type_by_name", "FeatureTypeFactory", "FeatureTypeDefaults",
]


def _hashable(v: Any) -> Any:
    if isinstance(v, dict):
        return frozenset((k, _hashable(x)) for k, x in v.items())
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, set):
        return frozenset(v)
    return v


class FeatureType:
    """Base value container: holds an optional value, knows emptiness & equality.

    Mirrors reference FeatureType.scala:44-171 (``type Value``, ``value``,
    ``isEmpty``, ``isNullable``, equality on value).
    """

    #: can this type hold an empty value? (reference ``NonNullable`` trait)
    is_nullable: ClassVar[bool] = True
    #: columnar storage kind — drives FeatureTable representation:
    #: one of 'real', 'integral', 'binary', 'date', 'text', 'vector',
    #: 'text_list', 'date_list', 'geolocation', 'multipicklist', 'map', 'prediction'
    column_kind: ClassVar[str] = "text"
    #: abstract classes are not registered
    is_abstract: ClassVar[bool] = True

    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        self.value = self._convert(value)
        if not self.is_nullable and self.is_empty:
            raise ValueError(f"{type(self).__name__} cannot be empty")

    @classmethod
    def _convert(cls, value: Any) -> Any:
        return value

    @property
    def is_empty(self) -> bool:
        return self.value is None

    @property
    def non_empty(self) -> bool:
        return not self.is_empty

    def exists(self, pred) -> bool:
        return self.non_empty and pred(self.value)

    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other) and self.value == other.value

    def __hash__(self) -> int:
        v = self.value
        if isinstance(v, dict):
            v = frozenset((k, _hashable(x)) for k, x in v.items())
        elif isinstance(v, list):
            v = tuple(_hashable(x) for x in v)
        elif isinstance(v, set):
            v = frozenset(v)
        return hash((type(self).__name__, v))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.value!r})"

    # -- class-level helpers -------------------------------------------------
    @classmethod
    def type_name(cls) -> str:
        return cls.__name__

    @classmethod
    def empty(cls) -> "FeatureType":
        """Per-type empty default (reference FeatureTypeDefaults.scala)."""
        return cls(None)


class NonNullable:
    """Marker mixin (reference FeatureType.scala NonNullable trait)."""
    is_nullable = False


class SingleResponse:
    """Marker: type usable as a single response label."""


class MultiResponse:
    """Marker: type usable as a multi response label."""


class Categorical:
    """Marker: categorical-valued type."""


class Location:
    """Marker: geographic location type (reference Location trait)."""


# ---------------------------------------------------------------------------
# Numerics (reference types/Numerics.scala, OPNumeric.scala)
# ---------------------------------------------------------------------------

class OPNumeric(FeatureType):
    """Abstract numeric feature (value is Optional[number])."""
    is_abstract = True

    def to_double(self) -> Optional[float]:
        return None if self.value is None else float(self.value)


class Real(OPNumeric):
    """Optional real number (reference Numerics.scala Real)."""
    is_abstract = False
    column_kind = "real"

    @classmethod
    def _convert(cls, value):
        if value is None:
            return None
        if isinstance(value, bool):
            return 1.0 if value else 0.0
        if isinstance(value, numbers.Number):
            v = float(value)
            return None if math.isnan(v) else v
        raise TypeError(f"cannot make {cls.__name__} from {type(value).__name__}")

    @property
    def v(self) -> Optional[float]:
        return self.value


class RealNN(NonNullable, Real, SingleResponse):
    """Non-nullable real — the label type for regression & the input to models
    (reference Numerics.scala RealNN)."""
    is_abstract = False


class Currency(Real):
    is_abstract = False


class Percent(Real):
    is_abstract = False


class Integral(OPNumeric):
    """Optional long (reference Numerics.scala Integral)."""
    is_abstract = False
    column_kind = "integral"

    @classmethod
    def _convert(cls, value):
        if value is None:
            return None
        if isinstance(value, bool):
            return int(value)
        if isinstance(value, numbers.Integral):
            return int(value)
        if isinstance(value, float):
            if math.isnan(value):
                return None
            if value.is_integer():
                return int(value)
        raise TypeError(f"cannot make {cls.__name__} from {value!r}")


class Date(Integral):
    """Epoch-millis date (reference Numerics.scala Date)."""
    is_abstract = False
    column_kind = "date"


class DateTime(Date):
    is_abstract = False


class Binary(OPNumeric, SingleResponse):
    """Optional boolean (reference Numerics.scala Binary)."""
    is_abstract = False
    column_kind = "binary"

    @classmethod
    def _convert(cls, value):
        if value is None:
            return None
        if isinstance(value, bool):
            return value
        if isinstance(value, numbers.Number):
            v = float(value)
            if math.isnan(v):
                return None
            return v != 0.0
        raise TypeError(f"cannot make {cls.__name__} from {value!r}")

    def to_double(self) -> Optional[float]:
        return None if self.value is None else (1.0 if self.value else 0.0)


# ---------------------------------------------------------------------------
# Text (reference types/Text.scala)
# ---------------------------------------------------------------------------

class Text(FeatureType):
    """Optional string (reference Text.scala)."""
    is_abstract = False
    column_kind = "text"

    @classmethod
    def _convert(cls, value):
        if value is None:
            return None
        if isinstance(value, str):
            return value
        raise TypeError(f"cannot make {cls.__name__} from {type(value).__name__}")


class Email(Text):
    is_abstract = False

    def prefix(self) -> Optional[str]:
        parts = self._split()
        return parts[0] if parts else None

    def domain(self) -> Optional[str]:
        parts = self._split()
        return parts[1] if parts else None

    def _split(self):
        if self.value is None:
            return None
        at = self.value.count("@")
        if at != 1:
            return None
        p, d = self.value.split("@")
        return (p, d) if p and d else None


class Base64(Text):
    is_abstract = False


class Phone(Text):
    is_abstract = False


class ID(Text):
    is_abstract = False


class URL(Text):
    is_abstract = False

    def domain(self) -> Optional[str]:
        if self.value is None:
            return None
        from urllib.parse import urlparse
        try:
            return urlparse(self.value).hostname
        except ValueError:
            return None

    def protocol(self) -> Optional[str]:
        if self.value is None:
            return None
        from urllib.parse import urlparse
        try:
            return urlparse(self.value).scheme or None
        except ValueError:
            return None

    def is_valid(self) -> bool:
        """Valid http/https/ftp URL with a host (reference Text.scala URL.isValid)."""
        if self.value is None:
            return False
        from urllib.parse import urlparse
        try:
            p = urlparse(self.value)
        except ValueError:
            return False
        return p.scheme in ("http", "https", "ftp") and bool(p.hostname) and "." in (p.hostname or "")


class TextArea(Text):
    is_abstract = False


class PickList(Text, Categorical, SingleResponse):
    is_abstract = False


class ComboBox(Text):
    is_abstract = False


class Country(Text, Location):
    is_abstract = False


class State(Text, Location):
    is_abstract = False


class City(Text, Location):
    is_abstract = False


class PostalCode(Text, Location):
    is_abstract = False


class Street(Text, Location):
    is_abstract = False


# ---------------------------------------------------------------------------
# Collections (reference types/OPVector.scala, Lists.scala, Sets.scala,
# Geolocation.scala)
# ---------------------------------------------------------------------------

class OPCollection(FeatureType):
    """Abstract collection: empty collection == empty value."""
    is_abstract = True

    @property
    def is_empty(self) -> bool:
        return self.value is None or len(self.value) == 0


class OPList(OPCollection):
    is_abstract = True
    @classmethod
    def _convert(cls, value):
        if value is None:
            return []
        return list(value)


class OPVector(OPCollection, NonNullable):
    """Dense numeric vector (reference OPVector.scala). Value is a list/array of
    floats; columnar storage is a single (n, d) device array."""
    is_abstract = False
    column_kind = "vector"

    @classmethod
    def _convert(cls, value):
        import numpy as np
        if value is None:
            return np.zeros((0,), dtype=np.float32)
        return np.asarray(value, dtype=np.float32)

    @property
    def is_empty(self) -> bool:
        return False  # vectors are non-nullable; zero-length is still a value

    def __eq__(self, other):
        import numpy as np
        return type(self) is type(other) and np.array_equal(self.value, other.value)

    def __hash__(self):
        return hash((type(self).__name__, self.value.tobytes()))


class TextList(OPList):
    is_abstract = False
    column_kind = "text_list"


class DateList(OPList):
    is_abstract = False
    column_kind = "date_list"

    @classmethod
    def _convert(cls, value):
        if value is None:
            return []
        return [int(v) for v in value]


class DateTimeList(DateList):
    is_abstract = False


class Geolocation(OPList, Location):
    """(lat, lon, accuracy) triple (reference Geolocation.scala)."""
    is_abstract = False
    column_kind = "geolocation"

    @classmethod
    def _convert(cls, value):
        if value is None:
            return []
        vals = [float(v) for v in value]
        if vals and len(vals) != 3:
            raise ValueError("Geolocation must have lat, lon, accuracy")
        if vals:
            lat, lon, _ = vals
            if not (-90.0 <= lat <= 90.0) or not (-180.0 <= lon <= 180.0):
                raise ValueError(f"invalid geolocation {vals}")
        return vals

    @property
    def lat(self) -> Optional[float]:
        return self.value[0] if self.value else None

    @property
    def lon(self) -> Optional[float]:
        return self.value[1] if self.value else None

    @property
    def accuracy(self) -> Optional[float]:
        return self.value[2] if self.value else None

    def to_unit_sphere(self) -> Optional[Tuple[float, float, float]]:
        """3D unit-sphere embedding used by the geolocation vectorizer;
        None for an empty geolocation."""
        if self.is_empty:
            return None
        lat, lon = math.radians(self.lat), math.radians(self.lon)
        return (math.cos(lat) * math.cos(lon), math.cos(lat) * math.sin(lon), math.sin(lat))


class OPSet(OPCollection, MultiResponse):
    is_abstract = True
    @classmethod
    def _convert(cls, value):
        if value is None:
            return set()
        return set(value)


class MultiPickList(OPSet, Categorical):
    is_abstract = False
    column_kind = "multipicklist"


# ---------------------------------------------------------------------------
# Maps (reference types/Maps.scala) — string-keyed maps mirroring scalar types
# ---------------------------------------------------------------------------

class OPMap(OPCollection):
    """Abstract string-keyed map. ``element_type`` is the scalar type mirrored."""
    is_abstract = True
    element_type: ClassVar[Optional[Type[FeatureType]]] = None
    column_kind = "map"

    @classmethod
    def _convert(cls, value):
        if value is None:
            return {}
        return dict(value)


def _mk_map(name: str, element: Type[FeatureType], extra_bases: Tuple[type, ...] = ()) -> type:
    return type(name, (OPMap,) + extra_bases, {
        "is_abstract": False,
        "element_type": element,
        "__doc__": f"Map[str, {element.__name__}] (reference Maps.scala {name}).",
    })


TextMap = _mk_map("TextMap", Text)
EmailMap = _mk_map("EmailMap", Email)
Base64Map = _mk_map("Base64Map", Base64)
PhoneMap = _mk_map("PhoneMap", Phone)
IDMap = _mk_map("IDMap", ID)
URLMap = _mk_map("URLMap", URL)
TextAreaMap = _mk_map("TextAreaMap", TextArea)
PickListMap = _mk_map("PickListMap", PickList)
ComboBoxMap = _mk_map("ComboBoxMap", ComboBox)
CountryMap = _mk_map("CountryMap", Country, (Location,))
StateMap = _mk_map("StateMap", State, (Location,))
CityMap = _mk_map("CityMap", City, (Location,))
PostalCodeMap = _mk_map("PostalCodeMap", PostalCode, (Location,))
StreetMap = _mk_map("StreetMap", Street, (Location,))
GeolocationMap = _mk_map("GeolocationMap", Geolocation, (Location,))
BinaryMap = _mk_map("BinaryMap", Binary)
IntegralMap = _mk_map("IntegralMap", Integral)
RealMap = _mk_map("RealMap", Real)
CurrencyMap = _mk_map("CurrencyMap", Currency)
PercentMap = _mk_map("PercentMap", Percent)
DateMap = _mk_map("DateMap", Date)
DateTimeMap = _mk_map("DateTimeMap", DateTime)
MultiPickListMap = _mk_map("MultiPickListMap", MultiPickList)


class Prediction(OPMap, NonNullable):
    """Model output map with reserved keys (reference Maps.scala Prediction:
    prediction / probability_* / rawPrediction_*)."""
    is_abstract = False
    element_type = Real
    column_kind = "prediction"

    PredictionName = "prediction"
    RawPredictionName = "rawPrediction"
    ProbabilityName = "probability"

    @classmethod
    def _convert(cls, value):
        if value is None:
            raise ValueError("Prediction cannot be empty")
        d = dict(value)
        if cls.PredictionName not in d:
            raise ValueError(f"Prediction must contain '{cls.PredictionName}' key")
        for k in d:
            if k != cls.PredictionName and not (
                    (k.startswith(cls.RawPredictionName + "_")
                     or k.startswith(cls.ProbabilityName + "_"))
                    and k.rsplit("_", 1)[1].isdigit()):
                raise ValueError(
                    f"Prediction key '{k}' is not one of the reserved keys "
                    f"(prediction, rawPrediction_i, probability_i)")
        return d

    @property
    def is_empty(self) -> bool:
        return False

    @property
    def prediction(self) -> float:
        return float(self.value[self.PredictionName])

    @property
    def raw_prediction(self) -> List[float]:
        return self._keyed(self.RawPredictionName)

    @property
    def probability(self) -> List[float]:
        return self._keyed(self.ProbabilityName)

    def _keyed(self, prefix: str) -> List[float]:
        ks = sorted(
            (k for k in self.value
             if k.startswith(prefix + "_") and k.rsplit("_", 1)[1].isdigit()),
            key=lambda k: int(k.rsplit("_", 1)[1]),
        )
        return [float(self.value[k]) for k in ks]

    @staticmethod
    def build(prediction: float, raw_prediction: Iterable[float] = (),
              probability: Iterable[float] = ()) -> "Prediction":
        d: Dict[str, float] = {Prediction.PredictionName: float(prediction)}
        for i, v in enumerate(raw_prediction):
            d[f"{Prediction.RawPredictionName}_{i}"] = float(v)
        for i, v in enumerate(probability):
            d[f"{Prediction.ProbabilityName}_{i}"] = float(v)
        return Prediction(d)


# ---------------------------------------------------------------------------
# Registry & factory (reference FeatureType.scala:265-324, FeatureTypeFactory)
# ---------------------------------------------------------------------------

def _collect_concrete(root: Type[FeatureType]) -> Dict[str, Type[FeatureType]]:
    out: Dict[str, Type[FeatureType]] = {}
    stack = [root]
    seen = set()
    while stack:
        c = stack.pop()
        if c in seen:
            continue
        seen.add(c)
        if not c.__dict__.get("is_abstract", False) and c is not root:
            out[c.__name__] = c
        stack.extend(c.__subclasses__())
    return out


#: name → concrete feature type class (52 types, matching the reference registry)
FEATURE_TYPES: Dict[str, Type[FeatureType]] = _collect_concrete(FeatureType)


def feature_type_by_name(name: str) -> Type[FeatureType]:
    try:
        return FEATURE_TYPES[name]
    except KeyError:
        raise ValueError(f"Unknown feature type '{name}'") from None


class FeatureTypeFactory:
    """Runtime construction from raw value (reference FeatureTypeFactory.scala)."""

    def __init__(self, feature_type: Type[FeatureType]):
        self.feature_type = feature_type

    def new_instance(self, value: Any) -> FeatureType:
        if isinstance(value, self.feature_type):
            return value
        return self.feature_type(value)

    @staticmethod
    def of(feature_type: Type[FeatureType]) -> "FeatureTypeFactory":
        return FeatureTypeFactory(feature_type)


class FeatureTypeDefaults:
    """Per-type empty defaults (reference FeatureTypeDefaults.scala)."""

    @staticmethod
    def default(feature_type: Type[FeatureType]) -> FeatureType:
        if feature_type is Prediction:
            return Prediction({Prediction.PredictionName: 0.0})
        if not feature_type.is_nullable and issubclass(feature_type, RealNN):
            return feature_type(0.0)
        return feature_type(None)
