"""Chaos campaign engine: randomized multi-fault schedules, invariant
oracles, and automatic schedule minimization (docs/robustness.md "Chaos
campaigns").

Five robustness layers built two dozen named chaos sites — retry and
quarantine, preemption-safe resume, serving breakers, drift self-healing,
OOM downshifts — but each site was only ever tested one-at-a-time. The
emergent interactions a production fleet actually produces (a drift refit
racing an OOM downshift racing a preemption) were unverified. This engine
closes that gap *compositionally*:

* **schedules** — a seeded RNG draws randomized fault schedules from the
  machine-readable site registry (``faults.ALL_SITES``): which sites,
  which modes (``raise``/``nan``/``preempt``/``oom``), which Nth-call
  triggers. Determinism is end to end: same seed → same schedules → same
  fault sequence (sites fire on call counters, never clocks).
* **scenarios** — each schedule runs against a real workload harness:
  ``train`` (checkpointed train + resume-on-preemption), ``sweep`` (the
  CV validator), ``serve`` (a staged serving flush, deterministic),
  ``serve_heal`` (registry + drift monitor + background refit under
  shifted traffic), ``stream`` (out-of-core train + resume), ``fleet``
  (a two-replica front door with routing/failover/probe faults — the
  zero-lost-futures accounting identity under replica kills),
  ``density`` (three models packed onto two one-warm-slot replicas:
  LRU eviction + demand paging + warm-copy failover under the
  ``place.*`` and ``fleet.*`` sites — the same accounting identity
  through model mobility), and ``transfer`` (the guarded host<->device
  helpers).
* **oracles** — after every run a library of invariants is checked:
  bit-equality of recovered results against the fault-free baseline
  wherever the site table promises it; full request accounting
  (submitted = completed + shed, zero lost futures); no leaked threads /
  runtimes / feeds / hearts / plan-cache overflow (the conftest no-leak
  fixtures as callable oracles — robustness/oracles.py);
  manifest/checkpoint integrity; typed-error discipline (nothing but the
  documented typed errors may escape a fenced region); and
  no-silent-divergence (a result may differ from baseline only when a
  fired site legitimately alters results AND fault accounting shows the
  recovery).
* **minimization** — a violating schedule is delta-debugged down to a
  minimal failing fault set and emitted as a reproducer: a ``TG_FAULTS``
  JSON + seed whose one-command re-run (``python -m transmogrifai_tpu.cli
  campaign --scenario <s>`` under ``TG_CHAOS=1 TG_FAULTS=...``)
  re-triggers the violation. A campaign failure is a repro, not a flaky
  soak.

Entry points: ``python -m transmogrifai_tpu.cli campaign`` and
``BENCH_MODE=campaign python bench.py`` (seeded fixed-budget soak
asserting 100% site coverage, zero violations, full accounting).

Env knobs (docs/robustness.md "Chaos campaigns"): ``TG_CAMPAIGN_SCHEDULES``
(default budget, 40), ``TG_CAMPAIGN_SEED`` (0),
``TG_CAMPAIGN_COLLECT_TIMEOUT_S`` (serve future-collection budget, 15),
``TG_CAMPAIGN_WORKDIR`` (scratch root; a temp dir otherwise).
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..observability import postmortem as _postmortem
from . import faults, oracles
from .faults import ALL_SITES, SimulatedPreemption, sites_for_scenario
from .policy import FaultLog, RetryPolicy

#: one schedule: {"scenario": <name>, "faults": {site: FaultSpec kwargs}}
Schedule = Dict[str, Any]

#: fired site -> the FaultLog kind its recovery must record (the
#: accounting half of the no-silent-recovery oracle; checked only where
#: the record reliably lands on the log the scenario observes)
ACCOUNT_KINDS = {
    "serve.flush": "breaker_degraded",
    "serve.dispatch": "breaker_degraded",
    "serve.complete": "breaker_degraded",
    "oom.serve": "oom_downshift",
    "drift.fold": "drift_fold_failed",
    "drift.verdict": "drift_verdict_failed",
    "drift.refit": "drift_refit_failed",
    "fleet.route": "fleet_failover",
    "fleet.replica_kill": "replica_lost",
    "fleet.probe": "fleet_probe_failed",
    "aot.load": "aot_fallback",
    "net.accept": "net_accept_refused",
    "net.read": "net_read_shed",
    "net.write": "net_write_shed",
    "place.assign": "place_assign_failed",
    "place.evict": "place_evict_failed",
    "place.pagein": "place_pagein_failed",
}


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _divergence_violations(name: str, equal: bool, fired: Set[str],
                           records: int) -> List[str]:
    """The no-silent-divergence oracle: a completed run's result may
    differ from the fault-free baseline only when (a) some fired site
    legitimately alters results (``bit_equal=False`` in the registry —
    e.g. a quarantine changes selection) and (b) the recovery left fault
    accounting behind. Divergence with only bit-equal-promising sites
    fired — or with empty accounting — is a broken recovery path."""
    if equal:
        return []
    if not fired:
        return [f"{name}: result diverged from the fault-free baseline "
                f"with no fault fired (scenario nondeterminism)"]
    altering = [s for s in fired
                if s in ALL_SITES and not ALL_SITES[s].bit_equal]
    if not altering:
        return [f"{name}: result diverged though every fired site "
                f"({sorted(fired)}) promises bit-equal recovery"]
    if not records:
        return [f"{name}: result diverged with empty fault accounting "
                f"(silent divergence)"]
    return []


class _Scenario:
    """Base scenario: lazy setup (fixtures + fault-free baseline), one
    ``run`` per schedule, and post-run invariant checks."""

    name = "?"

    def __init__(self, engine: "ChaosCampaign"):
        self.engine = engine
        self._ready = False
        self.baseline: Any = None

    def ensure_setup(self) -> None:
        if not self._ready:
            self.setup()
            self._ready = True

    def sites(self) -> List[str]:
        return sites_for_scenario(self.name)

    def setup(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def run(self, log: FaultLog) -> Dict[str, Any]:  # pragma: no cover
        raise NotImplementedError

    def violations(self, result: Dict[str, Any],
                   fired: Dict[str, Dict[str, int]],
                   log: FaultLog) -> List[str]:  # pragma: no cover
        """``fired`` is faults.fired_counts() for the run: {site: {mode:
        n}} of faults actually applied — oracles condition on it (a site
        armed past its call window never fired and promises nothing)."""
        raise NotImplementedError


class _TrainScenario(_Scenario):
    """Checkpointed in-core train (2-family selector sweep + refit) with
    resume-on-preemption; result = the fitted model's scored probe
    records + checkpoint-manifest integrity."""

    name = "train"

    def setup(self) -> None:
        import pandas as pd
        rng = np.random.RandomState(100)
        n = 240
        x1, x2, x3 = rng.randn(n), rng.randn(n), rng.randn(n)
        y = ((x1 + 0.5 * x2 - 0.25 * x3) > 0).astype(float)
        self.df = pd.DataFrame({"x1": x1, "x2": x2, "x3": x3, "y": y})
        self.probe = [{"x1": float(a), "x2": float(b), "x3": float(c)}
                      for a, b, c in zip(x1[:16], x2[:16], x3[:16])]
        self.baseline = self.run(FaultLog())

    def _build(self):
        from ..features import FeatureBuilder
        from ..impl.feature.transmogrifier import transmogrify
        from ..impl.selector.factories import (
            BinaryClassificationModelSelector)
        from ..workflow import OpWorkflow
        label = FeatureBuilder.RealNN("y").extract_field().as_response()
        feats = [FeatureBuilder.Real(c).extract_field().as_predictor()
                 for c in ("x1", "x2", "x3")]
        checked = transmogrify(feats).sanity_check(label)
        pred = (BinaryClassificationModelSelector.with_cross_validation(
            seed=11,
            models=[("OpLogisticRegression",
                     [{"regParam": 0.01, "elasticNetParam": 0.0},
                      {"regParam": 0.3, "elasticNetParam": 0.5}]),
                    ("OpLinearSVC", [{"regParam": 0.01}])])
            .set_input(label, checked).get_output())
        return (OpWorkflow().set_input_dataset(self.df)
                .set_result_features(pred))

    def run(self, log: FaultLog) -> Dict[str, Any]:
        from ..local import micro_batch_score_function
        ckpt = tempfile.mkdtemp(dir=self.engine.workdir, prefix="train_")
        try:
            model = None
            # ONE workflow across kill + resume: a real resume re-runs
            # the same script (same stage uids regenerate in the fresh
            # process); in-process that means reusing the wf object, so
            # checkpoint restores actually engage
            wf = (self._build().with_checkpoint_dir(ckpt)
                  .with_fault_policy(self.engine.retry_policy()))
            for attempt in range(4):
                try:
                    model = wf.train(resume=attempt > 0)
                    break
                except SimulatedPreemption:
                    continue  # the kill; "fresh process" resumes
            if model is None:
                raise SimulatedPreemption(
                    "train still preempted after 3 resumes")
            # compare prediction PAYLOADS: stage uids (hence result
            # feature names) regenerate per workflow build, but the
            # fitted numbers must not
            pred = model.result_features[0].name
            records = [rec[pred]
                       for rec in micro_batch_score_function(model)(
                           self.probe)]
            model_log = getattr(model, "_fault_log", None)
            return {"records": records,
                    "faultReports": len(model_log.reports)
                    if model_log else 0,
                    "manifest": self.engine.manifest_problems(ckpt)}
        finally:
            shutil.rmtree(ckpt, ignore_errors=True)

    def violations(self, result, fired, log) -> List[str]:
        out = [f"train: checkpoint manifest: {p}"
               for p in result["manifest"]]
        equal = result["records"] == self.baseline["records"]
        # train() activates the model's own FaultLog, so recovery
        # accounting lands there, not on the engine's ambient log
        out += _divergence_violations("train", equal, set(fired),
                                      result["faultReports"]
                                      + len(log.reports))
        return out


class _SweepScenario(_Scenario):
    """The CV validator alone (3 families — two linear plus a small RF so
    the histogram-engine ``hist.build`` gate is exercised): winner +
    per-family fold metrics compared bit-exactly; quarantines must be
    accounted."""

    name = "sweep"

    def setup(self) -> None:
        import jax.numpy as jnp

        from ..models.api import MODEL_REGISTRY
        import transmogrifai_tpu.models.linear  # noqa: F401 - registry
        import transmogrifai_tpu.models.trees   # noqa: F401 - registry
        rng = np.random.RandomState(101)
        X = rng.randn(512, 6).astype(np.float32)
        y = (X @ rng.randn(6).astype(np.float32) > 0).astype(np.float32)
        self.Xd, self.yd = jnp.asarray(X), jnp.asarray(y)
        lr = [{"regParam": r, "elasticNetParam": e}
              for r in (0.01, 0.1) for e in (0.0, 0.5)]
        svc = [{"regParam": 0.01}, {"regParam": 0.1}]
        rf = [{"maxDepth": 2, "minInstancesPerNode": 5,
               "minInfoGain": 0.001, "numTrees": 3,
               "subsamplingRate": 1.0}]
        self.models = [(MODEL_REGISTRY["OpLogisticRegression"], lr),
                       (MODEL_REGISTRY["OpLinearSVC"], svc),
                       (MODEL_REGISTRY["OpRandomForestClassifier"], rf)]
        self.baseline = self.run(FaultLog())

    def run(self, log: FaultLog) -> Dict[str, Any]:
        from ..impl.tuning.validators import OpCrossValidation
        cv = OpCrossValidation(num_folds=2, seed=0)
        best = cv.validate(self.models, self.Xd, self.yd, "binary",
                           "AuROC", True, 2)
        return {
            "winner": (best.family_name,
                       repr(sorted(best.hyper.items())),
                       float(best.metric_value)),
            "folds": [(r.family, np.asarray(r.fold_metrics).tobytes())
                      for r in best.results],
            "quarantined": len(best.quarantined),
        }

    def violations(self, result, fired, log) -> List[str]:
        equal = (result["winner"] == self.baseline["winner"]
                 and result["folds"] == self.baseline["folds"])
        return _divergence_violations("sweep", equal, set(fired),
                                      len(log.reports))


class _ServeScenario(_Scenario):
    """Deterministic staged serving flush: all requests queued before the
    batcher starts, so one flush carries them and every armed serve-side
    fault fires at a reproducible point. Oracles: zero lost futures, full
    accounting, per-row bit-equality for every completed request (every
    serve-pool site promises it), recovery kinds on the serve log."""

    name = "serve"

    def setup(self) -> None:
        from ..local import micro_batch_score_function
        from ..serving.loadgen import synthetic_rows
        self.model = self.engine.small_model()
        self.rows = synthetic_rows(self.model, 12, seed=55)
        self.baseline = micro_batch_score_function(self.model)(
            list(self.rows))

    def run(self, log: FaultLog) -> Dict[str, Any]:
        from ..serving.drift import (
            DriftBaseline, DriftConfig, DriftMonitor)
        from ..serving.runtime import ServeConfig, ServingRuntime
        monitor = DriftMonitor(DriftBaseline.from_model(self.model),
                               DriftConfig(min_rows=4, every_rows=4))
        # default pipeline_depth (2) so the staged gather/dispatch/
        # complete path is what the campaign hammers (and serve.complete
        # is coverable); depth 1 re-runs are one env knob away
        cfg = ServeConfig(max_batch=16, max_queue=16, max_wait_ms=10.0)
        rt = ServingRuntime(self.model, "campaign", cfg, fault_log=log,
                            drift_monitor=monitor, auto_start=False)
        completed: Dict[int, Dict[str, Any]] = {}
        shed: Dict[int, str] = {}
        failed: Dict[int, str] = {}
        lost: List[int] = []
        cancelled: List[int] = []
        shed_counters: Dict[str, float] = {}
        try:
            pending = []
            for i, row in enumerate(self.rows):
                try:
                    pending.append((i, rt.submit(row)))
                except Exception as e:
                    if isinstance(e, self.engine.typed_escapes()):
                        shed[i] = type(e).__name__
                    else:
                        raise  # untyped submit failure = discipline breach
            if pending:
                # one caller walks away before the batcher starts: the
                # runtime must shed the cancelled future TYPED
                # (reason="cancelled"), never silently vanish it
                ci, cfut = pending[-1]
                if cfut.cancel():
                    cancelled.append(ci)
            rt.start()
            deadline = time.monotonic() + self.engine.collect_timeout
            for i, fut in pending:
                if fut.cancelled():
                    continue  # accounted in the cancelled bucket
                try:
                    completed[i] = fut.result(
                        timeout=max(0.05, deadline - time.monotonic()))
                except _FutureTimeout:
                    lost.append(i)
                except Exception as e:
                    failed[i] = f"{type(e).__name__}: {e}"
            if cancelled:
                # the cancelled request is counted when its flush runs
                # (_shed_expired), which can trail the other futures'
                # resolution by one batcher iteration
                until = time.monotonic() + 2.0
                while (rt.summary()["shed"].get("cancelled", 0.0)
                       < len(cancelled) and time.monotonic() < until):
                    time.sleep(0.01)
            shed_counters = rt.summary()["shed"]
        finally:
            rt.close(drain=False)
        return {"completed": completed, "shed": shed, "failed": failed,
                "lost": lost, "cancelled": cancelled,
                "shedCounters": shed_counters,
                "accounting": {"submitted": len(self.rows),
                               "completed": len(completed),
                               "shed": len(shed), "failed": len(failed),
                               "lost": len(lost),
                               "cancelled": len(cancelled)}}

    def violations(self, result, fired, log) -> List[str]:
        out: List[str] = []
        n = len(self.rows)
        if result["lost"]:
            out.append(f"serve: {len(result['lost'])} request future(s) "
                       f"never resolved (lost): {result['lost']}")
        if result["failed"]:
            out.append(f"serve: request future(s) failed (requests must "
                       f"degrade, never fail): {result['failed']}")
        total = (len(result["completed"]) + len(result["shed"])
                 + len(result["failed"]) + len(result["lost"])
                 + len(result["cancelled"]))
        if total != n:
            out.append(f"serve: request accounting broken: "
                       f"{total} accounted of {n} submitted")
        if result["cancelled"]:
            got = result["shedCounters"].get("cancelled", 0.0)
            if got < len(result["cancelled"]):
                out.append(
                    f"serve: {len(result['cancelled'])} caller-cancelled "
                    f"request(s) but the runtime shed counter saw only "
                    f"{got} (silent cancelled-future drop)")
        mismatched = [i for i, rec in result["completed"].items()
                      if rec != self.baseline[i]]
        if mismatched:
            out.append(f"serve: completed record(s) not bit-equal to the "
                       f"fault-free run: rows {sorted(mismatched)}")
        kinds = {r.kind for r in log.reports}
        for site in fired:
            want = ACCOUNT_KINDS.get(site)
            if want and want not in kinds:
                out.append(f"serve: site {site} fired but recovery kind "
                           f"'{want}' was never recorded")
        if "serve.enqueue" in fired and not result["shed"]:
            out.append("serve: serve.enqueue fired but no submit was "
                       "shed with a typed error")
        return out


class _ServeHealScenario(_Scenario):
    """Registry + drift monitor + background refit under shifted traffic:
    the self-healing loop. With ``drift.refit`` armed the refit must fail
    typed, the OLD model must keep serving, and the breaker must stay
    untouched — even while ``oom.serve`` splits flushes underneath.

    Also the AOT program store's scenario: ``setup`` saves the model
    (populating ``<dir>/programs/`` + the manifest ``programs`` section),
    so every ``registry.load`` here warm-starts through deserialized
    programs. With ``aot.load`` armed, the injected bad artifact must
    degrade to a bit-equal re-traced result with a typed ``aot_fallback``
    on the runtime's fault log (ACCOUNT_KINDS) — never a crash or a
    silently divergent record (the per-row bit-equality oracle)."""

    name = "serve_heal"

    def setup(self) -> None:
        from ..local import micro_batch_score_function
        model = self.engine.small_model()
        # always save fresh: these dirs must be THIS engine's models,
        # even when two engines share a workdir
        self.saved = tempfile.mkdtemp(
            dir=self.engine.workdir, prefix="heal_") + "/model"
        self.refit_path = self.saved + "_refit"
        model.save(self.saved)
        self.engine.small_model(seed=8).save(self.refit_path)
        rng = np.random.RandomState(56)
        names = [f.name for f in model.raw_features]
        self.shifted = [{nm: float(rng.randn() + 6.0) for nm in names}
                        for _ in range(128)]
        self.baseline = micro_batch_score_function(model)(self.shifted)

    def run(self, log: FaultLog) -> Dict[str, Any]:
        from ..serving import ModelRegistry, ServeConfig
        from ..serving.drift import DriftConfig, live_refits
        cfg = ServeConfig(max_batch=32, max_queue=512, max_wait_ms=1.0)
        hook = lambda name, rt, report: self.refit_path  # noqa: E731
        completed: Dict[int, Dict[str, Any]] = {}
        failed: Dict[int, str] = {}
        lost: List[int] = []
        with ModelRegistry(cfg, refit_hook=hook) as reg:
            rt = reg.load("m", self.saved)
            if rt.drift_monitor is not None:
                # tighten the verdict cadence so 128 shifted rows are
                # enough to cross degraded and fire the refit hook
                rt.drift_monitor.config = DriftConfig(min_rows=32,
                                                      every_rows=32)
            pending = [(i, rt.submit(r))
                       for i, r in enumerate(self.shifted)]
            deadline = time.monotonic() + self.engine.collect_timeout
            for i, fut in pending:
                try:
                    completed[i] = fut.result(
                        timeout=max(0.05, deadline - time.monotonic()))
                except _FutureTimeout:
                    lost.append(i)
                except Exception as e:
                    failed[i] = f"{type(e).__name__}: {e}"
            t0 = time.monotonic()
            while live_refits() and time.monotonic() - t0 < 60:
                time.sleep(0.05)
            health = reg.health()
            swapped = reg.runtime("m") is not rt
            kinds = {r.kind for r in rt.fault_log.reports}
            breaker_opens = rt.breaker.snapshot()["opens"]
        return {"completed": completed, "failed": failed, "lost": lost,
                "swapped": swapped, "refits": health["refits"],
                "kinds": kinds, "breakerOpens": breaker_opens,
                "accounting": {"submitted": len(self.shifted),
                               "completed": len(completed), "shed": 0,
                               "failed": len(failed),
                               "lost": len(lost)}}

    def violations(self, result, fired, log) -> List[str]:
        out: List[str] = []
        if result["lost"]:
            out.append(f"serve_heal: {len(result['lost'])} lost "
                       f"request(s)")
        if result["failed"]:
            out.append(f"serve_heal: failed request(s): "
                       f"{result['failed']}")
        mismatched = [i for i, rec in result["completed"].items()
                      if rec != self.baseline[i]]
        if mismatched:
            out.append(f"serve_heal: record(s) not bit-equal to the "
                       f"fault-free run: rows {sorted(mismatched)[:8]}")
        for site in fired:
            want = ACCOUNT_KINDS.get(site)
            if want and want not in result["kinds"]:
                out.append(f"serve_heal: site {site} fired but recovery "
                           f"kind '{want}' was never recorded")
        if "drift.refit" in fired:
            if result["swapped"]:
                out.append("serve_heal: a failed refit must not swap the "
                           "serving model")
            if not any(not r.get("ok") for r in result["refits"]):
                out.append("serve_heal: failed refit missing from "
                           "registry refit history")
            if result["breakerOpens"]:
                out.append("serve_heal: a drift failure must leave the "
                           "breaker untouched")
        elif not result["swapped"]:
            out.append("serve_heal: degraded verdict did not refit + "
                       "hot-swap (self-healing loop broken)")
        return out


class _StreamScenario(_Scenario):
    """Out-of-core train (vectorize → sanity-check → StreamingGBT) with
    per-chunk checkpoints and resume-on-preemption. Prep-fold stats must
    be bit-equal on ANY schedule (monoid invariance); predictions are
    bit-equal except across an ``oom.stream`` downshift (tree quantile
    edges may shift within the documented tolerance)."""

    name = "stream"

    def setup(self) -> None:
        from ..table import Column, FeatureTable
        from ..types import Real, RealNN
        rng = np.random.RandomState(200)
        n, d = 1024, 4
        X = rng.randn(n, d).astype(np.float32)
        mask = rng.rand(n, d) >= 0.05
        y = (np.where(mask, X, 0.0)[:, 0] > 0.3).astype(np.float32)
        cols = {f"x{i}": Column(Real, X[:, i], mask[:, i])
                for i in range(d)}
        cols["y"] = Column(RealNN, y, None)
        self.table = FeatureTable(cols, n)
        self.probe_table = self.table.take(np.arange(64)).drop(["y"])
        self.d = d
        self.baseline = self.run(FaultLog())

    def _pipeline(self):
        from ..features import FeatureBuilder
        from ..impl.feature.transmogrifier import transmogrify
        from ..impl.preparators.sanity_checker import SanityChecker
        from ..streaming import StreamingGBT
        label = FeatureBuilder.RealNN("y").extract_field().as_response()
        feats = [FeatureBuilder.Real(f"x{i}").extract_field()
                 .as_predictor() for i in range(self.d)]
        checked = label.transform_with(SanityChecker(seed=1),
                                       transmogrify(feats))
        return (StreamingGBT(problem="binary", num_trees=1, max_depth=2,
                             n_bins=8, learning_rate=1.0)
                .set_input(label, checked).get_output())

    def run(self, log: FaultLog) -> Dict[str, Any]:
        from ..streaming import TableChunkSource
        from ..workflow import OpWorkflow
        ckpt = tempfile.mkdtemp(dir=self.engine.workdir, prefix="stream_")
        try:
            model = None
            # one wf across kill + resume (see _TrainScenario.run)
            wf = (OpWorkflow()
                  .set_result_features(self._pipeline())
                  .with_checkpoint_dir(ckpt)
                  .with_fault_policy(self.engine.retry_policy()))
            for attempt in range(4):
                try:
                    model = wf.train(
                        stream=TableChunkSource(self.table,
                                                chunk_rows=256),
                        resume=attempt > 0)
                    break
                except SimulatedPreemption:
                    continue
            if model is None:
                raise SimulatedPreemption(
                    "stream train still preempted after 3 resumes")
            rv = [s for s in model.stages
                  if type(s).__name__ == "RealVectorizerModel"][0]
            scored = model.score(table=self.probe_table)
            pred = model.result_features[0].name
            model_log = getattr(model, "_fault_log", None)
            kinds = ({r.kind for r in model_log.reports}
                     if model_log else set())
            return {"fills": np.asarray(rv.fills).tobytes(),
                    "preds": np.asarray(scored[pred].values,
                                        dtype=np.float64),
                    "faultKinds": kinds,
                    "faultReports": len(model_log.reports)
                    if model_log else 0,
                    "manifest": self.engine.manifest_problems(ckpt)}
        finally:
            shutil.rmtree(ckpt, ignore_errors=True)

    def violations(self, result, fired, log) -> List[str]:
        out = [f"stream: checkpoint manifest: {p}"
               for p in result["manifest"]]
        if result["fills"] != self.baseline["fills"]:
            out.append("stream: prep-fold stats not bit-equal (monoid "
                       "folds must be schedule-invariant)")
        exact = np.array_equal(result["preds"], self.baseline["preds"])
        preempted = any("preempt" in modes for modes in fired.values())
        if "oom.stream" in fired:
            if not np.allclose(result["preds"], self.baseline["preds"],
                               atol=5e-2):
                out.append("stream: downshifted predictions outside the "
                           "documented tolerance")
            # train() activates the model's own FaultLog — the downshift
            # record lands there. When a preemption interleaved, the
            # exhaustion may have hit a run that was killed before (or
            # just after) downshifting: its accounting legitimately died
            # with that run's log, so the check applies only to
            # uninterrupted trains.
            if (not preempted
                    and "oom_downshift" not in result["faultKinds"]):
                out.append("stream: oom.stream fired but no "
                           "oom_downshift was recorded")
        else:
            out += _divergence_violations(
                "stream", exact, set(fired),
                result["faultReports"] + len(log.reports))
        return out


class _FleetScenario(_Scenario):
    """Two-replica front door over one model: every request submitted
    through the fleet, one probe pass (so ``fleet.probe`` can fire), then
    collect. Oracles: the fleet accounting identity — submitted =
    completed + *typed* sheds, zero failed, zero lost futures — holds
    even when ``fleet.replica_kill`` murders a replica mid-schedule; every
    completed record is bit-equal to the fault-free single-process run;
    fired fleet sites leave their recovery kinds on the front door's
    FaultLog (replica_lost / fleet_failover / fleet_probe_failed)."""

    name = "fleet"

    def setup(self) -> None:
        from ..local import micro_batch_score_function
        from ..serving.loadgen import synthetic_rows
        self.model = self.engine.small_model()
        self.rows = synthetic_rows(self.model, 24, seed=57)
        self.baseline = micro_batch_score_function(self.model)(
            list(self.rows))

    def run(self, log: FaultLog) -> Dict[str, Any]:
        from ..serving.fleet import FleetConfig
        from ..serving.frontdoor import FrontDoor
        from ..serving.runtime import ServeConfig
        cfg = ServeConfig(max_batch=16, max_queue=64, max_wait_ms=10.0)
        fc = FleetConfig(min_replicas=2, max_replicas=2,
                         probe_interval_ms=0.0, probe_failures=1,
                         readmit_probes=1, max_failovers=2,
                         autoscale=False)
        completed: Dict[int, Dict[str, Any]] = {}
        shed: Dict[int, str] = {}
        failed: Dict[int, str] = {}
        lost: List[int] = []
        fd = FrontDoor({"m": self.model}, replicas=2, config=cfg,
                       fleet_config=fc, fault_log=log)
        try:
            pending = []
            for i, row in enumerate(self.rows):
                try:
                    pending.append((i, fd.submit(row)))
                except Exception as e:
                    if isinstance(e, self.engine.typed_escapes()):
                        shed[i] = type(e).__name__
                    else:
                        raise  # untyped submit failure = discipline breach
            # one deterministic probe pass: the ejection ladder (and the
            # fleet.probe site) run exactly once per schedule
            fd.probe_now()
            deadline = time.monotonic() + self.engine.collect_timeout
            for i, fut in pending:
                try:
                    completed[i] = fut.result(
                        timeout=max(0.05, deadline - time.monotonic()))
                except _FutureTimeout:
                    lost.append(i)
                except Exception as e:
                    if isinstance(e, self.engine.typed_escapes()):
                        shed[i] = type(e).__name__
                    else:
                        failed[i] = f"{type(e).__name__}: {e}"
            snapshot = fd.fleet_snapshot()
        finally:
            fd.close(drain=False)
        return {"completed": completed, "shed": shed, "failed": failed,
                "lost": lost, "fleet": snapshot,
                "accounting": {"submitted": len(self.rows),
                               "completed": len(completed),
                               "shed": len(shed), "failed": len(failed),
                               "lost": len(lost)}}

    def violations(self, result, fired, log) -> List[str]:
        out: List[str] = []
        n = len(self.rows)
        if result["lost"]:
            out.append(f"fleet: {len(result['lost'])} request future(s) "
                       f"never resolved (lost): {result['lost']}")
        if result["failed"]:
            out.append(f"fleet: request future(s) failed untyped "
                       f"(requests must complete or shed typed): "
                       f"{result['failed']}")
        total = (len(result["completed"]) + len(result["shed"])
                 + len(result["failed"]) + len(result["lost"]))
        if total != n:
            out.append(f"fleet: request accounting broken: "
                       f"{total} accounted of {n} submitted")
        mismatched = [i for i, rec in result["completed"].items()
                      if rec != self.baseline[i]]
        if mismatched:
            out.append(f"fleet: completed record(s) not bit-equal to the "
                       f"fault-free run: rows {sorted(mismatched)[:8]}")
        kinds = {r.kind for r in log.reports}
        for site in fired:
            want = ACCOUNT_KINDS.get(site)
            if want and want not in kinds:
                out.append(f"fleet: site {site} fired but recovery kind "
                           f"'{want}' was never recorded")
        if ("fleet.replica_kill" in fired
                and not result["fleet"]["kills"]):
            out.append("fleet: fleet.replica_kill fired but the fleet "
                       "snapshot shows no kill")
        return out


class _DensityScenario(_Scenario):
    """Multi-model fleet density: three models packed onto two replicas
    with ONE warm slot each (``PlaceConfig(max_warm=1)``), requests
    round-robined across the models — so every schedule exercises LRU
    eviction, single-flight demand paging, and (when
    ``fleet.replica_kill`` draws in) warm-copy loss with page-in
    recovery on the survivor. Oracles: the fleet accounting identity —
    submitted = completed + *typed* sheds, zero failed, zero lost
    futures — through model mobility; every completed record bit-equal
    to its model's fault-free run; fired ``place.*``/``fleet.*`` sites
    leave their recovery kinds on the front door's FaultLog."""

    name = "density"

    def setup(self) -> None:
        from ..local import micro_batch_score_function
        from ..serving.loadgen import synthetic_rows
        self.model_names = ("m7", "m8", "m9")
        self.models = {"m7": self.engine.small_model(7),
                       "m8": self.engine.small_model(8),
                       "m9": self.engine.small_model(9)}
        self.rows = {m: synthetic_rows(self.models[m], 6, seed=71 + i)
                     for i, m in enumerate(self.model_names)}
        self.baseline = {
            m: micro_batch_score_function(self.models[m])(
                list(self.rows[m]))
            for m in self.model_names}
        #: interleaved (model, row-index) submit order — maximal paging
        self.order = [(m, j) for j in range(6) for m in self.model_names]

    def run(self, log: FaultLog) -> Dict[str, Any]:
        from ..serving.fleet import FleetConfig
        from ..serving.frontdoor import FrontDoor
        from ..serving.placement import PlaceConfig
        from ..serving.runtime import ServeConfig
        cfg = ServeConfig(max_batch=16, max_queue=64, max_wait_ms=10.0)
        fc = FleetConfig(min_replicas=2, max_replicas=2,
                         probe_interval_ms=0.0, probe_failures=1,
                         readmit_probes=1, max_failovers=2,
                         autoscale=False)
        completed: Dict[Tuple[str, int], Dict[str, Any]] = {}
        shed: Dict[Tuple[str, int], str] = {}
        failed: Dict[Tuple[str, int], str] = {}
        lost: List[Tuple[str, int]] = []
        fd = FrontDoor(dict(self.models), replicas=2, config=cfg,
                       fleet_config=fc, fault_log=log,
                       placement=PlaceConfig(max_warm=1))
        try:
            pending = []
            for m, j in self.order:
                try:
                    pending.append(
                        ((m, j), fd.submit(self.rows[m][j], model=m)))
                except Exception as e:
                    if isinstance(e, self.engine.typed_escapes()):
                        shed[(m, j)] = type(e).__name__
                    else:
                        raise  # untyped submit failure = discipline breach
            fd.probe_now()
            deadline = time.monotonic() + self.engine.collect_timeout
            for key, fut in pending:
                try:
                    completed[key] = fut.result(
                        timeout=max(0.05, deadline - time.monotonic()))
                except _FutureTimeout:
                    lost.append(key)
                except Exception as e:
                    if isinstance(e, self.engine.typed_escapes()):
                        shed[key] = type(e).__name__
                    else:
                        failed[key] = f"{type(e).__name__}: {e}"
            snapshot = fd.fleet_snapshot()
        finally:
            fd.close(drain=False)
        return {"completed": completed, "shed": shed, "failed": failed,
                "lost": lost, "fleet": snapshot,
                "placement": snapshot.get("placement"),
                "accounting": {"submitted": len(self.order),
                               "completed": len(completed),
                               "shed": len(shed), "failed": len(failed),
                               "lost": len(lost)}}

    def violations(self, result, fired, log) -> List[str]:
        out: List[str] = []
        n = len(self.order)
        if result["lost"]:
            out.append(f"density: {len(result['lost'])} request "
                       f"future(s) never resolved (lost): "
                       f"{sorted(result['lost'])[:8]}")
        if result["failed"]:
            out.append(f"density: request future(s) failed untyped "
                       f"(requests must complete or shed typed): "
                       f"{result['failed']}")
        total = (len(result["completed"]) + len(result["shed"])
                 + len(result["failed"]) + len(result["lost"]))
        if total != n:
            out.append(f"density: request accounting broken: "
                       f"{total} accounted of {n} submitted")
        mismatched = [k for k, rec in result["completed"].items()
                      if rec != self.baseline[k[0]][k[1]]]
        if mismatched:
            out.append(f"density: completed record(s) not bit-equal to "
                       f"the fault-free run: {sorted(mismatched)[:8]}")
        kinds = {r.kind for r in log.reports}
        for site in fired:
            want = ACCOUNT_KINDS.get(site)
            if want and want not in kinds:
                out.append(f"density: site {site} fired but recovery "
                           f"kind '{want}' was never recorded")
        pl = result.get("placement") or {}
        if pl.get("inflightPageIns"):
            out.append(f"density: page-in(s) still in flight at "
                       f"snapshot: {pl['inflightPageIns']}")
        if ("fleet.replica_kill" in fired
                and not result["fleet"]["kills"]):
            out.append("density: fleet.replica_kill fired but the fleet "
                       "snapshot shows no kill")
        return out


class _NetScenario(_Scenario):
    """The network edge over one serving runtime: every request crosses
    a real localhost socket (alternating HTTP/JSON and binary framing)
    while ``net.accept``/``net.read``/``net.write`` chaos drops
    connections at each lifecycle stage. Oracles: the wire accounting
    identity — submitted = completed + *typed* sheds (an error status or
    a mid-request disconnect), zero failed (untyped 500s), zero lost
    futures — plus bit-equal completed records vs the fault-free
    in-process run, and fired net sites leaving their recovery kinds on
    the edge's FaultLog (net_accept_refused / net_read_shed /
    net_write_shed)."""

    name = "net"

    def setup(self) -> None:
        from ..local import micro_batch_score_function
        from ..serving.loadgen import synthetic_rows
        self.model = self.engine.small_model()
        self.rows = synthetic_rows(self.model, 16, seed=61)
        self.baseline = micro_batch_score_function(self.model)(
            list(self.rows))

    def run(self, log: FaultLog) -> Dict[str, Any]:
        import socket as _socket

        from ..serving.netedge import NetEdge
        from ..serving.netproto import WireClient, WireDisconnect
        from ..serving.runtime import ServeConfig, ServingRuntime
        cfg = ServeConfig(max_batch=16, max_queue=64, max_wait_ms=5.0)
        completed: Dict[int, Dict[str, Any]] = {}
        shed: Dict[int, str] = {}
        failed: Dict[int, str] = {}
        lost: List[int] = []
        rt = ServingRuntime(self.model, name="m", config=cfg)
        try:
            with NetEdge(rt, name="net", fault_log=log) as edge:
                host, port = edge.address
                clients = {p: WireClient(
                    host, port, protocol=p,
                    timeout=self.engine.collect_timeout)
                    for p in ("http", "binary")}
                try:
                    for i, row in enumerate(self.rows):
                        cli = clients["binary" if i % 2 else "http"]
                        try:
                            res = cli.request([row])
                        except WireDisconnect:
                            # mid-request disconnect: the typed wire shed
                            # (the future, if submitted, still resolves
                            # inside the runtime — proven by lost == 0)
                            shed[i] = "WireDisconnect"
                            continue
                        except _socket.timeout:
                            lost.append(i)
                            continue
                        if res.status == 200 and res.records:
                            completed[i] = res.records[0]
                        elif res.status >= 500 and res.error == "lost":
                            lost.append(i)
                        elif res.status == 500:
                            failed[i] = f"status 500: {res.error}"
                        else:
                            shed[i] = f"{res.status}:{res.error}"
                finally:
                    for c in clients.values():
                        c.close()
        finally:
            rt.close(drain=False)
        return {"completed": completed, "shed": shed, "failed": failed,
                "lost": lost,
                "accounting": {"submitted": len(self.rows),
                               "completed": len(completed),
                               "shed": len(shed), "failed": len(failed),
                               "lost": len(lost)}}

    def violations(self, result, fired, log) -> List[str]:
        out: List[str] = []
        n = len(self.rows)
        if result["lost"]:
            out.append(f"net: {len(result['lost'])} request(s) never got "
                       f"a response nor a typed shed (lost): "
                       f"{result['lost']}")
        if result["failed"]:
            out.append(f"net: request(s) failed untyped (requests must "
                       f"complete or shed typed): {result['failed']}")
        total = (len(result["completed"]) + len(result["shed"])
                 + len(result["failed"]) + len(result["lost"]))
        if total != n:
            out.append(f"net: request accounting broken: "
                       f"{total} accounted of {n} submitted")
        mismatched = [i for i, rec in result["completed"].items()
                      if rec != self.baseline[i]]
        if mismatched:
            out.append(f"net: completed record(s) not bit-equal to the "
                       f"fault-free run: rows {sorted(mismatched)[:8]}")
        kinds = {r.kind for r in log.reports}
        for site in fired:
            want = ACCOUNT_KINDS.get(site)
            if want and want not in kinds:
                out.append(f"net: site {site} fired but recovery kind "
                           f"'{want}' was never recorded")
        return out


class _TransferScenario(_Scenario):
    """The guarded host<->device transfer helpers alone: a placement and
    a readback through the always-on retry policies must round-trip
    bit-exactly or fail typed."""

    name = "transfer"

    def setup(self) -> None:
        self.x = (np.arange(2048, dtype=np.float32) * 0.5) - 311.0
        self.baseline = self.run(FaultLog())

    def run(self, log: FaultLog) -> Dict[str, Any]:
        from ..parallel.distributed import fetch_to_host, retrying_device_put
        dev = retrying_device_put(self.x)
        back = fetch_to_host(dev)
        return {"bytes": np.asarray(back, dtype=np.float32).tobytes()}

    def violations(self, result, fired, log) -> List[str]:
        equal = result["bytes"] == self.baseline["bytes"]
        return _divergence_violations("transfer", equal, set(fired),
                                      len(log.reports))


@dataclass
class CampaignReport:
    """One campaign's verdict: per-schedule results (faults armed, faults
    fired, outcome, violations), whole-campaign site coverage, the
    aggregated serve request accounting, and — for any violation — the
    minimized reproducer."""

    seed: int
    results: List[Dict[str, Any]] = field(default_factory=list)
    coverage: Dict[str, int] = field(default_factory=dict)
    uncovered: List[str] = field(default_factory=list)
    violations: List[Dict[str, Any]] = field(default_factory=list)
    minimized: List[Dict[str, Any]] = field(default_factory=list)
    accounting: Dict[str, int] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_json(self) -> Dict[str, Any]:
        covered = sum(1 for n in self.coverage.values() if n)
        return {
            "seed": self.seed,
            "schedules": len(self.results),
            "sites": len(self.coverage),
            "sitesCovered": covered,
            "coveragePct": round(100.0 * covered
                                 / max(1, len(self.coverage)), 1),
            "uncovered": list(self.uncovered),
            "firedBySite": dict(self.coverage),
            "violations": list(self.violations),
            "minimized": list(self.minimized),
            "accounting": dict(self.accounting),
            "results": list(self.results),
        }


class ChaosCampaign:
    """The engine. Typical use::

        eng = ChaosCampaign(seed=7)
        try:
            report = eng.run(count=40)
            assert report.ok and not report.uncovered
        finally:
            eng.close()
    """

    #: scenario draw weights for the randomized (post-coverage) schedules
    SCENARIO_WEIGHTS = (("serve", 0.24), ("train", 0.20), ("sweep", 0.15),
                        ("stream", 0.12), ("fleet", 0.08), ("density", 0.06),
                        ("net", 0.05), ("serve_heal", 0.05),
                        ("transfer", 0.05))
    _SCENARIOS = (_TrainScenario, _SweepScenario, _ServeScenario,
                  _ServeHealScenario, _StreamScenario, _FleetScenario,
                  _DensityScenario, _NetScenario, _TransferScenario)

    def __init__(self, seed: Optional[int] = None,
                 workdir: Optional[str] = None,
                 collect_timeout: Optional[float] = None,
                 scenarios: Optional[Sequence[str]] = None):
        self.seed = (seed if seed is not None
                     else _env_int("TG_CAMPAIGN_SEED", 0))
        self.collect_timeout = (
            collect_timeout if collect_timeout is not None
            else _env_float("TG_CAMPAIGN_COLLECT_TIMEOUT_S", 15.0))
        env_dir = os.environ.get("TG_CAMPAIGN_WORKDIR")
        self._own_workdir = workdir is None and not env_dir
        self.workdir = workdir or env_dir or tempfile.mkdtemp(
            prefix="tg_campaign_")
        os.makedirs(self.workdir, exist_ok=True)
        self.scenarios: Dict[str, _Scenario] = {
            cls.name: cls(self) for cls in self._SCENARIOS
            if scenarios is None or cls.name in scenarios}
        self._models: Dict[int, Any] = {}
        self._typed: Optional[Tuple[type, ...]] = None

    # -- shared fixtures -----------------------------------------------------
    def retry_policy(self) -> RetryPolicy:
        """Fast deterministic retries for the scenario harnesses (the
        chaos itself is counter-driven; backoff sleeps just slow runs)."""
        return RetryPolicy(max_retries=2, base_delay=0.001,
                           max_delay=0.002, jitter=0.0)

    def small_model(self, seed: int = 7):
        """A small fitted binary model shared by the serve scenarios."""
        if seed not in self._models:
            import pandas as pd

            from ..features import FeatureBuilder
            from ..impl.feature.transmogrifier import transmogrify
            from ..impl.selector.factories import (
                BinaryClassificationModelSelector)
            from ..workflow import OpWorkflow
            rng = np.random.RandomState(seed)
            n, d = 260, 3
            cols = {f"x{i}": rng.randn(n) for i in range(d)}
            y = (sum(cols.values()) > 0).astype(float)
            df = pd.DataFrame({**cols, "y": y})
            label = FeatureBuilder.RealNN("y").extract_field().as_response()
            feats = [FeatureBuilder.Real(f"x{i}").extract_field()
                     .as_predictor() for i in range(d)]
            checked = transmogrify(feats).sanity_check(label)
            pred = (BinaryClassificationModelSelector.with_cross_validation(
                seed=seed,
                models=[("OpLogisticRegression",
                         [{"regParam": 0.01, "elasticNetParam": 0.0}])])
                .set_input(label, checked).get_output())
            self._models[seed] = (OpWorkflow().set_input_dataset(df)
                                  .set_result_features(pred).train())
        return self._models[seed]

    def typed_escapes(self) -> Tuple[type, ...]:
        """The documented typed errors allowed to escape a scenario —
        anything else escaping a fenced region is an invariant
        violation (typed-error discipline)."""
        if self._typed is None:
            from ..local.scoring import ScoreSchemaError
            from ..persistence import CorruptModelError
            from ..serving.runtime import ServingError
            from ..streaming.trainer import StreamingNotSupportedError
            from .faults import InjectedFaultError, TransientFaultError
            from .guards import AllCandidatesFailedError
            from .resources import ResourceExhaustedError
            from .watchdog import WatchdogStallError
            self._typed = (TransientFaultError, InjectedFaultError,
                           ResourceExhaustedError, ServingError,
                           AllCandidatesFailedError, WatchdogStallError,
                           StreamingNotSupportedError, CorruptModelError,
                           ScoreSchemaError)
        return self._typed

    def manifest_problems(self, ckpt_dir: str) -> List[str]:
        """Checkpoint-integrity oracle: the manifest must load and every
        completion-recorded file must verify."""
        from ..persistence import FORMAT_VERSION
        from ..manifest import CheckpointManifest
        manifest, err = CheckpointManifest.load(ckpt_dir, FORMAT_VERSION)
        if err is not None and err != "missing":
            return [f"manifest unreadable: {err}"]
        return manifest.verify_recorded()

    # -- schedule generation -------------------------------------------------
    def _spec_for(self, site: str, mode: str, rng,
                  force_first: bool) -> Dict[str, Any]:
        spec: Dict[str, Any] = {
            "mode": mode,
            "nth": 1 if force_first else int(rng.randint(1, 3)),
            "count": 1}
        if mode == "raise":
            spec["transient"] = bool(rng.rand() < 0.7)
            if not force_first:
                spec["count"] = int(rng.randint(1, 3))
        elif mode == "oom":
            # oom.stream halves a 256-row chunk budget; one halving keeps
            # the schedule clear of the TG_OOM_MIN_CHUNK_ROWS floor
            spec["count"] = (1 if site == "oom.stream" or force_first
                             else int(rng.randint(1, 3)))
        elif mode == "nan":
            spec["index"] = 0 if rng.rand() < 0.7 else None
        elif mode == "preempt":
            spec["nth"] = 1 if force_first else int(rng.randint(1, 3))
            spec["count"] = 1  # one kill per armed site; resume recovers
        return spec

    def generate(self, count: int,
                 ensure_coverage: bool = True) -> List[Schedule]:
        """Deterministic schedule list for this engine's seed. With
        ``ensure_coverage`` (default) the list opens with one singleton
        schedule per registered site — nth=1, so the site provably fires
        — guaranteeing 100% site coverage by construction; randomized
        multi-site schedules fill the remaining budget."""
        rng = np.random.RandomState(self.seed)
        out: List[Schedule] = []
        available = set(self.scenarios)
        if ensure_coverage:
            for site in sorted(ALL_SITES):
                spec = ALL_SITES[site]
                scn = next((s for s in spec.scenarios if s in available),
                           None)
                if scn is None:
                    continue
                out.append({"scenario": scn, "faults": {
                    site: self._spec_for(site, spec.modes[0], rng,
                                         force_first=True)}})
        names = [n for n, _ in self.SCENARIO_WEIGHTS if n in available]
        weights = np.array([w for n, w in self.SCENARIO_WEIGHTS
                            if n in available])
        weights = weights / weights.sum()
        while len(out) < count:
            scn = str(names[int(rng.choice(len(names), p=weights))])
            pool = [s for s in sites_for_scenario(scn)]
            if not pool:
                continue
            k = 1 + int(rng.randint(0, min(3, len(pool))))
            sites = [str(s) for s in rng.choice(pool, size=k,
                                                replace=False)]
            # serve-side flushes coalesce (and fleet routing reacts to
            # live queue depths), so only first-call triggers are
            # schedule-deterministic there
            force = scn in ("serve", "serve_heal", "fleet", "net",
                            "density")
            fault_specs = {}
            for s in sorted(sites):
                mode = str(rng.choice(ALL_SITES[s].modes))
                fault_specs[s] = self._spec_for(s, mode, rng,
                                                force_first=force)
            out.append({"scenario": scn, "faults": fault_specs})
        return out

    # -- execution -----------------------------------------------------------
    def run_schedule(self, schedule: Schedule) -> Dict[str, Any]:
        """Arm the schedule, run its scenario, disarm, check every
        invariant oracle. Returns the schedule result record."""
        scn = self.scenarios[schedule["scenario"]]
        scn.ensure_setup()
        log = FaultLog()
        violations: List[str] = []
        outcome = "completed"
        result: Optional[Dict[str, Any]] = None
        fired_raw: Dict[str, Dict[str, int]] = {}
        with faults.injected({k: dict(v)
                              for k, v in schedule["faults"].items()}):
            try:
                with log.activate():
                    result = scn.run(log)
            except SimulatedPreemption as e:
                outcome = "preempted"
                violations.append(
                    f"{scn.name}: preemption escaped unrecovered: {e}")
            except Exception as e:
                outcome = f"raised:{type(e).__name__}"
                if not isinstance(e, self.typed_escapes()):
                    violations.append(
                        f"{scn.name}: untyped {type(e).__name__} escaped "
                        f"a fenced region: {e}")
                # trigger event: an error — typed or not — escaped a
                # campaign scenario; freeze the fault sequence that led
                # to it (rate-limited; observability/postmortem.py)
                _postmortem.trigger(
                    "campaign_escape", fault_log=log,
                    detail={"scenario": scn.name,
                            "error": f"{type(e).__name__}: {e}"[:300],
                            "typed": isinstance(e, self.typed_escapes()),
                            "faults": {k: dict(v) for k, v
                                       in schedule["faults"].items()}})
            finally:
                fired_raw = faults.fired_counts()
        if faults.active_sites():
            violations.append(
                f"sites left armed after clear: {faults.active_sites()}")
            faults.clear()
        violations.extend(oracles.campaign_violations())
        if outcome == "completed" and result is not None:
            violations.extend(scn.violations(result, fired_raw, log))
        return {"scenario": scn.name,
                "faults": {k: dict(v)
                           for k, v in schedule["faults"].items()},
                "fired": fired_raw, "outcome": outcome,
                "violations": violations,
                "accounting": (result or {}).get("accounting")}

    def run(self, count: Optional[int] = None,
            schedules: Optional[List[Schedule]] = None,
            minimize: bool = True) -> CampaignReport:
        """Run a campaign: ``count`` generated schedules (default
        ``TG_CAMPAIGN_SCHEDULES``/40; coverage singletons first), or an
        explicit ``schedules`` list. Violating schedules are delta-debug
        minimized into one-command reproducers when ``minimize``."""
        if schedules is None:
            budget = (count if count is not None
                      else _env_int("TG_CAMPAIGN_SCHEDULES", 40))
            schedules = self.generate(max(budget, 1))
        report = CampaignReport(
            seed=self.seed, coverage={s: 0 for s in ALL_SITES})
        acct = {"submitted": 0, "completed": 0, "shed": 0, "failed": 0,
                "lost": 0, "cancelled": 0}
        for idx, sch in enumerate(schedules):
            res = self.run_schedule(sch)
            res["index"] = idx
            for site, modes in res["fired"].items():
                if site in report.coverage:
                    report.coverage[site] += sum(modes.values())
            if res["accounting"]:
                for k in acct:
                    acct[k] += int(res["accounting"].get(k, 0))
            if res["violations"]:
                entry = {"index": idx, "scenario": res["scenario"],
                         "faults": res["faults"],
                         "violations": res["violations"]}
                if minimize:
                    mini = self.minimize(sch)
                    repro = self.reproducer(sch["scenario"], mini)
                    entry["minimized"] = mini
                    entry["repro"] = repro
                    report.minimized.append(repro)
                # trigger event: an invariant oracle fired — dump the
                # post-mortem bundle AFTER minimization (the probe re-runs
                # would shuffle the ring) and attach its path to the
                # one-command reproducer, so the repro ships with the
                # black-box context of the schedule that found it
                bundle = _postmortem.trigger(
                    "campaign_violation",
                    detail={"scenario": res["scenario"], "index": idx,
                            "violations": res["violations"],
                            "faults": res["faults"],
                            "minimized": entry.get("minimized"),
                            "cmd": (entry.get("repro") or {}).get("cmd")})
                if bundle is not None:
                    entry["postmortem"] = bundle
                    if "repro" in entry:
                        entry["repro"]["postmortem"] = bundle
                report.violations.append(entry)
            res.pop("accounting", None)
            report.results.append(res)
        report.uncovered = sorted(
            s for s, n in report.coverage.items() if n == 0)
        report.accounting = acct
        return report

    # -- minimization + reproducers ------------------------------------------
    def minimize(self, schedule: Schedule) -> Dict[str, Any]:
        """Delta-debug the schedule's fault set to a minimal failing
        subset: greedily drop one site at a time, keeping a drop only
        when the remaining set still violates, until a fixed point. The
        scenarios are deterministic, so every probe re-run replays the
        exact fault sequence — minimization converges instead of
        flaking."""
        fault_specs = dict(schedule["faults"])
        sites = sorted(fault_specs)

        def violates(subset: List[str]) -> bool:
            if not subset:
                return False
            sub = {"scenario": schedule["scenario"],
                   "faults": {s: fault_specs[s] for s in subset}}
            return bool(self.run_schedule(sub)["violations"])

        changed = True
        while changed and len(sites) > 1:
            changed = False
            for s in list(sites):
                rest = [k for k in sites if k != s]
                if violates(rest):
                    sites = rest
                    changed = True
        return {s: fault_specs[s] for s in sites}

    def reproducer(self, scenario: str,
                   fault_specs: Dict[str, Any]) -> Dict[str, Any]:
        """The one-command repro for a (minimized) failing fault set:
        the exact ``TG_FAULTS`` JSON plus the CLI invocation that
        re-runs the single schedule and exits non-zero on violation."""
        blob = json.dumps(fault_specs, sort_keys=True,
                          separators=(",", ":"))
        return {
            "scenario": scenario, "seed": self.seed,
            "faults": fault_specs,
            "env": {"TG_CHAOS": "1", "TG_FAULTS": blob},
            "cmd": (f"TG_CHAOS=1 TG_FAULTS='{blob}' python -m "
                    f"transmogrifai_tpu.cli campaign "
                    f"--scenario {scenario} --seed {self.seed}"),
        }

    def run_repro(self, repro: Dict[str, Any]) -> Dict[str, Any]:
        """Re-run a reproducer emitted by :meth:`reproducer`."""
        return self.run_schedule({"scenario": repro["scenario"],
                                  "faults": repro["faults"]})

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        """Drop scratch state (scenario checkpoint dirs, saved models)."""
        self._models.clear()
        if self._own_workdir:
            shutil.rmtree(self.workdir, ignore_errors=True)
