"""Retry policies and fault accounting.

The reference rides Spark's ``spark.task.maxFailures`` + lineage
recomputation; here retries are explicit: :class:`RetryPolicy` re-runs a
named operation on *transient* failures (device-transfer hiccups, link
resets, injected :class:`~.faults.TransientFaultError`) with exponential
backoff and deterministic jitter, and every recovery — retry, quarantine,
skipped checkpoint — is recorded as a :class:`FaultReport` in the
train-scoped :class:`FaultLog` that ``OpWorkflowModel.summary()["faults"]``
surfaces.
"""
from __future__ import annotations

import contextlib
import contextvars
import hashlib
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional

from ..observability import blackbox as _blackbox
from ..observability import metrics as _obs_metrics
from ..observability import trace as _obs_trace
from .faults import InjectedFaultError, TransientFaultError
from .resources import is_resource_exhausted

#: substrings (lowercased) marking an error transient: the gRPC-style
#: status codes surfaced by jax/PJRT transfer failures plus socket-level
#: resets on tunneled backends
TRANSIENT_PATTERNS = (
    "unavailable", "deadline exceeded", "deadline_exceeded", "data_loss",
    "connection reset", "connection refused", "broken pipe", "socket",
    "temporarily", "transfer failed", "resource temporarily",
)


def is_transient_error(exc: BaseException) -> bool:
    """Default transient-vs-fatal classification: explicit transient marker
    types, OS-level I/O interruptions, and runtime errors whose message
    carries a retryable transport status. Everything else — ValueError,
    shape/trace errors, injected fatal faults — is fatal: retrying a
    deterministic program on the same inputs cannot fix those.

    Resource exhaustion is checked FIRST and is never transient: an XLA
    ``RESOURCE_EXHAUSTED`` / host ``MemoryError`` / ``ENOMEM`` is
    deterministic at a given allocation size — re-running the identical
    allocation re-exhausts identically, so blind retry only triples the
    failure latency. (The "resource temporarily"/OSError heuristics below
    used to classify genuine exhaustion as retryable.) The downshift
    paths (robustness/resources.py) split the work instead."""
    if is_resource_exhausted(exc):
        return False
    if isinstance(exc, InjectedFaultError):
        return False
    if isinstance(exc, (TransientFaultError, ConnectionError, TimeoutError,
                        BrokenPipeError, InterruptedError)):
        return True
    if isinstance(exc, OSError):
        return True
    msg = str(exc).lower()
    # XlaRuntimeError (jaxlib) carries the PJRT status in its message
    if type(exc).__name__ == "XlaRuntimeError" or isinstance(exc, RuntimeError):
        return any(p in msg for p in TRANSIENT_PATTERNS)
    return False


@dataclass
class FaultReport:
    """One recovery event. ``kind``: ``retry`` (operation succeeded after
    ``attempts - 1`` retries), ``quarantine`` (candidate/family excluded
    from selection), ``checkpoint_skipped`` (corrupt/incomplete checkpoint
    detected and ignored on resume), ``restored`` (a fitted stage or sweep
    candidate rehydrated from a verified checkpoint instead of refitting),
    ``plan_fallback`` (a fused transform run raised and degraded to eager
    per-stage dispatch, plan.py), or ``fatal`` (retries exhausted /
    unretryable)."""
    site: str
    kind: str
    detail: Dict[str, Any] = field(default_factory=dict)
    attempts: int = 1

    @property
    def retries(self) -> int:
        return max(0, self.attempts - 1)

    def to_json(self) -> Dict[str, Any]:
        return {"site": self.site, "kind": self.kind,
                "attempts": self.attempts, "retries": self.retries,
                "detail": dict(self.detail)}


_CURRENT_LOG: "contextvars.ContextVar[Optional[FaultLog]]" = \
    contextvars.ContextVar("tg_fault_log", default=None)


#: ring bound for FaultLog.reports; a long-lived serving process under
#: sustained faults (an open breaker degrades every batch) must not grow
#: fault memory without bound — oldest reports drop, counted
FAULTS_MAX_ENV = "TG_FAULTS_MAX"
DEFAULT_FAULTS_MAX = 1024


def _faults_max() -> int:
    try:
        return max(1, int(os.environ.get(FAULTS_MAX_ENV, "")
                          or DEFAULT_FAULTS_MAX))
    except ValueError:
        return DEFAULT_FAULTS_MAX


class FaultLog:
    """Accumulator of :class:`FaultReport` records — train-scoped for
    ``OpWorkflow.train`` (activated around the whole fit), serve-scoped for
    ``serving.ServingRuntime`` (one per runtime).

    Components deep in the stack (validators, transfer helpers, checkpoint
    loader) record through the ambient :meth:`record` without threading the
    log through every signature; recording without an active log is a
    no-op, so library code never needs to guard. ``reports`` is a ring
    bounded by ``TG_FAULTS_MAX`` (default 1024): the newest reports win,
    drops are counted in :attr:`dropped` and the
    ``tg_faults_dropped_total`` counter — sustained serving faults must
    not leak memory."""

    def __init__(self, max_reports: Optional[int] = None):
        self.max_reports = (max(1, int(max_reports))
                            if max_reports is not None else _faults_max())
        self.reports: Deque[FaultReport] = deque()
        self.dropped = 0

    @contextlib.contextmanager
    def activate(self):
        token = _CURRENT_LOG.set(self)
        try:
            yield self
        finally:
            _CURRENT_LOG.reset(token)

    def add(self, report: FaultReport) -> None:
        """Append with the ring bound applied (the instance-level entry
        point; the serving runtime records here directly — its batcher
        thread has no ambient log)."""
        while len(self.reports) >= self.max_reports:
            self.reports.popleft()
            self.dropped += 1
            _obs_metrics.inc_counter(
                "tg_faults_dropped_total",
                help="fault reports dropped by the TG_FAULTS_MAX ring "
                "(docs/robustness.md)")
        self.reports.append(report)
        _emit_fault_observability(report)

    @staticmethod
    def current() -> Optional["FaultLog"]:
        """The ambient (activated) log of THIS thread, or None. Worker
        threads never see the consumer's ambient log (contextvars are
        per-thread) — components that record from their own threads
        capture this on the owning thread and ``add()`` directly (the
        serving batcher, the stream input engine's chunk cache)."""
        return _CURRENT_LOG.get()

    @staticmethod
    def record(report: FaultReport) -> None:
        log = _CURRENT_LOG.get()
        if log is not None:
            log.add(report)
        else:
            _emit_fault_observability(report)

    def of_kind(self, kind: str) -> List[FaultReport]:
        return [r for r in self.reports if r.kind == kind]

    def to_json(self) -> Dict[str, Any]:
        """The ``summary()["faults"]`` section (schema: docs/robustness.md)."""
        return {
            "quarantined": [r.to_json() for r in self.of_kind("quarantine")],
            "retries": [r.to_json() for r in self.of_kind("retry")],
            "checkpointsSkipped": [r.to_json()
                                   for r in self.of_kind("checkpoint_skipped")],
            "restored": [r.to_json() for r in self.of_kind("restored")],
            # fused transform runs that raised and degraded to eager
            # per-stage dispatch (docs/plan.md "Fallback semantics")
            "planFallbacks": [r.to_json()
                              for r in self.of_kind("plan_fallback")],
            # serve batches scored through the eager per-row fallback
            # (breaker open / dispatch failure; docs/serving.md)
            "breakerDegraded": [r.to_json()
                                for r in self.of_kind("breaker_degraded")],
            # drift-monitor events: contained fold/verdict failures plus
            # refit outcomes (drift_refit / drift_refit_failed;
            # docs/serving.md "Drift monitoring & self-healing")
            "drift": [r.to_json() for r in self.reports
                      if r.kind.startswith("drift_")],
            # adaptive degradation after resource exhaustion: row-batch
            # bisects, flush splits, chunk-budget halvings, grid splits
            # (docs/robustness.md "Resource exhaustion & watchdog")
            "oomDownshifts": [r.to_json()
                              for r in self.of_kind("oom_downshift")],
            # threads caught wedged by the watchdog or left alive past a
            # join(timeout=...) at close — never discarded silently
            "threadStalls": [r.to_json()
                             for r in self.of_kind("thread_stalled")],
            # stale run sentinels found on resume: a PREVIOUS process
            # owning this checkpoint dir exited uncleanly (SIGKILL, node
            # loss, the OOM killer — oomKillSuspected when its last phase
            # was device work; docs/robustness.md "Cross-process kill
            # detection")
            "uncleanExits": [r.to_json()
                             for r in self.of_kind("unclean_exit")],
            "fatal": [r.to_json() for r in self.of_kind("fatal")],
            # ring accounting: reports evicted under TG_FAULTS_MAX
            "droppedReports": self.dropped,
        }


def _emit_fault_observability(report: FaultReport) -> None:
    # observability choke point: every recovery anywhere in the stack
    # becomes a span event on whatever span is open (a trace shows the
    # quarantine in line with the sweep it interrupted) and a counter
    # keyed by kind (bounded cardinality; the site goes on the event
    # only). Both are no-ops when observability is off. The ALWAYS-ON
    # flight recorder (observability/blackbox.py) gets the same record —
    # one hook here puts every FaultLog event (retries, quarantines,
    # breaker degradations, downshifts, stalls, unclean exits, drift
    # events) into the black box, stamped with the ambient correlation
    # id when a run owns one.
    _blackbox.record("fault." + report.kind, site=report.site,
                     attempts=report.attempts)
    _obs_trace.add_event("fault." + report.kind, site=report.site,
                         attempts=report.attempts)
    _obs_metrics.inc_counter(
        "tg_faults_total", help="fault recoveries by kind "
        "(docs/robustness.md)", kind=report.kind)


@dataclass
class RetryPolicy:
    """Exponential backoff + deterministic jitter over transient failures.

    ``attempt_deadline``: an attempt whose wall-clock exceeds it is not
    retried even on a transient error — a stuck link that ate the whole
    budget should fail loud, not double the hang. ``classify`` overrides
    the default :func:`is_transient_error`."""
    max_retries: int = 2
    base_delay: float = 0.05
    max_delay: float = 2.0
    jitter: float = 0.25
    attempt_deadline: Optional[float] = None
    classify: Optional[Callable[[BaseException], bool]] = None

    def is_transient(self, exc: BaseException) -> bool:
        return (self.classify or is_transient_error)(exc)

    def delay_for(self, attempt: int, site: str) -> float:
        """Deterministic backoff: exponential in the attempt number, jittered
        by a hash of (site, attempt) — reproducible across runs, while
        distinct sites still decorrelate (no thundering herd on a shared
        coordinator)."""
        d = min(self.max_delay, self.base_delay * (2.0 ** attempt))
        if self.jitter:
            h = hashlib.md5(f"{site}:{attempt}".encode()).digest()
            frac = h[0] / 255.0
            d *= 1.0 + self.jitter * frac
        return d

    def execute(self, fn: Callable[[], Any], site: str) -> Any:
        """Run ``fn``; on transient failure back off and retry up to
        ``max_retries`` times. Success after >=1 retry records a ``retry``
        FaultReport; exhaustion or a fatal error records ``fatal`` and
        re-raises the last exception."""
        errors: List[str] = []
        attempt = 0
        while True:
            t0 = time.monotonic()
            try:
                out = fn()
            except Exception as e:
                elapsed = time.monotonic() - t0
                errors.append(f"{type(e).__name__}: {e}")
                over_deadline = (self.attempt_deadline is not None
                                 and elapsed > self.attempt_deadline)
                if (not self.is_transient(e) or attempt >= self.max_retries
                        or over_deadline):
                    FaultLog.record(FaultReport(
                        site=site, kind="fatal", attempts=attempt + 1,
                        detail={"errors": errors,
                                "overDeadline": over_deadline}))
                    raise
                delay = self.delay_for(attempt, site)
                _obs_trace.add_event("retry.backoff", site=site,
                                     attempt=attempt + 1,
                                     delaySecs=round(delay, 4))
                _obs_metrics.observe(
                    "tg_retry_backoff_seconds", delay,
                    help="backoff sleeps between transient-failure retries")
                time.sleep(delay)
                attempt += 1
                continue
            if attempt:
                FaultLog.record(FaultReport(
                    site=site, kind="retry", attempts=attempt + 1,
                    detail={"errors": errors}))
            return out
