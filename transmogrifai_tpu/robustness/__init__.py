"""Fault-isolated execution: retry policies, non-finite guards, and a
deterministic fault-injection harness.

The reference keeps long AutoML sweeps alive on flaky Spark executors via
task retries and lineage recomputation (reference: spark.task.maxFailures,
RDD lineage). The TPU rebuild replaced that substrate with jitted device
programs, so resilience has to be rebuilt at the framework layer:

* :mod:`.policy` — ``RetryPolicy`` (exponential backoff + deterministic
  jitter, per-attempt deadline, transient-vs-fatal classification) plus the
  ``FaultReport`` record and the train-scoped ``FaultLog``;
* :mod:`.guards` — non-finite guards over candidate CV metrics and fitted
  params, producing quarantine records instead of crashes;
* :mod:`.faults` — env/config-driven deterministic fault injection (named
  sites, fail-Nth-call, NaN poisoning) so every recovery path is testable
  on CPU (``JAX_PLATFORMS=cpu``, ``TG_CHAOS=1``);
* :mod:`.resources` — resource-exhaustion classification
  (``classify_exhaustion``: XLA ``RESOURCE_EXHAUSTED`` / host
  ``MemoryError`` → typed ``ResourceExhaustedError``) and the
  ``oom_downshift`` accounting behind the adaptive-degradation paths;
* :mod:`.watchdog` — heartbeat hang detection for worker threads
  (``TG_WATCHDOG_S``): a stalled batcher / feed producer / refit thread
  is recorded (``thread_stalled``), trips the serving breaker, or aborts
  a wedged feed with a typed error instead of hanging forever;
* :mod:`.oracles` — the no-leak / invariant checks as callable library
  functions, shared by the conftest fixtures and the campaign engine;
* :mod:`.campaign` — the chaos campaign engine: seeded randomized
  multi-fault schedules over the :data:`~.faults.ALL_SITES` registry,
  scenario harnesses, invariant oracles, and automatic delta-debug
  minimization of failing schedules into one-command ``TG_FAULTS``
  reproducers (docs/robustness.md "Chaos campaigns").

See docs/robustness.md for the fault-policy contract, the injection-site
table, and the ``summary()["faults"]`` schema.
"""
from . import faults  # noqa: F401
from .faults import ALL_SITES, SimulatedPreemption, SiteSpec  # noqa: F401
from .guards import (  # noqa: F401
    AllCandidatesFailedError, params_finite, quarantine_non_finite,
)
from .policy import (  # noqa: F401
    FaultLog, FaultReport, RetryPolicy, is_transient_error,
)
from .resources import (  # noqa: F401
    ResourceExhaustedError, classify_exhaustion, is_resource_exhausted,
)
from .watchdog import Watchdog, WatchdogStallError  # noqa: F401
