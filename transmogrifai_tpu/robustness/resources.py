"""Resource-exhaustion classification + adaptive-degradation accounting.

The reference's Spark substrate absorbed memory pressure for free —
executors spill to disk, tasks retry elsewhere — but a jitted XLA program
either fits on the device or dies with ``RESOURCE_EXHAUSTED``; host-side
table/record assembly dies with ``MemoryError``. Neither failure is
transient: retrying the identical allocation re-exhausts identically, so
the only useful response is to *downshift* — run the same work in smaller
pieces whose results compose back exactly. One classification helper lives
here so every choke point agrees on what "out of memory" looks like, and
one accounting helper so every downshift is observable the same way.

The four adaptive responses (docs/robustness.md "Resource exhaustion &
watchdog"):

* ``plan.py`` — a planned transform segment that exhausts bisects the row
  batch into smaller padding buckets (bit-equal by construction: the
  stages are per-row maps) before its existing eager fallback;
* ``serving/runtime.py`` — an exhausted flush splits in half down to
  singleton requests: latency degrades, requests never fail, and the
  circuit breaker counts only non-resource faults;
* ``streaming/trainer.py`` — a chunk the device cannot hold halves the
  chunk row budget and continues from the committed-row prefix
  (checkpoint records carry the active ``chunkRows`` so a killed
  downshifted train resumes bit-exactly);
* ``impl/tuning/validators.py`` — an exhausted packed (F·G) sweep grid
  splits in half and the per-config fold metrics merge back (metric
  concatenation along the config axis is the monoid), instead of
  quarantining the whole family.

Every downshift is a FaultLog ``oom_downshift`` report (span event +
``tg_faults_total{kind="oom_downshift"}`` via the FaultLog choke point)
plus the ``tg_oom_total{site}`` / ``tg_oom_downshift_total`` counters.
"""
from __future__ import annotations

import errno
import os
from typing import Any, Optional

from ..observability import metrics as _obs_metrics

#: message substrings (lowercased) marking a runtime error as device/host
#: memory exhaustion — the PJRT status name plus the prose jaxlib variants
EXHAUSTED_PATTERNS = (
    "resource_exhausted", "resource exhausted", "out of memory",
    "failed to allocate", "allocation failure",
)

#: minimum chunk row budget the streaming downshift may halve to
OOM_MIN_CHUNK_ROWS_ENV = "TG_OOM_MIN_CHUNK_ROWS"
DEFAULT_MIN_CHUNK_ROWS = 64


def min_chunk_rows() -> int:
    try:
        return max(1, int(os.environ.get(OOM_MIN_CHUNK_ROWS_ENV, "")
                          or DEFAULT_MIN_CHUNK_ROWS))
    except ValueError:
        return DEFAULT_MIN_CHUNK_ROWS


class ResourceExhaustedError(RuntimeError):
    """Typed resource exhaustion: the device (XLA ``RESOURCE_EXHAUSTED``)
    or the host (``MemoryError``, ``ENOMEM``) could not satisfy an
    allocation. Deterministic at a given work size — never blindly
    retried (robustness/policy.py routes it away from RetryPolicy); the
    downshift paths split the work instead."""

    def __init__(self, message: str, site: Optional[str] = None):
        super().__init__(message)
        self.site = site


def classify_exhaustion(exc: BaseException) -> Optional[ResourceExhaustedError]:
    """Return a typed :class:`ResourceExhaustedError` view of ``exc`` when
    it is a resource-exhaustion failure, else None. Recognizes:

    * :class:`ResourceExhaustedError` itself (injected or already wrapped);
    * host ``MemoryError`` and ``OSError`` with ``errno == ENOMEM``;
    * jaxlib ``XlaRuntimeError`` (and plain ``RuntimeError``) whose message
      carries the PJRT ``RESOURCE_EXHAUSTED`` status or an out-of-memory
      prose variant (:data:`EXHAUSTED_PATTERNS`).
    """
    if isinstance(exc, ResourceExhaustedError):
        return exc
    if isinstance(exc, MemoryError):
        return ResourceExhaustedError(f"host MemoryError: {exc}")
    if isinstance(exc, OSError) and getattr(exc, "errno", None) == errno.ENOMEM:
        return ResourceExhaustedError(f"host ENOMEM: {exc}")
    if type(exc).__name__ == "XlaRuntimeError" or isinstance(exc, RuntimeError):
        msg = str(exc).lower()
        if any(p in msg for p in EXHAUSTED_PATTERNS):
            return ResourceExhaustedError(
                f"{type(exc).__name__}: {exc}"[:500])
    return None


def is_resource_exhausted(exc: BaseException) -> bool:
    return classify_exhaustion(exc) is not None


def record_downshift(site: str, fault_log: Optional[Any] = None,
                     **detail: Any) -> None:
    """Account one adaptive downshift at ``site`` (``oom.plan`` /
    ``oom.serve`` / ``oom.stream`` / ``oom.sweep``): a FaultLog
    ``oom_downshift`` report (→ span event + ``tg_faults_total{kind}``
    through the FaultLog choke point) on ``fault_log`` (or the ambient
    train/serve log), plus the ``tg_oom_total{site}`` and
    ``tg_oom_downshift_total`` counters."""
    from .policy import FaultLog, FaultReport
    report = FaultReport(site=site, kind="oom_downshift",
                         detail=dict(detail))
    if fault_log is not None:
        fault_log.add(report)
    else:
        FaultLog.record(report)
    _obs_metrics.inc_counter(
        "tg_oom_total", help="resource-exhaustion events by site "
        "(docs/robustness.md)", site=site)
    _obs_metrics.inc_counter(
        "tg_oom_downshift_total",
        help="adaptive downshifts after resource exhaustion "
        "(docs/robustness.md)")
    # trigger event: exhaustion downshifts are recoveries, but the next
    # one might not be — dump the context while it exists (rate-limited;
    # observability/postmortem.py)
    from ..observability import postmortem as _postmortem
    _postmortem.trigger("oom_downshift", fault_log=fault_log,
                        detail={"site": site, **{k: v for k, v in
                                                 detail.items()}})
