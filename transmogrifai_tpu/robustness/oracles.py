"""Callable invariant oracles: the no-leak checks as library functions.

The conftest no-leak fixtures (tests/conftest.py) and the chaos-campaign
engine (robustness/campaign.py) need the SAME checks — "no serving
runtime survived", "no feed/watchdog/refit thread is alive", "no chaos
site is still armed", "the plan cache is bounded" — but a fixture can
only guard one test, while a campaign must run the checks after every one
of hundreds of randomized schedules. So the checks live here once, as
plain functions returning *violation strings* (empty list = clean), and
both consumers call them:

* each ``leaked_*`` probe reports what is live **without touching it**;
* each ``close_leaked_*`` helper force-closes the leftovers and returns
  what it closed — the fixtures use these on exit so one leaky test
  cannot poison the rest of the session, and the campaign uses them so
  one leaky schedule cannot poison the next;
* :func:`campaign_violations` is the aggregate the engine runs after
  every schedule (leaks are *violations*, then cleaned).

Nothing here imports heavyweight modules at import time — each probe
imports its subsystem lazily, so importing the oracles costs nothing.
"""
from __future__ import annotations

import os
import threading
from typing import Iterable, List, Optional

#: thread-name prefixes owned by framework worker threads; anything alive
#: with one of these names after a close/teardown is a leak. ``tg-serve``
#: prefix-matches both the batcher (``tg-serve[<model>]``) and the
#: pipelined completer (``tg-serve-completer[<model>]``), so the no-leak
#: sweep covers the whole serving dataplane automatically. ``tg-stream``
#: prefix-matches the input engine's ordered committer
#: (``tg-stream-feed``) and every producer worker (``tg-stream-w<i>``) —
#: a feed that fails to drain its pool on close shows up here.
THREAD_PREFIXES = ("tg-serve", "tg-stream", "tg-drift-refit", "tg-watchdog",
                   "tg-sampler", "tg-fleet", "tg-net")


# -- probes (read-only) ------------------------------------------------------

def leaked_serving_runtimes() -> List[str]:
    """Names of live (started, unclosed) serving runtimes."""
    from ..serving import runtime as _srt
    return [rt.name for rt in _srt.live_runtimes()]


def leaked_fleets() -> List[str]:
    """Names of live (started, unclosed) fleet front doors — each owns a
    probe thread plus N replica registries' worth of batcher threads."""
    from ..serving import frontdoor as _fd
    return [fd.name for fd in _fd.live_fleets()]


def leaked_net_edges() -> List[str]:
    """Names of live (started, unclosed) network edges — each owns a
    listening socket plus a ``tg-net`` event-loop thread."""
    from ..serving import netedge as _ne
    return [e.name for e in _ne.live_edges()]


def net_violations() -> List[str]:
    """The network-edge no-leak oracle: no listening socket, no
    ``tg-net`` thread, no pending connection task may survive (wired
    into :func:`campaign_violations` and the conftest ``_no_net_leak``
    fixture)."""
    from ..serving import netedge as _ne
    out: List[str] = []
    for e in _ne.live_edges():
        pending = e.pending_tasks()
        out.append(f"network edge '{e.name}' leaked (port "
                   f"{e.bound_port}, {pending} pending connection "
                   f"task(s))")
    return out


def leaked_placers() -> List[str]:
    """Names of live (unclosed) fleet placers — each holds residency
    state plus single-flight page-in events that block waiters."""
    from ..serving import placement as _pl
    return [p.name for p in _pl.live_placers()]


def placement_violations() -> List[str]:
    """The placement no-leak oracle: no placer may outlive its front
    door, and no single-flight page-in may still be in flight (a stuck
    page-in would block every later waiter for that model). Wired into
    :func:`campaign_violations` and the conftest ``_no_placement_leak``
    fixture."""
    from ..serving import placement as _pl
    out: List[str] = []
    for p in _pl.live_placers():
        inflight = p.inflight()
        out.append(f"placer '{p.name}' leaked"
                   + (f" ({len(inflight)} page-in(s) in flight: "
                      f"{sorted(inflight)})" if inflight else ""))
    return out


def leaked_stream_feeds() -> List[str]:
    """repr of open DeviceFeeds."""
    from ..streaming import feed as _feed
    return [f"DeviceFeed#{i}" for i, _ in enumerate(_feed.live_feeds())]


def leaked_watchdog_hearts() -> List[str]:
    """Names of registered (unclosed) watchdog hearts."""
    from . import watchdog as _wd
    return [h.name for h in _wd.live_hearts()]


def leaked_drift_refits() -> List[str]:
    """Names of live background drift-refit threads."""
    from ..serving import drift as _sdrift
    return [t.name for t in _sdrift.live_refits()]


def leaked_threads(prefixes: Iterable[str] = THREAD_PREFIXES) -> List[str]:
    """Live threads whose names carry a framework worker prefix."""
    pfx = tuple(prefixes)
    return [t.name for t in threading.enumerate()
            if t.name.startswith(pfx) and t.is_alive()]


def armed_fault_sites() -> List[str]:
    """Chaos sites still armed (must be empty outside an injection
    context)."""
    from . import faults
    return faults.active_sites()


def stray_postmortem_bundles() -> List[str]:
    """Post-mortem bundle files sitting in the *default* (env-less)
    ``TG_POSTMORTEM_DIR`` — between tests that directory must be empty
    (a test that expects bundles points the env at its own tmp dir, or
    its leftovers are swept by ``clean_postmortem_bundles``). The
    conftest ``_no_blackbox_leak`` fixture's probe."""
    from ..observability import postmortem as _postmortem
    return _postmortem.list_bundles(_postmortem.default_dir())


def clean_postmortem_bundles() -> List[str]:
    """Remove (and return) bundles from the default post-mortem dir —
    trigger events fired by a test are *expected* behavior, but their
    bundles must not accumulate across the session."""
    from ..observability import postmortem as _postmortem
    removed: List[str] = []
    for path in _postmortem.list_bundles(_postmortem.default_dir()):
        try:
            os.remove(path)
            removed.append(path)
        except OSError:
            pass
    return removed


def blackbox_violations() -> List[str]:
    """The flight recorder must stay bounded and no forced enable/disable
    override may linger (mirrors ``plan_cache_violations``)."""
    from ..observability import blackbox as _blackbox
    out: List[str] = []
    rec = _blackbox.recorder()
    snap = rec.snapshot()
    if snap["events"] > snap["maxEvents"]:
        out.append(f"flight recorder exceeded its ring bound: "
                   f"{snap['events']} > {snap['maxEvents']}")
    if _blackbox._enabled_override is not None:
        out.append("a forced blackbox enable/disable override is active")
    return out


def ledger_violations() -> List[str]:
    """The compile ledger must stay bounded and no forced enable/disable
    override may linger (mirrors ``blackbox_violations``)."""
    from ..observability import ledger as _ledger
    out: List[str] = []
    led = _ledger.ledger()
    snap = led.snapshot()
    if snap["records"] > snap["maxRecords"]:
        out.append(f"compile ledger exceeded its ring bound: "
                   f"{snap['records']} > {snap['maxRecords']}")
    if _ledger._enabled_override is not None:
        out.append("a forced ledger enable/disable override is active")
    return out


def leaked_sampler_sources() -> List[str]:
    """Names of registries still attached to the shared windowed-sampler
    thread (observability/timeseries.py) — every attached source keeps
    the ``tg-sampler`` thread alive and snapshots its registry forever."""
    from ..observability import timeseries as _ts
    return [s.name for s in _ts.attached()]


def registered_slo_specs() -> List[str]:
    """Keys of SLO specs still registered (observability/slo.py) — a spec
    leaked by a test silently changes every later runtime's budgets."""
    from ..observability import slo as _slo
    return [s.key for s in _slo.registered_specs()]


def slo_violations() -> List[str]:
    """Sampler/SLO state that must not outlive a test or a campaign
    schedule: attached sampler sources, registered specs, and a lingering
    forced TG_SAMPLER override (mirrors ``blackbox_violations``)."""
    from ..observability import timeseries as _ts
    out: List[str] = []
    srcs = leaked_sampler_sources()
    if srcs:
        out.append(f"sampler source(s) still attached: {srcs}")
    specs = registered_slo_specs()
    if specs:
        out.append(f"SLO spec(s) still registered: {specs}")
    if _ts._enabled_override is not None:
        out.append("a forced sampler enable/disable override is active")
    return out


def clean_slo_state() -> List[str]:
    """Force-detach sampler sources, drop registered specs, retire the
    tg-sampler thread; returns what was cleaned."""
    from ..observability import slo as _slo
    from ..observability import timeseries as _ts
    cleaned = leaked_sampler_sources() + registered_slo_specs()
    _ts.reset()
    _slo.reset()
    return cleaned


def programstore_violations() -> List[str]:
    """AOT program-store state that must not outlive a test or campaign
    schedule: an active capture scope (captures are strictly
    context-managed — one still open means a populate path leaked) and
    a lingering forced TG_AOT override. Open *sessions* are passive
    read-side dicts and are swept (not flagged) by the conftest fixture
    — but their presence changes later builds' ledger classification,
    so the sweep is mandatory."""
    from ..programstore import store as _pstore
    out: List[str] = []
    caps = _pstore.active_captures()
    if caps:
        out.append(f"AOT capture scope(s) still active: {caps}")
    if _pstore._enabled_override is not None:
        out.append("a forced AOT enable/disable override is active")
    return out


def histeng_violations() -> List[str]:
    """Histogram-engine state that must not outlive a test or campaign
    schedule: an active ``engine_mesh`` context (a leak would silently
    shard the next single-device tree trace's row blocks) and an
    unbounded contraction-factory cache. The conftest ``hist`` no-leak
    fixture also clears the factory cache per test."""
    from .. import histeng
    out: List[str] = []
    probe = histeng.engine_probe()
    if probe["mesh_ctx"] is not None:
        out.append(f"an engine mesh context leaked: {probe['mesh_ctx']}")
    # (n_bins, exact) pairs are few; triple digits means something is
    # generating fingerprints per call
    if probe["factory_cache"] > 100:
        out.append(f"histogram contraction factory cache unbounded: "
                   f"{probe['factory_cache']} entries")
    return out


def plan_cache_violations() -> List[str]:
    """The compiled-plan LRU must stay bounded and no forced
    planner-enable override may linger."""
    from .. import plan as _plan
    out: List[str] = []
    if not (isinstance(_plan._PLAN_CACHE_MAX, int)
            and _plan._PLAN_CACHE_MAX > 0):
        out.append(f"plan cache bound is {_plan._PLAN_CACHE_MAX!r}, "
                   f"not a positive int")
    elif len(_plan._PLAN_CACHE) > _plan._PLAN_CACHE_MAX:
        out.append(f"plan cache exceeded its LRU bound: "
                   f"{len(_plan._PLAN_CACHE)} > {_plan._PLAN_CACHE_MAX}")
    if _plan._enabled_override is not None:
        out.append("a forced planner enable/disable override is active")
    return out


# -- force-clean helpers (used on exit so one leak cannot cascade) ----------

def close_leaked_serving() -> List[str]:
    from ..serving import runtime as _srt
    leaked = _srt.live_runtimes()
    for rt in leaked:
        rt.close(drain=False)
    return [rt.name for rt in leaked]


def close_leaked_net_edges() -> List[str]:
    """Force-close leftover network edges — closed BEFORE the fleets
    and runtimes they front, so their connection handlers resolve
    (typed ``server_close`` sheds) while the targets still accept."""
    from ..serving import netedge as _ne
    leaked = _ne.live_edges()
    for e in leaked:
        e.close()
    return [e.name for e in leaked]


def close_leaked_fleets() -> List[str]:
    """Force-close leftover front doors (replicas included) — closed
    BEFORE the runtime sweep so a fleet's runtimes are not reported
    twice."""
    from ..serving import frontdoor as _fd
    leaked = _fd.live_fleets()
    for fd in leaked:
        fd.close(drain=False)
    return [fd.name for fd in leaked]


def close_leaked_placers() -> List[str]:
    """Force-close leftover placers (releases any blocked page-in
    waiters) — normally a placer closes with its front door, so anything
    here was detached from a fleet that already leaked."""
    from ..serving import placement as _pl
    leaked = _pl.live_placers()
    for p in leaked:
        p.close()
    return [p.name for p in leaked]


def close_leaked_feeds() -> List[str]:
    from ..streaming import feed as _feed
    leaked = _feed.live_feeds()
    for f in leaked:
        f.close()
    return [f"DeviceFeed#{i}" for i, _ in enumerate(leaked)]


def close_leaked_hearts() -> List[str]:
    """Close leftover hearts and let the shared scanner thread retire."""
    from . import watchdog as _wd
    leaked = _wd.live_hearts()
    for h in leaked:
        h.close()
    _wd.idle_join()
    return [h.name for h in leaked]


def join_drift_refits(timeout: float = 30.0) -> List[str]:
    """Join outstanding refit threads; returns any still alive after."""
    from ..serving import drift as _sdrift
    for t in _sdrift.live_refits():
        t.join(timeout=timeout)
    return [t.name for t in _sdrift.live_refits()]


# -- aggregates --------------------------------------------------------------

def campaign_violations(clean: bool = True,
                        refit_join_timeout: float = 30.0) -> List[str]:
    """The engine's post-schedule invariant sweep: every leak is a
    violation, and (with ``clean=True``, the default) the leftovers are
    force-closed so the NEXT schedule starts from a clean process — a
    campaign reports the first schedule that leaks instead of cascading
    false failures."""
    out: List[str] = []
    still = join_drift_refits(timeout=refit_join_timeout)
    if still:
        out.append(f"drift refit thread(s) outlived the schedule: {still}")
    out.extend(net_violations())
    fds = leaked_fleets()
    if fds:
        out.append(f"fleet front door(s) leaked: {fds}")
    out.extend(placement_violations())
    rts = leaked_serving_runtimes()
    if rts:
        out.append(f"serving runtime(s) leaked: {rts}")
    feeds = leaked_stream_feeds()
    if feeds:
        out.append(f"device feed(s) leaked: {feeds}")
    hearts = leaked_watchdog_hearts()
    if hearts:
        out.append(f"watchdog heart(s) leaked: {hearts}")
    out.extend(slo_violations())
    if clean:
        close_leaked_net_edges()
        close_leaked_fleets()
        close_leaked_placers()
        close_leaked_serving()
        close_leaked_feeds()
        close_leaked_hearts()
        clean_slo_state()
    else:
        from . import watchdog as _wd
        from ..observability import timeseries as _ts
        _wd.idle_join()
        _ts.idle_join()
    threads = leaked_threads()
    if threads:
        out.append(f"worker thread(s) survived: {threads}")
    out.extend(plan_cache_violations())
    out.extend(histeng_violations())
    out.extend(blackbox_violations())
    out.extend(ledger_violations())
    out.extend(programstore_violations())
    if clean:
        # sessions opened by a schedule's registry.load must not change
        # the NEXT schedule's ledger classification (an open session
        # turns would-be-cold builds into aot-miss)
        from ..programstore import store as _pstore
        _pstore.close_sessions()
    return out
