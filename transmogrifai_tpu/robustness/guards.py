"""Non-finite guards: quarantine diverging candidates instead of crashing.

A GLM candidate whose loss diverges, a tree whose leaf stats overflow, or a
poisoned metric (faults.py) all surface as non-finite CV metrics or fitted
params. The guards turn each into a quarantine record — the sweep continues
on the remaining candidates — and only the all-candidates-failed case
raises, with every reason aggregated.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .policy import FaultLog, FaultReport


class AllCandidatesFailedError(RuntimeError):
    """Every candidate of the sweep was quarantined; carries the aggregated
    per-candidate reasons so one traceback explains the whole failure."""

    def __init__(self, records: List[Dict[str, Any]]):
        self.records = list(records)
        lines = [f"  - {r.get('family')}[{r.get('gridIndex')}] "
                 f"{r.get('hyper')}: {r.get('reason')}" for r in self.records]
        super().__init__(
            "all %d sweep candidate(s) were quarantined:\n%s"
            % (len(self.records), "\n".join(lines)))


def quarantine_non_finite(family: str, grid: List[Dict[str, Any]],
                          fold_metrics: np.ndarray, metric_name: str,
                          larger_better: bool,
                          reason: Optional[str] = None,
                          ) -> Tuple[np.ndarray, np.ndarray,
                                     List[Dict[str, Any]]]:
    """Validate one family's (F, G) CV metric matrix.

    Returns ``(mean_metrics, masked_means, records)``: per-config means (NaN
    preserved for reporting), the means with non-finite entries replaced by
    the worst possible value (so argmax/argmin never elects a quarantined
    config — plain np.argmax treats NaN as the maximum), and one quarantine
    record per non-finite config. When every config is finite the masked
    means equal the raw means bit-for-bit, keeping selection byte-identical
    to the unguarded path."""
    mean_metrics = fold_metrics.mean(axis=0)
    finite = np.isfinite(mean_metrics)
    records: List[Dict[str, Any]] = []
    for g in np.nonzero(~finite)[0]:
        rec = {
            "family": family,
            "gridIndex": int(g),
            "hyper": dict(grid[g]) if g < len(grid) else {},
            "metricName": metric_name,
            "foldMetrics": [float(v) for v in fold_metrics[:, g]],
            "reason": reason or ("non-finite validation metric "
                                 f"({mean_metrics[g]!r})"),
        }
        records.append(rec)
        FaultLog.record(FaultReport(site="validator.candidate",
                                    kind="quarantine", detail=rec))
    if finite.all():
        return mean_metrics, mean_metrics, records
    worst = -np.inf if larger_better else np.inf
    return mean_metrics, np.where(finite, mean_metrics, worst), records


def params_finite(params: Dict[str, Any], allow_inf: Sequence[str] = ()
                  ) -> bool:
    """True when every float leaf of a fitted param pytree is finite. Keys
    in ``allow_inf`` (a family's ``inf_ok_params`` — e.g. tree thresholds,
    where +inf is the "stopped node" sentinel) are checked for NaN only.
    The reduction runs on device; only one scalar per leaf crosses the
    link."""
    import jax.numpy as jnp
    for k, v in params.items():
        if isinstance(v, dict):
            if not params_finite(v, allow_inf):
                return False
            continue
        try:
            arr = jnp.asarray(v)
        except (TypeError, ValueError):
            continue
        if jnp.issubdtype(arr.dtype, jnp.floating):
            ok = (jnp.logical_not(jnp.any(jnp.isnan(arr)))
                  if k in allow_inf else jnp.all(jnp.isfinite(arr)))
            if not bool(ok):
                return False
    return True
