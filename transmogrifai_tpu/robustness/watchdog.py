"""Heartbeat watchdog: hang detection for the framework's worker threads.

Retries, breakers and checkpoints (PRs 1/2/6) all assume a failure
*raises*; a wedged thread — a batcher stuck in a hung XLA dispatch, a
chunk-feed producer blocked in a dead reader, a drift refit that never
returns — raises nothing and hangs the process forever. The watchdog
closes that gap: monitored threads register a :class:`Heart` and ``beat()``
it every loop iteration; one shared scanner thread (``tg-watchdog``,
started lazily with the first heart, exiting with the last) checks every
heart against its stall budget (``TG_WATCHDOG_S``, default 30 s; 0
disables). A stall fires **once per episode** (re-arming when beats
resume):

* a ``thread_stalled`` FaultLog report on the heart's log (or the ambient
  train/serve log) + the ``tg_watchdog_stalls_total{site}`` counter +
  the ``fault.thread_stalled`` span event (via the FaultLog choke point);
* the heart's ``on_stall`` callback — the serving runtime trips its
  circuit breaker there (new batches degrade to the eager path instead of
  queueing behind the wedge), and the streaming feed aborts the consumer
  with a typed :class:`WatchdogStallError` instead of hanging forever.

The same ``thread_stalled`` accounting backs the join-timeout leak checks:
``DeviceFeed.close`` / ``ServingRuntime.close`` / ``ModelRegistry.close``
call :func:`report_thread_stalled` when a ``join(timeout=...)`` leaves the
thread alive, instead of silently discarding it.

The clock is injectable (per-:class:`Watchdog` instance) and
:meth:`Watchdog.check_now` scans synchronously, so stall detection is
deterministically testable without sleeping (tests/test_pressure.py).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, List, Optional

from ..observability import metrics as _obs_metrics

logger = logging.getLogger(__name__)

WATCHDOG_ENV = "TG_WATCHDOG_S"
DEFAULT_STALL_S = 30.0


def env_stall_seconds() -> float:
    """The default stall budget (seconds). 0 disables the watchdog —
    hearts become inert no-ops."""
    try:
        raw = os.environ.get(WATCHDOG_ENV, "")
        return float(raw) if raw else DEFAULT_STALL_S
    except ValueError:
        return DEFAULT_STALL_S


class WatchdogStallError(RuntimeError):
    """A monitored thread stopped beating past its stall budget. Raised to
    abort work that would otherwise wait on the wedged thread forever
    (e.g. the streaming feed's consumer)."""


class Heart:
    """One monitored thread's heartbeat handle. ``beat()`` on every loop
    iteration; ``close()`` when the thread exits (idempotent)."""

    __slots__ = ("name", "kind", "stall_after", "on_stall", "fault_log",
                 "last_beat", "stalled", "stalls", "closed", "_wd")

    def __init__(self, wd: "Watchdog", name: str, kind: str,
                 stall_after: float,
                 on_stall: Optional[Callable[["Heart", float], None]],
                 fault_log: Optional[Any]):
        self._wd = wd
        self.name = name
        self.kind = kind
        self.stall_after = stall_after
        self.on_stall = on_stall
        self.fault_log = fault_log
        self.last_beat = wd.clock()
        self.stalled = False
        self.stalls = 0
        self.closed = False

    def beat(self) -> None:
        self.last_beat = self._wd.clock()

    def close(self) -> None:
        self._wd.unregister(self)


class _NullHeart:
    """Inert heart returned when the watchdog is disabled (TG_WATCHDOG_S=0)
    — every touch point stays a no-op method call."""

    name = kind = "disabled"
    stalled = closed = False
    stalls = 0

    def beat(self) -> None:
        pass

    def close(self) -> None:
        pass


NULL_HEART = _NullHeart()


class Watchdog:
    """Heart registry + one scanner thread. The module-level singleton
    (:func:`watchdog`) monitors production threads; tests build their own
    instance with an injectable ``clock`` and drive :meth:`check_now`."""

    def __init__(self, stall_after: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 start_thread: bool = True):
        self.stall_after = (env_stall_seconds() if stall_after is None
                            else float(stall_after))
        self.clock = clock
        self._start_thread = start_thread
        self._lock = threading.Lock()
        self._hearts: List[Heart] = []
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        return self.stall_after > 0

    def register(self, name: str, kind: str = "thread",
                 stall_after: Optional[float] = None,
                 on_stall: Optional[Callable[[Heart, float], None]] = None,
                 fault_log: Optional[Any] = None):
        """Start monitoring a thread; returns its :class:`Heart` (the
        inert :data:`NULL_HEART` when disabled)."""
        budget = self.stall_after if stall_after is None else float(stall_after)
        if budget <= 0:
            return NULL_HEART
        heart = Heart(self, name, kind, budget, on_stall, fault_log)
        with self._lock:
            self._hearts.append(heart)
            if self._start_thread and (
                    self._thread is None or not self._thread.is_alive()):
                self._thread = threading.Thread(
                    target=self._run, name="tg-watchdog", daemon=True)
                self._thread.start()
        return heart

    def unregister(self, heart: Heart) -> None:
        with self._lock:
            heart.closed = True
            if heart in self._hearts:
                self._hearts.remove(heart)
            self._wake.set()  # let an idle scanner notice and exit

    def hearts(self) -> List[Heart]:
        with self._lock:
            return list(self._hearts)

    def check_now(self, now: Optional[float] = None) -> List[Heart]:
        """Scan every heart once; fire stalls; return the hearts newly
        stalled by this scan (the synchronous test entry point)."""
        now = self.clock() if now is None else now
        fired: List[Heart] = []
        for h in self.hearts():
            if h.closed:
                continue
            waited = now - h.last_beat
            if waited >= h.stall_after:
                if not h.stalled:
                    h.stalled = True
                    h.stalls += 1
                    fired.append(h)
                    self._fire(h, waited)
            else:
                h.stalled = False  # beats resumed: re-arm the episode
        return fired

    def _fire(self, heart: Heart, waited: float) -> None:
        report_thread_stalled(
            site=f"watchdog.{heart.kind}", thread_name=heart.name,
            waited_s=waited, fault_log=heart.fault_log,
            stallAfterS=heart.stall_after)
        cb = heart.on_stall
        if cb is not None:
            try:
                cb(heart, waited)
            except Exception:  # a stall handler must never kill the scanner
                logger.exception("watchdog on_stall handler for %s raised",
                                 heart.name)

    def _run(self) -> None:
        while True:
            with self._lock:
                if not self._hearts:
                    self._thread = None
                    return
                budget = min(h.stall_after for h in self._hearts)
            interval = min(max(budget / 4.0, 0.05), 5.0)
            self._wake.wait(interval)
            self._wake.clear()
            try:
                self.check_now()
            except Exception:  # pragma: no cover - defensive
                logger.exception("watchdog scan failed")

    def idle_join(self, timeout: float = 5.0) -> None:
        """Join the scanner thread once no hearts remain (test teardown)."""
        with self._lock:
            t = self._thread
            if self._hearts or t is None:
                return
        self._wake.set()
        t.join(timeout)


_GLOBAL_LOCK = threading.Lock()
_GLOBAL: Optional[Watchdog] = None


def watchdog() -> Watchdog:
    """The process-global watchdog (env-driven stall budget, real clock)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = Watchdog()
        return _GLOBAL


def register(name: str, kind: str = "thread",
             stall_after: Optional[float] = None,
             on_stall: Optional[Callable[[Heart, float], None]] = None,
             fault_log: Optional[Any] = None):
    """Register a heart on the global watchdog. Re-reads ``TG_WATCHDOG_S``
    per call so tests/benches can flip the budget per runtime."""
    wd = watchdog()
    budget = env_stall_seconds() if stall_after is None else stall_after
    return wd.register(name, kind=kind, stall_after=budget,
                       on_stall=on_stall, fault_log=fault_log)


def live_hearts() -> List[Heart]:
    """Open hearts on the global watchdog (conftest no-leak probe)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        wd = _GLOBAL
    return wd.hearts() if wd is not None else []


def idle_join(timeout: float = 5.0) -> None:
    global _GLOBAL
    with _GLOBAL_LOCK:
        wd = _GLOBAL
    if wd is not None:
        wd.idle_join(timeout)


def report_thread_stalled(site: str, thread_name: str, waited_s: float,
                          fault_log: Optional[Any] = None,
                          **detail: Any) -> None:
    """Account one stalled/leaked thread: a ``thread_stalled`` FaultLog
    report (→ span event + ``tg_faults_total{kind}``) on ``fault_log`` or
    the ambient log, plus ``tg_watchdog_stalls_total{site}``. Shared by
    the watchdog scanner and the ``join(timeout=...)`` leak checks in
    feed/runtime/registry ``close()``."""
    from .policy import FaultLog, FaultReport
    report = FaultReport(
        site=site, kind="thread_stalled",
        detail={"thread": thread_name, "waitedS": round(waited_s, 3),
                **detail})
    if fault_log is not None:
        fault_log.add(report)
    else:
        FaultLog.record(report)
    _obs_metrics.inc_counter(
        "tg_watchdog_stalls_total",
        help="thread stalls detected by the watchdog / join-timeout "
        "leak checks (docs/robustness.md)", site=site)
    logger.warning("thread '%s' stalled for %.1fs (site %s)",
                   thread_name, waited_s, site)
    # trigger event: a wedged thread is exactly the incident the black
    # box exists for — freeze its context into a post-mortem bundle
    # (rate-limited; observability/postmortem.py)
    from ..observability import postmortem as _postmortem
    _postmortem.trigger(
        "thread_stalled", fault_log=fault_log,
        detail={"site": site, "thread": thread_name,
                "waitedS": round(waited_s, 3), **detail})
