"""Deterministic fault-injection harness.

Every recovery path in the framework is exercised through *named sites*
compiled into the production code (``inject``/``poison`` calls). A site is
completely inert — one dict lookup on an empty dict — unless a
:class:`FaultSpec` is armed for it, either programmatically
(:func:`configure` / the :func:`injected` context manager, used by the
``chaos``-marked tests) or via environment::

    TG_CHAOS=1 TG_FAULTS='{"distributed.to_host": {"mode": "raise", "nth": 1, "count": 2}}'

The env path is gated on ``TG_CHAOS`` so a leaked ``TG_FAULTS`` can never
arm sites in a production process; ``tests/conftest.py`` additionally
asserts no sites are active around every non-chaos test.

Determinism: sites fire purely on call counters (fail the Nth..Nth+count-1
matching calls) — no clocks, no randomness — so a chaos test replays the
exact same fault sequence on every run.

Injection sites (see docs/robustness.md for the full table):

===========================  ====================================================
site                         fires in
===========================  ====================================================
``validator.family_fit``     per model family, before its sweep branch dispatches
``hist.build``               per tree family, before its histogram programs
                             build or dispatch (histeng/engine.py chaos_gate;
                             a raise quarantines the family like
                             ``validator.family_fit``)
``validator.fold_metrics``   per family, on the host (F, G) CV metric matrix
                             (``nan`` mode poisons candidate metrics)
``selector.refit``           before the winner's full-data refit
``dag.stage_fit``            before each estimator fit in the DAG
``distributed.to_host``      before each guarded device→host transfer
``distributed.device_put``   before each guarded host→device placement
``plan.segment_execute``     before each fused transform-plan segment runs
                             (plan.py; a raise here exercises the planned→
                             eager fallback — ``plan.*`` sites deliberately
                             do NOT disable the planner the way other armed
                             sites do)
``serve.enqueue``            in ``ServingRuntime.submit``, before admission
                             (serving/runtime.py; models the admission layer
                             failing — surfaces as a typed error to the one
                             caller, the runtime stays up)
``serve.flush``              in the batcher, after deadline shedding and
                             before dispatch (a raise degrades the batch to
                             the eager per-row path)
``serve.dispatch``           before the compiled micro-batch dispatch (a
                             raise feeds the per-model circuit breaker and
                             degrades the batch to the eager path; like
                             ``plan.*``, ``serve.*`` sites do NOT disable
                             the transform planner)
``serve.complete``           in the pipelined completer, before flattening
                             a device result (fires only with
                             ``TG_SERVE_PIPELINE`` > 1; the failure counts
                             against the *dispatching* flush and the batch
                             degrades to the eager path)
``stream.read``              in the chunk-feed producer thread, before each
                             chunk is pulled from the ChunkSource
                             (streaming/feed.py; errors — preemption
                             included — forward through the bounded queue
                             and re-raise in the consumer)
``stream.upload``            in the producer, before the chunk's packed
                             host→device upload (``to_device``)
``stream.cache``             in a producer worker, on every transformed-
                             chunk cache lookup (streaming/cache.py) — a
                             raise models a corrupt/evicted entry and
                             degrades to the typed recompute fallback
                             (bit-equal, never wrong data); preemption
                             kills mid-lookup and resumes bit-exactly
``stream.fold``              in the consumer, before a chunk folds into the
                             estimator's monoid state (key = pass id);
                             ``mode: "preempt"`` here is the canonical
                             kill-mid-epoch test — resume continues from
                             the last committed chunk bit-exactly
``drift.fold``               in the drift monitor, before a scored
                             micro-batch folds into the per-feature
                             scoring sketches (serving/drift.py; a raise
                             is contained by the runtime's crash-isolation
                             fence — typed ``drift_fold_failed``, zero
                             request impact; ``drift.*`` sites keep the
                             transform planner active like ``serve.*``)
``drift.verdict``            before a drift verdict pass compares the
                             scoring sketches against the training
                             baseline (contained in the monitor — typed
                             ``drift_verdict_failed``, fold state intact)
``drift.refit``              in the background refit thread, before the
                             refit hook runs (a raise means no new model:
                             typed ``drift_refit_failed``, the old model
                             keeps serving, breaker untouched)
``oom.plan``                 before each fused transform-plan segment runs
                             (plan.py; ``mode: "oom"`` raises a typed
                             :class:`~.resources.ResourceExhaustedError`
                             — the planned run bisects the row batch to
                             smaller padding buckets, bit-equal by
                             construction; ``oom.*`` sites keep the
                             planner active like ``plan.*``/``serve.*``)
``oom.serve``                before the compiled micro-batch dispatch in
                             the serve batcher (serving/runtime.py; an
                             exhausted flush splits in half down to
                             singletons — requests degrade in latency,
                             never fail, and the breaker counts only
                             non-resource faults)
``oom.stream``               in the chunk-feed producer, before the packed
                             host→device upload (streaming/feed.py; the
                             trainer halves the chunk row budget and
                             continues from the committed-row prefix)
``oom.sweep``                before a family's fused sweep program
                             dispatches (validators.py; the packed (F·G)
                             grid splits in half and fold metrics merge —
                             the family is downshifted, not quarantined)
``fleet.route``              in the front door, on the routing hop to the
                             selected replica (serving/frontdoor.py; a
                             raise fails the request over to another
                             replica within the bounded failover budget
                             — typed shed when exhausted; ``fleet.*``
                             sites keep the planner active like
                             ``serve.*``)
``fleet.replica_kill``       in the front door, as a request routes to
                             the selected replica (a raise kills that
                             replica — queued requests fail over to
                             survivors with zero lost futures, and a
                             ``replica_lost`` post-mortem bundle dumps)
``fleet.probe``              in the fleet health-probe pass, before a
                             replica's ``health()`` read (consecutive
                             failures walk the ejection ladder; healthy
                             probes readmit)
``aot.load``                 in the AOT program store, after an entry is
                             found and before its artifact loads
                             (programstore/store.py; models a corrupt /
                             truncated / stale-jaxlib artifact — the
                             dispatch falls back to the trace path
                             bit-equally with a typed ``aot_fallback``
                             record and an ``aot-miss`` ledger cause;
                             ``aot.*`` sites keep the planner active
                             like ``plan.*`` — the store lives inside
                             the planner's segment dispatch)
``net.accept``               in the network edge, per connection right
                             after the socket accept (serving/netedge.py;
                             a raise drops the connection as a typed
                             ``accept_fault`` shed with a
                             ``net_accept_refused`` FaultLog record —
                             nothing was submitted, nothing can be lost;
                             ``net.*`` sites keep the planner active
                             like ``serve.*``)
``net.read``                 per request, before the frame/body is read
                             off the socket (a raise models the read
                             path dying mid-request: the peer observes a
                             disconnect, the edge accounts a typed
                             ``read_fault`` shed + ``net_read_shed``)
``net.write``                per response, before the bytes are written
                             back (by this point every submitted future
                             has already resolved — the peer sees a
                             mid-request disconnect, the edge accounts a
                             typed ``write_fault`` shed +
                             ``net_write_shed``; never a lost future)
``place.assign``             in the placement bin-pack, per model as it
                             is assigned to a replica
                             (serving/placement.py; a raise leaves the
                             model cold — typed ``place_assign_failed``
                             — and it pages in on first demand, zero
                             request impact; ``place.*`` sites keep the
                             planner active like ``fleet.*``)
``place.evict``              before an LRU victim's runtime unloads (a
                             raise skips the eviction — the predicted
                             capacity is advisory — with a typed
                             ``place_evict_failed``; the page-in
                             proceeds anyway)
``place.pagein``             in the single-flight page-in leader,
                             before the cold model's runtime loads (a
                             raise fails the page-in typed —
                             ``place_pagein_failed`` — and the front
                             door retries within its bounded failover
                             budget: typed shed when exhausted, never
                             a lost future)
===========================  ====================================================

Preemption sites (``mode: "preempt"`` — raise :class:`SimulatedPreemption`,
a *BaseException* that models the process being killed: no ``except
Exception`` recovery path may swallow it, exactly like a real SIGTERM):

===========================  ====================================================
``preempt.stage_fit``        mid-DAG, before an estimator's fit starts
``preempt.checkpoint_write`` inside a stage-checkpoint write, between the
                             payload files and the manifest commit
``preempt.sweep``            mid-sweep, before a model family's branch runs
``preempt.refit``            after the sweep, before the winner's refit
===========================  ====================================================
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability import metrics as _obs_metrics

logger = logging.getLogger(__name__)

#: chaos gate: the env-driven spec (TG_FAULTS) is honored only when this is
#: set, so fault hooks can never arm themselves in a production process
CHAOS_ENV = "TG_CHAOS"
#: JSON dict {site: spec-dict} (see FaultSpec fields)
SPEC_ENV = "TG_FAULTS"


class TransientFaultError(RuntimeError):
    """Injected error classified transient by RetryPolicy (a stand-in for
    device-transfer hiccups: UNAVAILABLE / DEADLINE_EXCEEDED / link resets)."""


class InjectedFaultError(RuntimeError):
    """Injected error classified fatal (never retried)."""


class SimulatedPreemption(BaseException):
    """A deterministic stand-in for the process being killed (TPU
    preemption, SIGTERM, OOM-kill). Derives from ``BaseException`` — like
    ``KeyboardInterrupt`` — so quarantine/retry handlers (``except
    Exception``) can never absorb it: the only valid recovery is a fresh
    process calling ``train(resume=True)``."""


@dataclass
class FaultSpec:
    """One armed site.

    ``mode``: ``"raise"`` (throw from :func:`inject`), ``"nan"`` (poison
    the array passed to :func:`poison`), ``"preempt"`` (throw
    :class:`SimulatedPreemption` — a simulated process kill), or
    ``"oom"`` (throw :class:`~.resources.ResourceExhaustedError` — a
    simulated device/host allocation failure the adaptive downshift
    paths recover from).
    ``nth``/``count``: fire on matching calls nth..nth+count-1 (1-based).
    ``key``: only fire when the call's ``key`` matches (None = any).
    ``index``: nan mode — flat index to poison; None poisons the whole
    array. ``transient``: raise mode — throw :class:`TransientFaultError`
    (retryable) vs :class:`InjectedFaultError`.
    """
    site: str
    mode: str = "raise"
    nth: int = 1
    count: int = 1
    key: Optional[str] = None
    index: Optional[int] = 0
    transient: bool = True


@dataclass(frozen=True)
class SiteSpec:
    """One *registered* chaos site — the machine-readable row behind the
    docstring table above and the docs/robustness.md site tables (a test
    asserts all three agree, so the inventory can never silently rot).

    ``modes``: injection modes the site supports. ``module``: the file
    whose production code compiles the ``inject``/``poison`` call in.
    ``scenarios``: campaign scenario names that exercise the site
    (first entry is the canonical one the coverage pass uses —
    robustness/campaign.py). ``recovery``: the promised recovery, prose.
    ``bit_equal``: True when the promise is that a run recovering from
    this fault produces results **bit-identical** to the fault-free run
    (the campaign's strongest oracle); False when recovery legitimately
    alters the result (e.g. a quarantined candidate changes selection) —
    such divergence must then be visible in fault accounting, never
    silent."""
    name: str
    modes: Tuple[str, ...]
    module: str
    scenarios: Tuple[str, ...]
    recovery: str
    bit_equal: bool = True


def _site(name, modes, module, scenarios, recovery, bit_equal=True):
    return SiteSpec(name, tuple(modes.split("|")), module,
                    tuple(scenarios.split("|")), recovery, bit_equal)


#: the machine-readable site inventory (docs/robustness.md carries the
#: human tables; tests/test_campaign.py asserts they agree and that every
#: site here is armed by at least one tier-1 test — no dead chaos sites)
ALL_SITES: Dict[str, SiteSpec] = {s.name: s for s in (
    _site("validator.family_fit", "raise", "impl/tuning/validators.py",
          "sweep|train",
          "family quarantined; the other families still race",
          bit_equal=False),
    _site("hist.build", "raise", "histeng/engine.py", "sweep|train",
          "tree family quarantined before its histogram programs "
          "dispatch; the other families still race",
          bit_equal=False),
    _site("validator.fold_metrics", "nan", "impl/tuning/validators.py",
          "sweep|train",
          "poisoned candidates quarantined, sweep continues",
          bit_equal=False),
    _site("selector.refit", "raise", "impl/selector/model_selector.py",
          "train",
          "winner quarantined; next-ranked finite candidate refits",
          bit_equal=False),
    _site("dag.stage_fit", "raise", "dag.py", "train",
          "stage fit retried under the fault policy (transient), else "
          "typed failure"),
    _site("distributed.to_host", "raise", "parallel/distributed.py",
          "sweep|transfer|train",
          "device->host transfer retried (transient); a fatal transfer "
          "fault quarantines the consuming family", bit_equal=False),
    _site("distributed.device_put", "raise", "parallel/distributed.py",
          "transfer|mesh_sweep",
          "host->device placement retried (transient); a fatal placement "
          "fault quarantines the consuming family", bit_equal=False),
    _site("plan.segment_execute", "raise", "plan.py", "train|serve",
          "planned run falls back to eager per-stage dispatch, bit-equal"),
    _site("serve.enqueue", "raise", "serving/runtime.py", "serve",
          "typed error to the one caller; the runtime stays up"),
    _site("serve.flush", "raise", "serving/runtime.py", "serve",
          "batch degrades to the eager per-row path, bit-equal"),
    _site("serve.dispatch", "raise", "serving/runtime.py", "serve",
          "breaker counts the failure; batch degrades eager, bit-equal"),
    _site("serve.complete", "raise", "serving/runtime.py", "serve",
          "pipelined completion-side failure: the breaker counts it "
          "against the dispatching flush; batch degrades eager, "
          "bit-equal (fires only with TG_SERVE_PIPELINE > 1)"),
    _site("stream.read", "raise|preempt", "streaming/feed.py", "stream",
          "error forwards through the queue; preemption resumes "
          "bit-exactly from the last committed chunk"),
    _site("stream.upload", "raise|preempt", "streaming/feed.py", "stream",
          "error forwards through the queue; resume bit-exact"),
    _site("stream.cache", "raise|preempt", "streaming/cache.py", "stream",
          "corrupt/evicted entry falls back to a typed bit-equal "
          "recompute from source; preemption resumes bit-exactly"),
    _site("stream.fold", "raise|preempt", "streaming/trainer.py", "stream",
          "fold retried/resumed from the committed state, bit-exact"),
    _site("drift.fold", "raise", "serving/drift.py", "serve|serve_heal",
          "contained by the runtime fence; zero request impact"),
    _site("drift.verdict", "raise", "serving/drift.py", "serve|serve_heal",
          "contained in the monitor; fold state intact"),
    _site("drift.refit", "raise", "serving/registry.py", "serve_heal",
          "no swap; the old model keeps serving, breaker untouched"),
    _site("oom.plan", "oom", "plan.py", "train|serve",
          "row batch bisects to smaller padding buckets, bit-equal"),
    _site("oom.serve", "oom", "serving/runtime.py", "serve|serve_heal",
          "flush splits down to singletons; zero failed requests, "
          "bit-equal records"),
    _site("oom.stream", "oom", "streaming/feed.py", "stream",
          "chunk row budget halves from the committed-row prefix; prep "
          "folds bit-equal, tree edges within documented tolerance",
          bit_equal=False),
    _site("oom.sweep", "oom", "impl/tuning/validators.py", "sweep|train",
          "packed grid splits and fold metrics merge (identical winner); "
          "exhaustion persisting to a single config quarantines the "
          "family", bit_equal=False),
    _site("fleet.route", "raise", "serving/frontdoor.py", "fleet|density",
          "request fails over to another replica (bounded budget); "
          "typed shed when exhausted — never a lost future"),
    _site("fleet.replica_kill", "raise", "serving/frontdoor.py",
          "fleet|density",
          "replica killed mid-flight; queued requests fail over to "
          "survivors, replica_lost post-mortem dumped, zero lost — "
          "under placement, models whose only warm copy died page in "
          "on a survivor"),
    _site("fleet.probe", "raise", "serving/frontdoor.py", "fleet|density",
          "probe failure counted; consecutive failures eject the "
          "replica, healthy probes readmit it — requests unaffected"),
    _site("aot.load", "raise", "programstore/store.py", "serve_heal",
          "bad AOT artifact falls back to the trace path bit-equally; "
          "typed aot_fallback recorded, ledger build classified "
          "aot-miss — never a request error"),
    _site("net.accept", "raise", "serving/netedge.py", "net",
          "connection dropped at accept as a typed accept_fault shed; "
          "net_accept_refused recorded, nothing submitted, zero lost"),
    _site("net.read", "raise", "serving/netedge.py", "net",
          "read path dies mid-request; peer sees a disconnect, edge "
          "accounts a typed read_fault shed (net_read_shed)"),
    _site("net.write", "raise", "serving/netedge.py", "net",
          "write path dies mid-response after every future resolved; "
          "typed write_fault shed (net_write_shed), never a lost future"),
    _site("place.assign", "raise", "serving/placement.py", "density",
          "model left cold by the bin-pack (place_assign_failed); it "
          "pages in on first demand — zero request impact"),
    _site("place.evict", "raise", "serving/placement.py", "density",
          "eviction skipped (capacity prediction is advisory) with a "
          "typed place_evict_failed; the page-in proceeds anyway"),
    _site("place.pagein", "raise", "serving/placement.py", "density",
          "page-in fails typed (place_pagein_failed); the front door "
          "retries within the bounded failover budget — typed shed "
          "when exhausted, never a lost future"),
    _site("preempt.stage_fit", "preempt", "dag.py", "train|stream",
          "train(resume=True) restores verified stages, bit-exact"),
    _site("preempt.checkpoint_write", "preempt", "persistence.py",
          "train|stream",
          "torn checkpoint detected by manifest; resume refits it"),
    _site("preempt.sweep", "preempt", "impl/tuning/validators.py", "train",
          "persisted sweep state replays bit-exactly on resume"),
    _site("preempt.refit", "preempt", "impl/selector/model_selector.py",
          "train",
          "resume replays the sweep from disk and goes straight to refit"),
)}


def sites_for_scenario(scenario: str) -> List[str]:
    """Registered sites a campaign scenario can exercise (sorted)."""
    return sorted(n for n, s in ALL_SITES.items()
                  if scenario in s.scenarios)


_LOCK = threading.Lock()
_SPECS: Dict[str, FaultSpec] = {}
_CALLS: Dict[str, int] = {}
#: (site, mode) -> times an armed spec actually APPLIED its fault (raised /
#: poisoned) — always-on process-local accounting the campaign engine reads
#: for per-schedule coverage; mirrored into the gated
#: ``tg_chaos_injections_total{site,mode}`` counter (zero writes when
#: metrics are off). Reset by clear()/configure().
_FIRED: Dict[Tuple[str, str], int] = {}
_ENV_LOADED = False


def _load_env() -> None:
    global _ENV_LOADED
    if _ENV_LOADED:
        return
    _ENV_LOADED = True
    raw = os.environ.get(SPEC_ENV)
    if not raw:
        return
    if not os.environ.get(CHAOS_ENV):
        logger.warning(
            "%s is set but %s is not: ignoring fault-injection spec (sites "
            "stay inert outside chaos runs)", SPEC_ENV, CHAOS_ENV)
        return
    configure(json.loads(raw))


def configure(specs: Dict[str, Dict[str, Any]]) -> None:
    """Arm sites from {site: spec-dict}; resets all call counters."""
    with _LOCK:
        for site, kv in specs.items():
            _SPECS[site] = FaultSpec(site=site, **kv)
        _CALLS.clear()
        _FIRED.clear()


def clear() -> None:
    """Disarm every site and reset counters."""
    with _LOCK:
        _SPECS.clear()
        _CALLS.clear()
        _FIRED.clear()


def fired_counts() -> Dict[str, Dict[str, int]]:
    """{site: {mode: n}} faults actually applied since the last
    configure()/clear() — the campaign engine's per-schedule coverage
    accounting (armed-but-never-fired sites are invisible here)."""
    with _LOCK:
        out: Dict[str, Dict[str, int]] = {}
        for (site, mode), n in _FIRED.items():
            out.setdefault(site, {})[mode] = n
        return out


def _record_fired(site: str, mode: str) -> None:
    with _LOCK:
        _FIRED[(site, mode)] = _FIRED.get((site, mode), 0) + 1
    # an applied chaos fault is part of the incident narrative — the
    # flight recorder must show the injection next to the recovery it
    # provoked (observability/blackbox.py)
    from ..observability import blackbox as _blackbox
    _blackbox.record("chaos.injection", site=site, mode=mode)
    _obs_metrics.inc_counter(
        "tg_chaos_injections_total",
        help="chaos faults actually applied, by site and mode "
        "(docs/robustness.md 'Chaos campaigns')", site=site, mode=mode)


def active_sites() -> List[str]:
    """Names of currently-armed sites (empty in production)."""
    _load_env()
    return sorted(_SPECS)


@contextlib.contextmanager
def injected(specs: Dict[str, Dict[str, Any]]):
    """Arm ``specs`` for the duration of the block, then disarm everything
    (the chaos tests' entry point)."""
    configure(specs)
    try:
        yield
    finally:
        clear()


def _fires(site: str, key: Optional[str]) -> Optional[FaultSpec]:
    spec = _SPECS.get(site)
    if spec is None:
        return None
    if spec.key is not None and key != spec.key:
        return None
    with _LOCK:
        n = _CALLS.get(site, 0) + 1
        _CALLS[site] = n
    if spec.nth <= n < spec.nth + spec.count:
        return spec
    return None


def inject(site: str, key: Optional[str] = None) -> None:
    """Raise the armed fault for ``site`` if its spec fires on this call.
    Inert (one falsy dict check) when nothing is armed."""
    if not _SPECS and _ENV_LOADED:
        return
    _load_env()
    spec = _fires(site, key)
    if spec is None or spec.mode not in ("raise", "preempt", "oom"):
        return
    _record_fired(site, spec.mode)
    if spec.mode == "preempt":
        raise SimulatedPreemption(
            f"simulated preemption at site '{site}'"
            + (f" (key={key})" if key else ""))
    if spec.mode == "oom":
        from .resources import ResourceExhaustedError
        raise ResourceExhaustedError(
            f"injected resource exhaustion at site '{site}'"
            + (f" (key={key})" if key else ""), site=site)
    exc = TransientFaultError if spec.transient else InjectedFaultError
    raise exc(f"injected fault at site '{site}'"
              + (f" (key={key})" if key else ""))


def poison(site: str, arr: np.ndarray, key: Optional[str] = None) -> np.ndarray:
    """Return ``arr`` with NaN poisoning applied if the armed ``nan`` spec
    for ``site`` fires on this call; otherwise return ``arr`` untouched."""
    if not _SPECS and _ENV_LOADED:
        return arr
    _load_env()
    spec = _fires(site, key)
    if spec is None or spec.mode != "nan":
        return arr
    _record_fired(site, spec.mode)
    out = np.array(arr, dtype=np.float64 if arr.dtype.kind != "f"
                   else arr.dtype, copy=True)
    if spec.index is None:
        out[...] = np.nan
    else:
        out.reshape(-1)[spec.index] = np.nan
    return out
