"""Feature distribution sketches for the RawFeatureFilter.

Mirrors the reference distribution machinery (reference:
core/src/main/scala/com/salesforce/op/filters/FeatureDistribution.scala —
histogram/text-hash bins + JS divergence; Summary.scala; PreparedFeatures.scala)
re-based on the native streaming-histogram sketch
(native/streaming_histogram.cpp): numeric features stream through the C++
SPDT sketch in one host pass, text-ish features hash into a fixed bin space —
both mergeable monoids, so multi-host readers reduce them the same way the
reference monoid-reduces over RDD partitions (RawFeatureFilter.scala:135-196).
"""
from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..table import Column
from ..utils.streaming_histogram import StreamingHistogram

#: numeric column kinds sketched with the streaming histogram
_NUMERIC_KINDS = frozenset({"real", "binary", "integral", "date"})


def js_divergence(p, q, bins: int = 100) -> float:
    """Jensen-Shannon divergence in [0, 1] (log base 2) — THE shared
    implementation (reference FeatureDistribution.jsDivergence). Accepts
    either two dense mass arrays over identical bins, or two
    :class:`StreamingHistogram` sketches directly (binned over shared
    boundaries derived from their joint range — the serve-side drift
    monitor's path, where no dense arrays exist). RawFeatureFilter and
    the DriftMonitor both resolve here; there is deliberately no second
    copy of this math anywhere in the tree."""
    if isinstance(p, StreamingHistogram) or isinstance(q, StreamingHistogram):
        if not (isinstance(p, StreamingHistogram)
                and isinstance(q, StreamingHistogram)):
            raise TypeError("js_divergence needs two sketches or two arrays, "
                            f"got {type(p).__name__} vs {type(q).__name__}")
        edges = sketch_bin_edges(p, q, bins)
        if edges is None:
            return 0.0
        p, q = p.density(edges), q.density(edges)
    p, q = np.asarray(p, float), np.asarray(q, float)
    if p.size == 0 or q.size == 0 or p.size != q.size:
        return 0.0
    ps, qs = p.sum(), q.sum()
    if ps == 0 or qs == 0:
        return 0.0
    p, q = p / ps, q / qs
    m = (p + q) / 2.0
    with np.errstate(divide="ignore", invalid="ignore"):
        kl_pm = np.where(p > 0, p * np.log2(p / m), 0.0).sum()
        kl_qm = np.where(q > 0, q * np.log2(q / m), 0.0).sum()
    return float((kl_pm + kl_qm) / 2.0)


def sketch_bin_edges(a: StreamingHistogram, b: StreamingHistogram,
                     bins: int) -> Optional[np.ndarray]:
    """Shared open-ended bin boundaries over two sketches' joint [min, max]
    (the sketch twin of :func:`numeric_bin_edges`, which works from
    Summary records); None when neither sketch saw a finite value."""
    lo = min(a.min, b.min)
    hi = max(a.max, b.max)
    if not np.isfinite(lo) or not np.isfinite(hi):
        return None
    if hi <= lo:
        hi = lo + 1.0
    edges = np.linspace(lo, hi, bins + 1)
    return np.concatenate([[lo - 1.0], edges[1:-1], [hi + 1.0]])


@dataclass
class Summary:
    """Per-feature value summary (reference filters/Summary.scala)."""
    min: float = np.inf
    max: float = -np.inf
    sum: float = 0.0
    count: float = 0.0

    @staticmethod
    def of(values: np.ndarray) -> "Summary":
        if values.size == 0:
            return Summary()
        return Summary(float(np.min(values)), float(np.max(values)),
                       float(np.sum(values)), float(values.size))


def _hash_bin(token: str, bins: int) -> int:
    # stable across processes (zlib.crc32, not PYTHONHASHSEED-dependent)
    return zlib.crc32(token.encode("utf-8", "ignore")) % bins


@dataclass
class FeatureDistribution:
    """Binned distribution of one feature (or one map key).

    For numeric features ``sketch`` is a streaming histogram and
    ``distribution`` its mass over shared boundaries; for text-ish features
    ``distribution`` is direct hash-bin counts (reference
    FeatureDistribution.scala text path).
    """
    name: str
    key: Optional[str] = None          # map key, if this is a map sub-feature
    count: float = 0.0                 # total rows seen
    nulls: float = 0.0                 # rows where the value is missing
    distribution: np.ndarray = field(default_factory=lambda: np.zeros(0))
    summary: Summary = field(default_factory=Summary)
    is_numeric: bool = True
    sketch: Optional[StreamingHistogram] = None
    #: mesh path: (V_d, M_d, col_index, shift) — row-sharded device column
    #: data for exact CDF-diff binning (``RawFeatureFilter`` batch-fills all
    #: device-backed dists in ONE program; replaces the host SPDT sketch
    #: when a mesh is attached). ``shift``: f64 center subtracted before the
    #: f32 cast (keeps epoch-millis-scale values exact within f32).
    device_data: Optional[Any] = None

    @property
    def full_name(self) -> str:
        return self.name if self.key is None else f"{self.name}[{self.key}]"

    def fill_fraction(self) -> float:
        return 0.0 if self.count == 0 else 1.0 - self.nulls / self.count

    # -- comparisons (reference FeatureDistribution relativeFillRate etc.) ---
    def relative_fill_delta(self, other: "FeatureDistribution") -> float:
        return abs(self.fill_fraction() - other.fill_fraction())

    def relative_fill_ratio(self, other: "FeatureDistribution") -> float:
        a, b = self.fill_fraction(), other.fill_fraction()
        lo, hi = min(a, b), max(a, b)
        return np.inf if lo == 0 else hi / lo

    def js_divergence(self, other: "FeatureDistribution") -> float:
        """Jensen-Shannon divergence in [0, 1] — delegates to the shared
        module-level :func:`js_divergence` (one implementation for dense
        bins, sketches, train-time RFF, and serve-time drift)."""
        return js_divergence(self.distribution, other.distribution)

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name, "key": self.key, "count": self.count,
            "nulls": self.nulls, "fillFraction": self.fill_fraction(),
            "distribution": np.asarray(self.distribution).tolist(),
            "min": self.summary.min, "max": self.summary.max,
        }


# ---------------------------------------------------------------------------
# Sketch computation
# ---------------------------------------------------------------------------

def numeric_distribution(name: str, values: np.ndarray, valid: np.ndarray,
                         max_bins: int, key: Optional[str] = None,
                         ) -> FeatureDistribution:
    """Sketch one numeric column through the streaming Histogram fold —
    the SAME fill-rate/sketch monoid the out-of-core trainer and the
    serve-side DriftMonitor fold (streaming/folds.py HistogramFold), so a
    train-time RFF baseline and a serve-time accumulation are states of
    one fold, not two reimplementations."""
    from ..streaming.folds import HistogramFold
    vals = np.asarray(values, dtype=np.float64)
    fold = HistogramFold(1, max_bins=max_bins)
    state = fold.accumulate(fold.zero(), vals.reshape(-1, 1),
                            np.asarray(valid, bool).reshape(-1, 1))
    return fold_distribution(fold, state, 0, name, key=key)


def fold_distribution(fold, state, j: int, name: str,
                      key: Optional[str] = None) -> FeatureDistribution:
    """A :class:`FeatureDistribution` view of column ``j`` of a
    ``HistogramFold`` state (sketch + fill rate + summary) — shared by
    :func:`numeric_distribution` and the serving DriftMonitor."""
    sketch = fold.column_histogram(state, j)
    n = float(state["rows"])
    filled = n - float(state["nulls"][j])
    mn = sketch.min if filled else np.inf
    mx = sketch.max if filled else -np.inf
    # Summary.sum comes from bin centroids: SPDT merging preserves the
    # mass-weighted mean, so it equals the true sum up to float rounding.
    # Summary fields are used for bin edges + reporting, never for a
    # filter decision.
    val_sum = float(sum(p * m for p, m in sketch.bins())) if filled else 0.0
    return FeatureDistribution(
        name=name, key=key, count=n, nulls=float(state["nulls"][j]),
        summary=Summary(mn, mx, val_sum, sketch.total),
        is_numeric=True, sketch=sketch)


def text_distribution(name: str, tokens_per_row: Sequence[Optional[Sequence[str]]],
                      text_bins: int, key: Optional[str] = None,
                      ) -> FeatureDistribution:
    counts = np.zeros(text_bins, dtype=np.float64)
    nulls = 0
    card = 0.0
    for toks in tokens_per_row:
        if toks is None:
            nulls += 1
            continue
        for t in toks:
            counts[_hash_bin(str(t), text_bins)] += 1.0
            card += 1.0
    return FeatureDistribution(
        name=name, key=key, count=float(len(tokens_per_row)),
        nulls=float(nulls), distribution=counts,
        summary=Summary(0.0, float(text_bins), card, card), is_numeric=False)


def numeric_bin_edges(train: FeatureDistribution,
                      score: Optional[FeatureDistribution],
                      max_bins: int) -> Optional[np.ndarray]:
    """Shared bin boundaries from the train/score summaries (reference:
    score distributions are binned against train Summary bins), or None when
    the feature has no finite range."""
    lo = train.summary.min
    hi = train.summary.max
    if score is not None and score.summary.count:
        lo, hi = min(lo, score.summary.min), max(hi, score.summary.max)
    if not np.isfinite(lo) or not np.isfinite(hi):
        return None
    if hi <= lo:
        hi = lo + 1.0
    edges = np.linspace(lo, hi, max_bins + 1)
    # open-ended first/last bins via sentinels beyond the observed range
    return np.concatenate([[lo - 1.0], edges[1:-1], [hi + 1.0]])


def fill_numeric_bins(train: FeatureDistribution,
                      score: Optional[FeatureDistribution],
                      max_bins: int) -> None:
    """Bin both sketches over shared boundaries. Device-backed dists
    (``device_data``) are normally batch-filled by the RawFeatureFilter in
    one program before this runs; this per-feature path is the fallback."""
    finite_edges = numeric_bin_edges(train, score, max_bins)
    if finite_edges is None:
        return
    for dist in (train, score):
        if dist is None:
            continue
        if dist.device_data is not None:
            import jax.numpy as jnp
            V_d, M_d, j, shift = dist.device_data
            le = ((V_d[:, j, None]
                   <= jnp.asarray((finite_edges - shift).astype(np.float32)
                                  )[None, :]) & M_d[:, j, None])
            cs = np.asarray(le.astype(jnp.float32).sum(axis=0))
            dist.distribution = np.diff(cs)
        elif dist.sketch is not None:
            dist.distribution = dist.sketch.density(finite_edges)


def compare_distributions(train: FeatureDistribution,
                          score: FeatureDistribution,
                          bins: int) -> Dict[str, float]:
    """Train-vs-score comparison metrics — the ONE implementation both the
    train-time RawFeatureFilter and the serve-time DriftMonitor call:
    numeric sketches are binned over shared boundaries
    (:func:`fill_numeric_bins`), then fill-rate delta/ratio and JS
    divergence come from the shared :func:`js_divergence` math."""
    if train.is_numeric:
        fill_numeric_bins(train, score, bins)
    return {
        "trainFill": train.fill_fraction(),
        "scoreFill": score.fill_fraction(),
        "fillDelta": train.relative_fill_delta(score),
        "fillRatio": float(train.relative_fill_ratio(score)),
        "jsDivergence": train.js_divergence(score),
    }


def column_distributions(name: str, col: Column, max_bins: int, text_bins: int,
                         ) -> List[FeatureDistribution]:
    """Distribution(s) for one raw column; maps explode per key (reference
    PreparedFeatures: map features tracked per key)."""
    valid = col.valid_mask()
    if col.kind in _NUMERIC_KINDS:
        return [numeric_distribution(name, np.asarray(col.values, dtype=np.float64),
                                     valid, max_bins)]
    if col.kind == "map":
        by_key: Dict[str, List[Tuple[int, Any]]] = {}
        vals = col.values
        n = len(col)
        for i in range(n):
            if not valid[i] or vals[i] is None:
                continue
            for k, v in vals[i].items():
                by_key.setdefault(str(k), []).append((i, v))
        out: List[FeatureDistribution] = []
        for k, pairs in sorted(by_key.items()):
            present = {i for i, _ in pairs}
            sample = next((v for _, v in pairs if v is not None), None)
            if isinstance(sample, (int, float, bool, np.floating, np.integer)):
                kv = np.zeros(n, dtype=np.float64)
                km = np.zeros(n, dtype=bool)
                for i, v in pairs:
                    if v is not None:
                        try:
                            kv[i] = float(v)
                            km[i] = True
                        except (TypeError, ValueError):
                            pass
                out.append(numeric_distribution(name, kv, km, max_bins, key=k))
            else:
                toks: List[Optional[List[str]]] = [None] * n
                for i, v in pairs:
                    if v is not None:
                        toks[i] = [str(v)]
                out.append(text_distribution(name, toks, text_bins, key=k))
        return out
    # text-ish host kinds
    vals = col.values
    toks: List[Optional[List[str]]] = []
    for i in range(len(col)):
        if not valid[i] or vals[i] is None:
            toks.append(None)
        elif isinstance(vals[i], (list, tuple, set)):
            toks.append([str(x) for x in vals[i]])
        else:
            toks.append([str(vals[i])])
    return [text_distribution(name, toks, text_bins)]
