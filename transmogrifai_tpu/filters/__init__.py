from .distribution import FeatureDistribution, Summary  # noqa: F401
from .raw_feature_filter import (  # noqa: F401
    RawFeatureFilter, RawFeatureFilterResults,
)
