"""RawFeatureFilter — pre-DAG screening of raw features.

Mirrors the reference (reference:
core/src/main/scala/com/salesforce/op/filters/RawFeatureFilter.scala): before
any stage fits, compare each raw feature's training distribution against the
scoring distribution and the label, and blacklist features (or individual map
keys) that are too empty, too shifted, or leak the label through their null
pattern. Metrics (getRawFeatureFilterMetrics:207-291): fill rates, fill
rate delta/ratio between train and score, Jensen-Shannon divergence, and
null-indicator↔label correlation (leakage). Exclusion reasons (:302+)
drive the blacklists; the cleaned table plus
``RawFeatureFilterResults`` feed the workflow (OpWorkflow.scala:524-563).

The null-label correlations for ALL features are computed in one jitted
device pass (a (n, F) null-indicator matrix against the label — the TPU
re-expression of the reference's per-partition monoid reduce).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..features import Feature
from ..table import Column, FeatureTable
from .distribution import (
    FeatureDistribution, column_distributions, compare_distributions,
    fill_numeric_bins,
)


@dataclass
class FeatureMetrics:
    """Per-feature (or per map key) filter metrics (reference
    RawFeatureFilterMetrics)."""
    name: str
    key: Optional[str]
    train_fill_rate: float
    score_fill_rate: Optional[float] = None
    fill_rate_delta: Optional[float] = None
    fill_ratio_diff: Optional[float] = None
    js_divergence: Optional[float] = None
    null_label_correlation: Optional[float] = None
    exclusion_reasons: List[str] = field(default_factory=list)

    @property
    def full_name(self) -> str:
        return self.name if self.key is None else f"{self.name}[{self.key}]"


@dataclass
class RawFeatureFilterResults:
    """Config + metrics + decisions (reference RawFeatureFilterResults.scala)."""
    config: Dict[str, Any]
    metrics: List[FeatureMetrics]
    excluded_features: List[str]
    excluded_map_keys: Dict[str, List[str]]

    def to_json(self) -> Dict[str, Any]:
        def clean(d: Dict[str, Any]) -> Dict[str, Any]:
            return {k: (None if isinstance(v, float) and not np.isfinite(v) else v)
                    for k, v in d.items()}
        return {
            "config": self.config,
            "metrics": [clean(vars(m)) for m in self.metrics],
            "excludedFeatures": self.excluded_features,
            "excludedMapKeys": self.excluded_map_keys,
        }


class RawFeatureFilter:
    """Screens raw features before the DAG fits (reference
    RawFeatureFilter.scala ctor params :60-108)."""

    def __init__(self,
                 score_reader=None,
                 score_table: Optional[FeatureTable] = None,
                 bins: int = 100,
                 min_fill_rate: float = 0.001,
                 max_fill_difference: float = 0.90,
                 max_fill_ratio_diff: float = 20.0,
                 max_js_divergence: float = 0.90,
                 max_correlation: float = 0.90,
                 correlation_type: str = "pearson",
                 protected_features: Sequence[str] = (),
                 text_bins: int = 255):
        self.score_reader = score_reader
        self.score_table = score_table
        self.bins = bins
        self.min_fill_rate = min_fill_rate
        self.max_fill_difference = max_fill_difference
        self.max_fill_ratio_diff = max_fill_ratio_diff
        self.max_js_divergence = max_js_divergence
        self.max_correlation = max_correlation
        self.correlation_type = correlation_type
        self.protected_features = set(protected_features)
        self.text_bins = text_bins
        self.mesh = None

    def set_mesh(self, mesh) -> "RawFeatureFilter":
        """Shard the numeric distribution stats over a mesh's 'data' axis.

        RFF is the FIRST full pass over raw data (reference monoid reduce
        over RDD partitions, RawFeatureFilter.scala:135-196) — without this
        it is a single-host serial bottleneck before any sharded work
        starts. Numeric columns batch into one row-sharded device pass
        (count/min/max/sum + exact CDF-diff histograms); string/map columns
        remain host work by design (SURVEY §2.9 host boundary)."""
        self.mesh = mesh
        return self

    # -- distribution computation (reference computeFeatureStats:135-196) ----
    def _distributions(self, table: FeatureTable, features: Sequence[Feature],
                       ) -> Dict[str, List[FeatureDistribution]]:
        out: Dict[str, List[FeatureDistribution]] = {}
        numeric: List[Feature] = []
        for f in features:
            if f.is_response:
                continue
            col = table.get(f.name)
            if col is None:
                continue
            if self.mesh is not None and col.kind in (
                    "real", "binary", "integral", "date"):
                numeric.append(f)
                continue
            out[f.name] = column_distributions(
                f.name, col, self.bins, self.text_bins)
        if numeric:
            out.update(self._device_numeric_distributions(table, numeric))
        return out

    def _device_numeric_distributions(
            self, table: FeatureTable, feats: Sequence[Feature],
            ) -> Dict[str, List[FeatureDistribution]]:
        """All numeric columns in ONE row-sharded device stats pass: per-
        column count/nulls/min/max/sum, with the binned distributions
        batch-filled later (``_batch_fill_device_bins`` — one program for
        every feature, one sync). Columns are f64-centered on host before
        the f32 cast so epoch-millis-scale values keep full precision in
        the shifted frame. Counting is EXACT (CDF diff) — a tighter
        estimator than the host SPDT sketch's interpolated density, so a
        metric sitting within the sketch's approximation error of a
        threshold can decide differently with a mesh attached; fill rates
        and summaries are bit-matched."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .distribution import Summary

        n = table.num_rows
        n_data = self.mesh.shape["data"]
        n_pad = -(-max(n, 1) // n_data) * n_data
        V = np.zeros((n_pad, len(feats)), np.float32)
        M = np.zeros((n_pad, len(feats)), bool)
        shifts = np.zeros(len(feats), np.float64)
        for j, f in enumerate(feats):
            col = table[f.name]
            vals = np.asarray(col.values, np.float64)
            valid = col.valid_mask()
            if valid.any():
                shifts[j] = float(np.median(vals[valid]))
            V[:n, j] = (vals - shifts[j]).astype(np.float32)
            M[:n, j] = valid
        sh = NamedSharding(self.mesh, P("data", None))
        V_d = jax.device_put(jnp.asarray(V), sh)
        M_d = jax.device_put(jnp.asarray(M), sh)
        self._stats_input_sharding = str(V_d.sharding.spec)

        @jax.jit
        def stats(v, m):
            # counts stay int32 (exact past 2^24 — a float stack would
            # round them on 100M-row tables); the three float stats fuse
            # into one (3, d) array so the host pays TWO transfers, not
            # four (a transfer costs ~100 ms on the tunneled backend)
            cnt = m.astype(jnp.int32).sum(axis=0)
            vs = jnp.where(m, v, 0.0)
            fl = jnp.stack((jnp.where(m, v, jnp.inf).min(axis=0),
                            jnp.where(m, v, -jnp.inf).max(axis=0),
                            vs.sum(axis=0)))
            return cnt, fl

        cnt_d, fl_d = stats(V_d, M_d)
        cnt = np.asarray(cnt_d)
        mn, mx, sm = np.asarray(fl_d)

        out: Dict[str, List[FeatureDistribution]] = {}
        for j, f in enumerate(feats):
            c = float(cnt[j])
            out[f.name] = [FeatureDistribution(
                name=f.name, count=float(n), nulls=float(n) - c,
                summary=Summary(
                    float(mn[j]) + shifts[j] if c else np.inf,
                    float(mx[j]) + shifts[j] if c else -np.inf,
                    float(sm[j]) + shifts[j] * c, c),
                is_numeric=True, device_data=(V_d, M_d, j, shifts[j]))]
        return out

    @staticmethod
    def _batch_fill_device_bins(train_dists, score_dists, max_bins: int,
                                ) -> None:
        """Fill every device-backed dist's binned distribution in ONE
        program per table (a lax.map over columns) + one sync each — the
        per-feature path would cost two link round-trips per feature."""
        from .distribution import numeric_bin_edges

        groups: Dict[int, List[Tuple[Any, np.ndarray]]] = {}
        handles: Dict[int, Tuple[Any, Any]] = {}
        for name, dlist in train_dists.items():
            for d in dlist:
                if d.device_data is None:
                    continue
                sd = None
                if score_dists is not None:
                    sd = next((s for s in score_dists.get(name, [])
                               if s.key == d.key), None)
                edges = numeric_bin_edges(d, sd, max_bins)
                for dist in (d, sd):
                    if dist is None or dist.device_data is None:
                        continue
                    V_d, M_d, j, shift = dist.device_data
                    if edges is None:
                        dist.device_data = None
                        continue
                    groups.setdefault(id(V_d), []).append(
                        (dist, (edges - shift).astype(np.float32)))
                    handles[id(V_d)] = (V_d, M_d)
        if not groups:
            return
        import jax
        import jax.numpy as jnp

        @jax.jit
        def batched_cdf(v, m, cols, edges):
            def one(args):
                vj, mj, ej = args
                le = (vj[:, None] <= ej[None, :]) & mj[:, None]
                return le.astype(jnp.float32).sum(axis=0)
            return jax.lax.map(
                one, (v[:, cols].T, m[:, cols].T, edges))

        for gid, pairs in groups.items():
            V_d, M_d = handles[gid]
            cols = jnp.asarray([p[0].device_data[2] for p in pairs],
                               dtype=jnp.int32)
            edges = jnp.asarray(np.stack([p[1] for p in pairs]))
            cdfs = np.asarray(batched_cdf(V_d, M_d, cols, edges))
            for (dist, _), cs in zip(pairs, cdfs):
                dist.distribution = np.diff(cs)
                dist.device_data = None

    def _null_label_correlations(self, table: FeatureTable,
                                 features: Sequence[Feature],
                                 label: Optional[Column],
                                 dists: Dict[str, List[FeatureDistribution]],
                                 ) -> Dict[str, float]:
        """One device pass: corr(null indicator, label) for every feature/key
        (reference PreparedFeatures null-label vectors + Pearson)."""
        if label is None:
            return {}
        import jax.numpy as jnp
        from ..ops.stats import pearson_correlation, spearman_correlation

        y = np.asarray(label.values, dtype=np.float32)
        cols: List[np.ndarray] = []
        names: List[str] = []
        for f in features:
            if f.is_response or f.name not in dists:
                continue
            col = table[f.name]
            if col.kind == "map":
                valid = col.valid_mask()
                # one key-set per row, shared across all of the feature's keys;
                # a key present with a None/NaN value counts as NULL, matching
                # the fill-rate definition in column_distributions
                def _row_keys(v) -> frozenset:
                    if v is None:
                        return frozenset()
                    return frozenset(
                        str(k) for k, x in v.items()
                        if x is not None
                        and not (isinstance(x, float) and np.isnan(x)))
                row_keys = [
                    _row_keys(col.values[i]) if valid[i] else frozenset()
                    for i in range(len(col))]
                for d in dists[f.name]:
                    ind = np.array([0.0 if d.key in ks else 1.0
                                    for ks in row_keys], dtype=np.float32)
                    cols.append(ind)
                    names.append(d.full_name)
            else:
                ind = (~col.valid_mask()).astype(np.float32)
                cols.append(ind)
                names.append(f.name)
        if not cols:
            return {}
        X = jnp.asarray(np.stack(cols, axis=1))
        yd = jnp.asarray(y)
        # correlations are not pad-invariant, so shard only when the row
        # count divides the 'data' axis evenly (always true for the padded
        # stats pass; here rows come straight from the reader)
        if (self.mesh is not None
                and X.shape[0] % self.mesh.shape["data"] == 0):
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            X = jax.device_put(X, NamedSharding(self.mesh, P("data", None)))
            yd = jax.device_put(yd, NamedSharding(self.mesh, P("data")))
        corr_fn = (spearman_correlation
                   if self.correlation_type == "spearman"
                   else pearson_correlation)
        corrs = np.asarray(corr_fn(X, yd))
        return {n: float(c) for n, c in zip(names, corrs)}

    # -- main entry (reference generateFilteredRaw) --------------------------
    def filter_raw(self, table: FeatureTable, raw_features: Sequence[Feature],
                   ) -> Tuple[FeatureTable, List[Feature], RawFeatureFilterResults]:
        train_dists = self._distributions(table, raw_features)

        score_table = self.score_table
        if score_table is None and self.score_reader is not None:
            score_table = self.score_reader.generate_table(
                [f for f in raw_features if not f.is_response])
        score_dists = (self._distributions(score_table, raw_features)
                       if score_table is not None else None)

        label_col = next((table[f.name] for f in raw_features
                          if f.is_response and f.name in table), None)
        null_corr = self._null_label_correlations(
            table, raw_features, label_col, train_dists)
        # mesh path: bin every device-backed distribution in one batched
        # program per table before the per-feature metric loop
        self._batch_fill_device_bins(train_dists, score_dists, self.bins)

        metrics: List[FeatureMetrics] = []
        excluded_features: List[str] = []
        excluded_map_keys: Dict[str, List[str]] = {}

        for f in raw_features:
            if f.is_response or f.name not in train_dists:
                continue
            f_metrics: List[FeatureMetrics] = []
            for d in train_dists[f.name]:
                sd = None
                if score_dists is not None:
                    sd = next((s for s in score_dists.get(f.name, [])
                               if s.key == d.key), None)
                if d.is_numeric and sd is None:
                    fill_numeric_bins(d, sd, self.bins)
                m = FeatureMetrics(
                    name=f.name, key=d.key,
                    train_fill_rate=d.fill_fraction(),
                    null_label_correlation=null_corr.get(d.full_name))
                if sd is not None:
                    # the shared train-vs-score comparison (also the drift
                    # monitor's math, serving/drift.py). fill_ratio inf
                    # (one side completely empty) must EXCEED the
                    # threshold, matching the reference's
                    # Double.PositiveInfinity compare
                    cmp = compare_distributions(d, sd, self.bins)
                    m.score_fill_rate = cmp["scoreFill"]
                    m.fill_rate_delta = cmp["fillDelta"]
                    m.fill_ratio_diff = cmp["fillRatio"]
                    m.js_divergence = cmp["jsDivergence"]
                self._apply_exclusions(m, sd is not None)
                f_metrics.append(m)
                metrics.append(m)

            # a map feature with NO discovered keys (all rows empty) would
            # otherwise produce zero metrics and dodge the fill checks an
            # equally-empty scalar feature fails — fall back to whole-column
            # fill rates
            whole_column_fallback = not f_metrics
            if whole_column_fallback:
                col = table[f.name]
                m = FeatureMetrics(
                    name=f.name, key=None,
                    train_fill_rate=(float(col.valid_mask().mean())
                                     if len(col) else 0.0))
                if score_table is not None and f.name in score_table.column_names:
                    scol = score_table[f.name]
                    m.score_fill_rate = (float(scol.valid_mask().mean())
                                         if len(scol) else 0.0)
                    m.fill_rate_delta = abs(m.train_fill_rate - m.score_fill_rate)
                    lo = min(m.train_fill_rate, m.score_fill_rate)
                    hi = max(m.train_fill_rate, m.score_fill_rate)
                    m.fill_ratio_diff = float(np.inf) if lo == 0 else hi / lo
                self._apply_exclusions(m, m.score_fill_rate is not None)
                f_metrics.append(m)
                metrics.append(m)

            if f.name in self.protected_features:
                for m in f_metrics:
                    if m.exclusion_reasons:
                        m.exclusion_reasons = [
                            r + " (protected, kept)" for r in m.exclusion_reasons]
                continue
            is_map = table[f.name].kind == "map" and not whole_column_fallback
            if is_map and len(f_metrics) > 0:
                bad_keys = [m.key for m in f_metrics
                            if m.exclusion_reasons and m.key is not None]
                all_bad = bad_keys and len(bad_keys) == len(f_metrics)
                if all_bad:
                    excluded_features.append(f.name)
                elif bad_keys:
                    excluded_map_keys[f.name] = bad_keys
            elif any(m.exclusion_reasons for m in f_metrics):
                excluded_features.append(f.name)

        results = RawFeatureFilterResults(
            config={
                "bins": self.bins, "minFillRate": self.min_fill_rate,
                "maxFillDifference": self.max_fill_difference,
                "maxFillRatioDiff": self.max_fill_ratio_diff,
                "maxJSDivergence": self.max_js_divergence,
                "maxCorrelation": self.max_correlation,
                "correlationType": self.correlation_type,
            },
            metrics=metrics,
            excluded_features=sorted(excluded_features),
            excluded_map_keys=excluded_map_keys,
        )

        cleaned = self._clean_table(table, excluded_features, excluded_map_keys)
        blacklist = [f for f in raw_features if f.name in set(excluded_features)]
        return cleaned, blacklist, results

    def _apply_exclusions(self, m: FeatureMetrics, has_score: bool) -> None:
        """Reference ColumnStatistics/ExclusionReasons logic (:302+)."""
        if m.train_fill_rate < self.min_fill_rate:
            m.exclusion_reasons.append(
                f"train fill rate {m.train_fill_rate:.4f} below "
                f"{self.min_fill_rate}")
        if has_score:
            if m.score_fill_rate is not None and m.score_fill_rate < self.min_fill_rate:
                m.exclusion_reasons.append(
                    f"score fill rate {m.score_fill_rate:.4f} below "
                    f"{self.min_fill_rate}")
            if m.fill_rate_delta is not None and m.fill_rate_delta > self.max_fill_difference:
                m.exclusion_reasons.append(
                    f"fill rate delta {m.fill_rate_delta:.4f} above "
                    f"{self.max_fill_difference}")
            if m.fill_ratio_diff is not None and m.fill_ratio_diff > self.max_fill_ratio_diff:
                m.exclusion_reasons.append(
                    f"fill ratio diff {m.fill_ratio_diff:.2f} above "
                    f"{self.max_fill_ratio_diff}")
            if m.js_divergence is not None and m.js_divergence > self.max_js_divergence:
                m.exclusion_reasons.append(
                    f"JS divergence {m.js_divergence:.4f} above "
                    f"{self.max_js_divergence}")
        if (m.null_label_correlation is not None
                and abs(m.null_label_correlation) > self.max_correlation):
            m.exclusion_reasons.append(
                f"null-label correlation {m.null_label_correlation:.4f} above "
                f"{self.max_correlation} (leakage)")

    @staticmethod
    def _clean_table(table: FeatureTable, excluded: List[str],
                     excluded_keys: Dict[str, List[str]]) -> FeatureTable:
        out = table.drop([n for n in excluded if n in table.column_names])
        for name, keys in excluded_keys.items():
            if name not in out.column_names:
                continue
            col = out[name]
            gone = set(keys)
            vals = np.empty(len(col), dtype=object)
            for i, v in enumerate(col.values):
                vals[i] = (None if v is None
                           else {k: x for k, x in v.items() if str(k) not in gone})
            mask = np.array([v is not None and len(v) > 0 for v in vals])
            out = out.with_column(name, Column(col.feature_type, vals, mask,
                                               col.metadata))
        return out
