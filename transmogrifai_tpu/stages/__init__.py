from .base import (
    OpPipelineStage, AllowLabelAsInput, Transformer, Estimator,
    FeatureGeneratorStage,
    UnaryTransformer, BinaryTransformer, TernaryTransformer,
    QuaternaryTransformer, SequenceTransformer,
    UnaryEstimator, BinaryEstimator, SequenceEstimator,
)
