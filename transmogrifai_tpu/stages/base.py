"""Pipeline stage base classes.

Mirrors the reference stage hierarchy (reference:
features/src/main/scala/com/salesforce/op/stages/OpPipelineStages.scala:56-553,
base/unary/UnaryEstimator.scala, base/binary, base/ternary, base/quaternary,
base/sequence, FeatureGeneratorStage.scala:62-110) with a columnar twist:

* the primary execution path is **columnar** — ``Transformer.transform(table)``
  returns a whole output ``Column``, typically produced by a jitted kernel over
  device arrays (the analog of the reference fusing all row lambdas of a DAG
  layer into one RDD map, FitStagesUtil.scala:96-119; here XLA does the fusing);
* every transformer also exposes the row-level dual ``transform_row(row)`` — the
  equivalent of the reference's ``OpTransformer.transformKeyValue`` contract
  (OpPipelineStages.scala:527-553) that powers Spark-free local scoring.
"""
from __future__ import annotations

import abc
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from ..features import Feature, make_uid
from ..table import Column, FeatureTable
from ..types import FeatureType, OPVector


#: class-name → stage class, the analog of the reference's reflection-based
#: stage reader (OpPipelineStageReader.scala) resolving classes by name
STAGE_REGISTRY: Dict[str, type] = {}


class OpPipelineStage(abc.ABC):
    """Base of every stage: typed inputs, single typed output, params
    (reference OpPipelineStageBase, OpPipelineStages.scala:56-162)."""

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        STAGE_REGISTRY[cls.__name__] = cls

    #: input feature types; None entries mean "any feature type"
    input_types: Tuple[Optional[Type[FeatureType]], ...] = ()
    #: output feature type
    output_type: Type[FeatureType] = OPVector

    def __init__(self, operation_name: str, uid: Optional[str] = None):
        self.operation_name = operation_name
        self.uid = uid or make_uid(type(self).__name__)
        self.input_features: Tuple[Feature, ...] = ()
        self._output_feature: Optional[Feature] = None
        self._params: Dict[str, Any] = {}

    # -- wiring --------------------------------------------------------------
    def set_input(self, *features: Feature) -> "OpPipelineStage":
        self._check_input_length(features)
        for i, (f, expected) in enumerate(zip(features, self._expected_types(features))):
            if expected is not None and not issubclass(f.feature_type, expected):
                raise TypeError(
                    f"{type(self).__name__} input {i} must be {expected.__name__}, "
                    f"got {f.type_name} (feature '{f.name}')")
        self.input_features = tuple(features)
        self._output_feature = None
        return self

    def _check_input_length(self, features: Sequence[Feature]) -> None:
        if self.input_types and len(features) != len(self.input_types):
            raise ValueError(
                f"{type(self).__name__} takes {len(self.input_types)} inputs, "
                f"got {len(features)}")

    def _expected_types(self, features: Sequence[Feature]):
        if self.input_types:
            return self.input_types
        return (None,) * len(features)

    @property
    def input_names(self) -> Tuple[str, ...]:
        return tuple(f.name for f in self.input_features)

    def output_name(self) -> str:
        base = "-".join(self.input_names) if self.input_features else self.operation_name
        if len(base) > 64:
            # deep DAGs would otherwise double name length per level
            import hashlib
            base = base[:48] + "-" + hashlib.md5(base.encode()).hexdigest()[:8]
        return f"{base}_{self.operation_name}_{self.uid.rsplit('_', 1)[-1]}"

    def output_is_response(self) -> bool:
        """Output is a response iff any input is (reference
        OpPipelineStage.outputIsResponse); stages mixing in AllowLabelAsInput
        override to False."""
        return any(f.is_response for f in self.input_features)

    def get_output(self) -> Feature:
        if self._output_feature is None:
            self._output_feature = Feature(
                name=self.output_name(), feature_type=self.output_type,
                is_response=self.output_is_response(), origin_stage=self,
                parents=self.input_features)
        return self._output_feature

    # -- params (analog of Spark ML Params + OpParams injection) -------------
    def set_params(self, **kv) -> "OpPipelineStage":
        for k, v in kv.items():
            if not hasattr(self, k):
                raise ValueError(f"{type(self).__name__} has no param '{k}'")
            setattr(self, k, v)
        return self

    def get_params(self) -> Dict[str, Any]:
        return {k: v for k, v in vars(self).items()
                if not k.startswith("_") and k not in (
                    "input_features", "operation_name", "uid")}

    def __repr__(self) -> str:
        return f"{type(self).__name__}(uid={self.uid!r})"


class AllowLabelAsInput:
    """Lets a stage consume the label without marking its output as response
    (reference OpPipelineStages.scala:204-211; used by SanityChecker, LOCO)."""

    def output_is_response(self) -> bool:
        return False


class Transformer(OpPipelineStage):
    """A fitted/stateless stage that maps a table to one new column."""

    @abc.abstractmethod
    def transform_column(self, table: FeatureTable) -> Column:
        """Columnar path: compute the whole output column (device kernels)."""

    def transform(self, table: FeatureTable) -> FeatureTable:
        out = self.get_output()
        return table.with_column(out.name, self.transform_column(table))

    # row-level dual (reference OpTransformer.transformKeyValue)
    def transform_row(self, row: Dict[str, Any]) -> Any:
        """Single-row scoring path. Default: delegate to transform_fn if the
        subclass defines one, else run the columnar path on a 1-row table."""
        fn = getattr(self, "transform_fn", None)
        if fn is not None:
            args = [row.get(f.name) for f in self.input_features]
            return fn(*args)
        one = FeatureTable(
            {f.name: Column.of_values(f.feature_type, [row.get(f.name)])
             for f in self.input_features}, 1)
        out = self.transform_column(one)
        if out.mask is not None and not bool(np.asarray(out.mask)[0]):
            return None
        v = np.asarray(out.values)[0]
        return v.tolist() if isinstance(v, np.ndarray) else (
            v.item() if isinstance(v, np.generic) else v)


class PendingFit:
    """A dispatched-but-unsynced estimator fit: the device stat programs are
    queued, the host decision logic waits in ``finish``. Lets a caller
    (workflow-level CV pass 1, model_selector.py) queue F folds' fits
    back-to-back and pay ONE host transfer instead of F serial round-trips
    (the reference's analog: concurrent fold Futures,
    OpValidator.applyDAG :228-256)."""

    def __init__(self, dev: Dict[str, Any], finish: Callable[[Dict[str, Any]],
                                                             "Transformer"]):
        self.dev = dev          # name -> device array, still materializing
        self._finish = finish   # host dict (same keys, np arrays) -> model

    def finish_now(self) -> "Transformer":
        # even a single fit resolves through the fused per-dtype transfer:
        # a plain np.asarray per leaf costs a ~100 ms tunnel round-trip
        # EACH (7 leaves for a SanityChecker fit)
        return materialize_pending([self])[0]


def materialize_pending(pendings: "List[PendingFit]") -> "List[Transformer]":
    """Resolve many queued fits with ONE host transfer per dtype: all
    pending device leaves concatenate into flat vectors (grouped by dtype —
    casting counts through f32 would round above 2^24), transfer once, and
    split back. On tunneled backends a transfer costs ~70-130 ms of pure
    link latency, so F·|leaves| separate np.asarray calls dominate the
    actual stat kernels."""
    import jax.numpy as jnp
    leaves = []               # (pending_idx, key, shape, dtype)
    by_dtype: Dict[Any, list] = {}
    for pi, p in enumerate(pendings):
        for k, v in p.dev.items():
            if isinstance(v, np.ndarray):
                # host leaves keep their exact dtype (jnp.asarray would
                # silently narrow f64/i64 under the default x64-off
                # config — the rounding hazard this function's per-dtype
                # grouping exists to avoid)
                leaves.append((pi, k, None, None))
                continue
            v = jnp.asarray(v)
            leaves.append((pi, k, v.shape, v.dtype))
            by_dtype.setdefault(str(v.dtype), []).append(v.reshape(-1))
    flat_host = {dt: np.asarray(jnp.concatenate(vs)) if len(vs) > 1
                 else np.asarray(vs[0])
                 for dt, vs in by_dtype.items()}
    offs = {dt: 0 for dt in flat_host}
    host_dicts: List[Dict[str, Any]] = [{} for _ in pendings]
    for pi, k, shape, dtype in leaves:
        if shape is None:          # host leaf, passed through untouched
            host_dicts[pi][k] = pendings[pi].dev[k]
            continue
        dt = str(dtype)
        size = int(np.prod(shape)) if shape else 1
        host_dicts[pi][k] = flat_host[dt][offs[dt]:offs[dt] + size
                                          ].reshape(shape)
        offs[dt] += size
    return [p._finish(h) for p, h in zip(pendings, host_dicts)]


class Estimator(OpPipelineStage):
    """A stage that must be fit on data, producing a Transformer model
    (reference Unary/Binary/…Estimator fitFn pattern)."""

    @abc.abstractmethod
    def fit(self, table: FeatureTable) -> Transformer:
        """Fit on the table and return the fitted model transformer. The model
        MUST reuse this stage's uid and output feature so DAG wiring holds
        (reference: model uid == estimator uid)."""

    def fit_queued(self, table: FeatureTable) -> PendingFit:
        """Queued-fit protocol: dispatch the device stat programs and defer
        the host sync + decision logic to ``PendingFit.finish``. The default
        wraps plain ``fit`` (sync happens inside it); estimators whose fit
        is transfer-latency-bound override this (SanityChecker)."""
        model = self.fit(table)
        return PendingFit({}, lambda _h: model)

    def _finalize_model(self, model: Transformer) -> Transformer:
        model.uid = self.uid
        model.input_features = self.input_features
        # keep the estimator's naming so output feature names stay stable
        model.operation_name = self.operation_name
        model.output_type = self.output_type
        model._output_feature = self.get_output()
        return model


class FeatureGeneratorStage(OpPipelineStage):
    """Origin stage of raw features: holds the record-level ``extract_fn`` and
    the optional event-aggregation monoid (reference
    FeatureGeneratorStage.scala:62-110)."""

    def __init__(self, extract_fn: Callable[[Any], Any], output_name: str,
                 output_type: Type[FeatureType], is_response: bool,
                 aggregator: Optional[Any] = None,
                 aggregate_window: Optional[int] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name=f"generate_{output_name}", uid=uid)
        self.extract_fn = extract_fn
        self.output_type = output_type
        self.is_response = is_response
        self.aggregator = aggregator
        self.aggregate_window = aggregate_window
        self._raw_name = output_name

    def output_name(self) -> str:
        return self._raw_name

    def output_is_response(self) -> bool:
        return self.is_response

    def extract(self, record: Any) -> Any:
        v = self.extract_fn(record)
        if isinstance(v, FeatureType):
            return v.value
        return v


# ---------------------------------------------------------------------------
# Arity-typed lambda stages (reference base/unary/.., base/sequence/..)
# ---------------------------------------------------------------------------

def _iter_cell_values(cols: Sequence[Column]):
    """Iterate rows over just these columns, yielding python values (None =
    missing) — avoids materializing whole-table rows in lambda fallbacks."""
    n = len(cols[0]) if cols else 0
    arrs = [np.asarray(c.values) for c in cols]
    masks = [c.valid_mask() for c in cols]
    for i in range(n):
        out = []
        for a, m in zip(arrs, masks):
            if not m[i]:
                out.append(None)
            else:
                v = a[i]
                out.append(v.tolist() if isinstance(v, np.ndarray) else (
                    v.item() if isinstance(v, np.generic) else v))
        yield tuple(out)


def _vectorized_value_transform(transform_fn: Callable[..., Any],
                                output_type: Type[FeatureType],
                                cols: Sequence[Column]) -> Optional[Column]:
    """Whole-column numpy fast path for value-level lambdas: when every
    input column is numeric and fully valid (no ``None`` the lambda could
    see), apply ``transform_fn`` to the arrays directly — arithmetic
    lambdas are ufunc-compatible and run in one vectorized sweep instead of
    a python loop rebuilding a list per cell. Returns None (→ row-map
    fallback) when inputs are object/masked, the fn rejects arrays
    (truthiness / branching lambdas raise), or the result doesn't look like
    one value per row. The produced Column replicates ``of_values``
    semantics exactly: NaN results are missing (mask False, slot 0)."""
    kind = output_type.column_kind
    if kind not in ("real", "binary", "integral") or not cols:
        return None
    n = len(cols[0])
    if n == 0:     # zero-row probes: the row map is free and warning-free
        return None
    arrs = []
    for c in cols:
        a = np.asarray(c.values)
        if a.dtype.kind not in "fiub" or a.ndim != 1:
            return None
        if c.mask is not None and not np.asarray(c.mask).all():
            return None
        # mirror the row map's value types exactly: ``.item()`` hands the
        # lambda python floats (f64) / ints, so compute in f64/int64 — a
        # float32 sweep would round transcendentals differently
        arrs.append(a.astype(np.float64) if a.dtype.kind in "fb"
                    else a.astype(np.int64))
    try:
        out = transform_fn(*arrs)
    except Exception:
        return None
    if not isinstance(out, np.ndarray) or out.shape != (n,) \
            or out.dtype.kind not in "fiub":
        return None
    missing = np.isnan(out) if out.dtype.kind == "f" else np.zeros(n, bool)
    mask = ~missing
    if kind == "real":
        vals = np.where(missing, 0.0, out).astype(np.float32)
    elif kind == "binary":
        vals = np.where(missing, False,
                        out != 0).astype(np.float32)
    else:  # integral → host int64 (reference Long semantics)
        vals = np.where(missing, 0, out).astype(np.int64)
    return Column(output_type, vals, mask)


class _LambdaTransformer(Transformer):
    """Shared machinery: a value-level ``transform_fn`` over plain python values
    (None == missing) plus an optional ``columnar_fn`` over Columns. Without a
    columnar_fn the transform tries a vectorized numpy sweep
    (:func:`_vectorized_value_transform`) and only then falls back to a
    host-side row map — which remains exactly where it belongs: string-ish
    object columns and lambdas that branch per value."""

    def __init__(self, operation_name: str,
                 transform_fn: Callable[..., Any],
                 output_type: Type[FeatureType],
                 columnar_fn: Optional[Callable[..., Column]] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name, uid)
        self.transform_fn = transform_fn
        self.output_type = output_type
        self.columnar_fn = columnar_fn

    def transform_column(self, table: FeatureTable) -> Column:
        cols = [table[f.name] for f in self.input_features]
        if self.columnar_fn is not None:
            return self.columnar_fn(*cols)
        out = _vectorized_value_transform(self.transform_fn,
                                          self.output_type, cols)
        if out is not None:
            return out
        vals = [self.transform_fn(*args) for args in _iter_cell_values(cols)]
        return Column.of_values(self.output_type, vals)


class UnaryTransformer(_LambdaTransformer):
    """fn: I → O (reference base/unary/UnaryTransformer.scala)."""

    def __init__(self, operation_name, transform_fn, output_type,
                 input_type: Optional[Type[FeatureType]] = None, **kw):
        super().__init__(operation_name, transform_fn, output_type, **kw)
        self.input_types = (input_type,)


class BinaryTransformer(_LambdaTransformer):
    """fn: (I1, I2) → O (reference base/binary/BinaryTransformer.scala)."""

    def __init__(self, operation_name, transform_fn, output_type,
                 input_types: Tuple = (None, None), **kw):
        super().__init__(operation_name, transform_fn, output_type, **kw)
        self.input_types = tuple(input_types)


class TernaryTransformer(_LambdaTransformer):
    def __init__(self, operation_name, transform_fn, output_type,
                 input_types: Tuple = (None, None, None), **kw):
        super().__init__(operation_name, transform_fn, output_type, **kw)
        self.input_types = tuple(input_types)


class QuaternaryTransformer(_LambdaTransformer):
    def __init__(self, operation_name, transform_fn, output_type,
                 input_types: Tuple = (None, None, None, None), **kw):
        super().__init__(operation_name, transform_fn, output_type, **kw)
        self.input_types = tuple(input_types)


class SequenceTransformer(_LambdaTransformer):
    """Variadic homogeneous inputs → one output (reference
    base/sequence/SequenceTransformer.scala). transform_fn receives a list of
    values; columnar_fn receives the list of Columns."""

    def __init__(self, operation_name, transform_fn, output_type, **kw):
        super().__init__(operation_name, transform_fn, output_type, **kw)

    def _check_input_length(self, features):
        if len(features) < 1:
            raise ValueError(f"{type(self).__name__} needs at least one input")

    def transform_row(self, row: Dict[str, Any]) -> Any:
        if self.transform_fn is not None:
            vals = [row.get(f.name) for f in self.input_features]
            return self.transform_fn(vals)
        # columnar-only stages (vectorizers): run the columnar path on 1 row
        one = FeatureTable(
            {f.name: Column.of_values(f.feature_type, [row.get(f.name)])
             for f in self.input_features}, 1)
        out = self.transform_column(one)
        if out.mask is not None and not bool(np.asarray(out.mask)[0]):
            return None
        v = np.asarray(out.values)[0]
        return v.tolist() if isinstance(v, np.ndarray) else (
            v.item() if isinstance(v, np.generic) else v)

    def transform_column(self, table: FeatureTable) -> Column:
        cols = [table[f.name] for f in self.input_features]
        if self.columnar_fn is not None:
            return self.columnar_fn(cols)
        vals = [self.transform_fn(list(args)) for args in _iter_cell_values(cols)]
        return Column.of_values(self.output_type, vals)


class BinarySequenceTransformer(SequenceTransformer):
    """One distinguished input + variadic homogeneous rest (reference
    base/sequence/BinarySequenceTransformer.scala): transform_fn receives
    (head_value, [rest_values])."""

    def _check_input_length(self, features):
        if len(features) < 2:
            raise ValueError(
                f"{type(self).__name__} needs a head input plus at least one "
                f"sequence input")


class _BinarySequenceEstimatorMixin:
    """fit_fn receives (head_column, [rest_columns]) (reference
    base/sequence/BinarySequenceEstimator.scala)."""

    def fit(self, table):
        cols = [table[f.name] for f in self.input_features]
        state = self.fit_fn(cols[0], cols[1:])
        model = self.make_model(state)
        return self._finalize_model(model)


class _LambdaEstimator(Estimator):
    """Estimator from a fit function: fit_fn(columns...) → transform lambdas."""

    def __init__(self, operation_name: str,
                 fit_fn: Callable[..., Dict[str, Any]],
                 output_type: Type[FeatureType],
                 make_model: Callable[[Dict[str, Any]], Transformer],
                 uid: Optional[str] = None):
        super().__init__(operation_name, uid)
        self.fit_fn = fit_fn
        self.output_type = output_type
        self.make_model = make_model

    def fit(self, table: FeatureTable) -> Transformer:
        cols = [table[f.name] for f in self.input_features]
        state = self.fit_fn(*cols)
        model = self.make_model(state)
        return self._finalize_model(model)


class UnaryEstimator(_LambdaEstimator):
    def __init__(self, operation_name, fit_fn, output_type, make_model,
                 input_type: Optional[Type[FeatureType]] = None, **kw):
        super().__init__(operation_name, fit_fn, output_type, make_model, **kw)
        self.input_types = (input_type,)


class BinaryEstimator(_LambdaEstimator):
    def __init__(self, operation_name, fit_fn, output_type, make_model,
                 input_types: Tuple = (None, None), **kw):
        super().__init__(operation_name, fit_fn, output_type, make_model, **kw)
        self.input_types = tuple(input_types)


class TernaryEstimator(_LambdaEstimator):
    """(reference base/ternary/TernaryEstimator.scala)."""

    def __init__(self, operation_name, fit_fn, output_type, make_model,
                 input_types: Tuple = (None, None, None), **kw):
        super().__init__(operation_name, fit_fn, output_type, make_model, **kw)
        self.input_types = tuple(input_types)


class QuaternaryEstimator(_LambdaEstimator):
    """(reference base/quaternary/QuaternaryEstimator.scala)."""

    def __init__(self, operation_name, fit_fn, output_type, make_model,
                 input_types: Tuple = (None, None, None, None), **kw):
        super().__init__(operation_name, fit_fn, output_type, make_model, **kw)
        self.input_types = tuple(input_types)


class SequenceEstimator(_LambdaEstimator):
    """Variadic homogeneous-input estimator (reference
    base/sequence/SequenceEstimator.scala:57) — base of all multi-feature
    vectorizers."""

    def _check_input_length(self, features):
        if len(features) < 1:
            raise ValueError(f"{type(self).__name__} needs at least one input")

    def fit(self, table: FeatureTable) -> Transformer:
        cols = [table[f.name] for f in self.input_features]
        state = self.fit_fn(cols)
        model = self.make_model(state)
        return self._finalize_model(model)

class BinarySequenceEstimator(_BinarySequenceEstimatorMixin, SequenceEstimator):
    """(reference base/sequence/BinarySequenceEstimator.scala)."""

    def _check_input_length(self, features):
        if len(features) < 2:
            raise ValueError(
                f"{type(self).__name__} needs a head input plus at least one "
                f"sequence input")

