"""``op gen`` / ``op trace`` — project generator + trace capture.

Mirrors the reference CLI (reference: cli/src/main/scala/com/salesforce/op/cli/
— ``op gen`` parses an Avro schema (SchemaSource.scala, AvroField.scala) or
infers one from CSV, asks about the problem kind and field roles (answers can
come from a file, CommandParser.scala:98-101), and renders a runnable project
from the ``templates/simple`` tree, cli/README.md:34-57). Here: take the
schema from an Avro ``.avsc`` (--schema) or infer it from the data, apply
--answers overrides, classify the problem from the response column, and emit
a runnable python project (app.py + README + test) wired to this framework.

Usage::

    python -m transmogrifai_tpu.cli gen --input data.csv --response y \
        --output my_project --name MyApp [--id-field id]
    python -m transmogrifai_tpu.cli gen --input data.avro \
        --schema schema.avsc --response survived --output proj \
        [--answers answers.txt]

Answers file (the reference's non-interactive answers mechanism): one
``key=value`` per line —

    problem=binary                 # binary | multiclass | regression
    type.<field>=PickList          # override a field's inferred FeatureType
    role.<field>=drop              # predictor (default) | id | drop

``trace`` (docs/observability.md) trains an example dataset with the
observability subsystem force-enabled and writes the full telemetry bundle
to a directory::

    python -m transmogrifai_tpu.cli trace --output ./trace_out \
        [--dataset synthetic|iris] [--rows 600] [--seed 42]

    trace_out/trace.json     # Chrome trace-event JSON (chrome://tracing,
                             # https://ui.perfetto.dev)
    trace_out/spans.jsonl    # one JSON object per span (jq/pandas)
    trace_out/metrics.prom   # Prometheus text exposition
    trace_out/summary.json   # summary()["observability"] aggregates
"""
from __future__ import annotations

import argparse
import json
import os
from typing import Any, Dict, List, Optional, Tuple


def infer_schema(df, response: str, id_field: Optional[str]
                 ) -> Tuple[str, List[Tuple[str, str]]]:
    """→ (problem kind, [(column, FeatureType name)]) (the analog of the
    reference's SchemaSource/AvroField role inference)."""
    import pandas as pd
    fields: List[Tuple[str, str]] = []
    y = df[response].dropna()
    # any numeric response with many distinct values is a regression target —
    # integer-coded quantities (prices, counts) included, not just floats
    if pd.api.types.is_numeric_dtype(y.dtype) and y.nunique() > 20:
        problem = "regression"
    elif y.nunique() <= 2:
        problem = "binary"
    else:
        problem = "multiclass"
    for col in df.columns:
        if col == response or col == id_field:
            continue
        s = df[col]
        if pd.api.types.is_bool_dtype(s.dtype):
            ft = "Binary"
        elif pd.api.types.is_integer_dtype(s.dtype):
            ft = "Integral"
        elif pd.api.types.is_float_dtype(s.dtype):
            ft = "Real"
        elif pd.api.types.is_datetime64_any_dtype(s.dtype):
            ft = "DateTime"
        else:
            nun = s.nunique(dropna=True)
            ft = "PickList" if nun <= max(30, len(s) // 20) else "Text"
        fields.append((col, ft))
    return problem, fields


#: Avro primitive -> FeatureType (reference AvroField.scala:89-126; enums
#: pivot as PickList, nullable unions unwrap — typeOfNullable :140-146)
_AVRO_TYPES = {"int": "Integral", "long": "Integral", "boolean": "Binary",
               "float": "Real", "double": "Real", "string": "Text"}


def avro_schema_fields(schema_path: str) -> List[Tuple[str, str]]:
    """Parse an Avro record schema (.avsc) into [(field, FeatureType)]
    (the analog of the reference's SchemaSource.AvroSchemaFromFile)."""
    with open(schema_path) as fh:
        schema = json.load(fh)
    if schema.get("type") != "record":
        raise SystemExit(f"{schema_path}: top-level avro type must be "
                         f"'record', got {schema.get('type')!r}")
    out: List[Tuple[str, str]] = []
    for f in schema.get("fields", []):
        t = f["type"]
        if isinstance(t, list):  # nullable union: unwrap the non-null arm
            arms = [a for a in t if a != "null"]
            if len(arms) != 1:
                raise SystemExit(
                    f"{schema_path}: field {f['name']!r} has a multi-type "
                    f"union {t} — only nullable two-arm unions are supported")
            t = arms[0]
        if isinstance(t, dict):
            if t.get("type") == "enum":
                out.append((f["name"], "PickList"))
                continue
            t = t.get("type")
        ft = _AVRO_TYPES.get(t)
        if ft is None:
            raise SystemExit(
                f"{schema_path}: unsupported avro type {t!r} for field "
                f"{f['name']!r} (supported: {sorted(_AVRO_TYPES)}, enum)")
        out.append((f["name"], ft))
    return out


def parse_answers(path: str) -> Dict[str, str]:
    """key=value answers file (reference answers mechanism,
    CommandParser.scala:98-101)."""
    out: Dict[str, str] = {}
    with open(path) as fh:
        for ln, line in enumerate(fh, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                raise SystemExit(f"{path}:{ln}: expected key=value, "
                                 f"got {line!r}")
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def _apply_answers(problem: str, fields: List[Tuple[str, str]],
                   answers: Dict[str, str],
                   reserved: Tuple[Optional[str], ...] = ()
                   ) -> Tuple[str, List[Tuple[str, str]]]:
    problem = answers.get("problem", problem)
    if problem not in ("binary", "multiclass", "regression"):
        raise SystemExit(f"answers: problem must be binary|multiclass|"
                         f"regression, got {problem!r}")
    # reject typos up front: unknown field names and unknown feature types
    # would otherwise surface only when the GENERATED app runs
    from .types import FEATURE_TYPES
    known = {c for c, _ in fields}
    # answers may (redundantly) mention the response/id columns the command
    # line already assigned — but only with CONSISTENT roles; a
    # contradicting role would otherwise be silently dropped
    response_name = reserved[0] if reserved else None
    id_name = reserved[1] if len(reserved) > 1 else None
    reserved_names = {r for r in reserved if r}
    for k, v in answers.items():
        if k.startswith(("role.", "type.")):
            fld = k.split(".", 1)[1]
            if fld in reserved_names:
                if k.startswith("role."):
                    want = "response" if fld == response_name else "id"
                    if v != want:
                        raise SystemExit(
                            f"answers: {k}={v!r} contradicts the command "
                            f"line, which assigned {fld!r} as the {want}")
                continue
            if fld not in known:
                raise SystemExit(
                    f"answers: {k} refers to unknown field {fld!r} "
                    f"(fields: {sorted(known)})")
        if k.startswith("type.") and v not in FEATURE_TYPES:
            raise SystemExit(f"answers: {k}={v!r} is not a feature type")
        if not k.startswith(("role.", "type.")) and k != "problem":
            raise SystemExit(f"answers: unknown key {k!r}")
    out: List[Tuple[str, str]] = []
    for col, ft in fields:
        role = answers.get(f"role.{col}", "predictor")
        if role in ("drop", "id"):
            continue
        if role != "predictor":
            raise SystemExit(f"answers: role.{col} must be "
                             f"predictor|id|drop, got {role!r}")
        out.append((col, answers.get(f"type.{col}", ft)))
    return problem, out


_APP_TEMPLATE = '''\
"""{name} — generated by `python -m transmogrifai_tpu.cli gen`.

Train:   python app.py --run-type train --model-location ./model
Score:   python app.py --run-type score --model-location ./model \\
                       --write-location ./scores.parquet
"""
import pandas as pd

from transmogrifai_tpu import FeatureBuilder, transmogrify
from transmogrifai_tpu.impl.selector.factories import {selector}
from transmogrifai_tpu.readers.readers import DataReaders
from transmogrifai_tpu.runner import OpApp, OpWorkflowRunner
from transmogrifai_tpu.workflow import OpWorkflow

DATA_PATH = {data_path!r}

# -- raw features (inferred from the CSV schema; edit types as needed) -------
{response_lines}
predictors = [
{predictor_lines}
]

# -- pipeline -----------------------------------------------------------------
features = transmogrify(predictors)
checked = features.sanity_check(response)
prediction = ({selector}
              .with_cross_validation(seed=42)
              .set_input(response, checked).get_output())

workflow = OpWorkflow().set_result_features(prediction)
runner = OpWorkflowRunner(
    workflow,
    train_reader={reader_expr},
)

if __name__ == "__main__":
    result = OpApp(runner).main()
    if result.model is not None:
        print(result.model.summary_pretty())
'''

_README_TEMPLATE = """\
# {name}

Generated by `python -m transmogrifai_tpu.cli gen` from `{data_path}`.

- problem kind: **{problem}**
- response: `{response}`
- predictors: {n_predictors} columns

```bash
python app.py --run-type train --model-location ./model
python app.py --run-type score --model-location ./model --write-location ./scores.parquet
```
"""

_TEST_TEMPLATE = '''\
"""Smoke test for the generated app."""
import subprocess
import sys


def test_app_trains(tmp_path):
    out = subprocess.run(
        [sys.executable, "app.py", "--run-type", "train",
         "--model-location", str(tmp_path / "model")],
        capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
'''


def generate(input_csv: str, response: str, output: str, name: str,
             id_field: Optional[str] = None,
             schema: Optional[str] = None,
             answers: Optional[str] = None) -> Dict[str, str]:
    import pandas as pd
    is_avro = input_csv.endswith(".avro")
    if is_avro:
        from .utils.avro import read_avro
        df = pd.DataFrame(list(read_avro(input_csv)))
        reader_expr = "DataReaders.Simple.avro(DATA_PATH)"
    else:
        df = pd.read_csv(input_csv)
        reader_expr = "DataReaders.Simple.csv_auto(DATA_PATH)"
    if response not in df.columns:
        raise SystemExit(f"response column {response!r} not in {input_csv} "
                         f"(columns: {list(df.columns)})")
    problem, fields = infer_schema(df, response, id_field)
    if schema is not None:
        declared = avro_schema_fields(schema)
        names = {c for c, _ in declared}
        if response not in names:
            raise SystemExit(f"response {response!r} not in schema {schema}")
        fields = [(c, ft) for c, ft in declared
                  if c != response and c != id_field]
    if answers is not None:
        problem, fields = _apply_answers(problem, fields,
                                         parse_answers(answers),
                                         reserved=(response, id_field))
    selector = {
        "binary": "BinaryClassificationModelSelector",
        "multiclass": "MultiClassificationModelSelector",
        "regression": "RegressionModelSelector",
    }[problem]
    predictor_lines = "\n".join(
        f"    FeatureBuilder.{ft}({col!r}).extract_field().as_predictor(),"
        for col, ft in fields)
    # classification labels must reach the selector as 0..K-1: the balancers
    # and metrics assume it (the reference CLI asks about the response field
    # role and indexes string labels). Numeric labels already coded 0..K-1
    # pass through; anything else — strings, or numeric codings like {1,2} —
    # indexes into the sorted class list.
    y = df[response].dropna()
    numeric = pd.api.types.is_numeric_dtype(y.dtype)
    if problem == "regression" or (
            numeric and sorted(float(v) for v in y.unique())
            == [float(i) for i in range(y.nunique())]):
        response_lines = (f"response = FeatureBuilder.RealNN({response!r})"
                          f".extract_field().as_response()")
    else:
        labels = sorted(str(v) for v in y.unique())
        response_lines = (
            f"RESPONSE_LABELS = {labels!r}\n"
            f"response = FeatureBuilder.RealNN({response!r}).extract(\n"
            f"    lambda r: float(RESPONSE_LABELS.index(str(r.get({response!r}))))\n"
            f"    if str(r.get({response!r})) in RESPONSE_LABELS else None"
            f").as_response()")
    app = _APP_TEMPLATE.format(
        name=name, selector=selector, data_path=os.path.abspath(input_csv),
        response_lines=response_lines, predictor_lines=predictor_lines,
        reader_expr=reader_expr)
    readme = _README_TEMPLATE.format(
        name=name, data_path=input_csv, problem=problem, response=response,
        n_predictors=len(fields))
    os.makedirs(output, exist_ok=True)
    files = {"app.py": app, "README.md": readme, "test_app.py": _TEST_TEMPLATE}
    for fname, content in files.items():
        with open(os.path.join(output, fname), "w") as fh:
            fh.write(content)
    return files


def _trace_workflow(dataset: str, rows: int, seed: int):
    """→ (workflow, scoring rows) for the trace capture run. ``synthetic``
    needs no data files; ``iris`` uses the bundled helloworld-parity
    example (requires its dataset on disk)."""
    import numpy as np
    import pandas as pd

    from .features import FeatureBuilder
    from .impl.feature.transmogrifier import transmogrify
    from .impl.selector.factories import BinaryClassificationModelSelector
    from .workflow import OpWorkflow

    if dataset == "iris":
        from .examples.iris import build_workflow
        wf, _label, _pred = build_workflow(seed=seed)
        return wf, None
    rng = np.random.RandomState(seed)
    x1, x2, x3 = rng.randn(rows), rng.randn(rows), rng.randn(rows)
    y = ((x1 + 0.5 * x2 - 0.25 * x3) > 0).astype(float)
    df = pd.DataFrame({"x1": x1, "x2": x2, "x3": x3, "y": y})
    label = FeatureBuilder.RealNN("y").extract_field().as_response()
    preds = [FeatureBuilder.Real(c).extract_field().as_predictor()
             for c in ("x1", "x2", "x3")]
    checked = transmogrify(preds).sanity_check(label)
    # a small two-family sweep: enough for per-family spans + a winner
    # refit without the full default grids' runtime
    models = [("OpLogisticRegression",
               [{"regParam": r, "elasticNetParam": 0.0}
                for r in (0.01, 0.1)]),
              ("OpLinearSVC", [{"regParam": 0.01}])]
    pred = (BinaryClassificationModelSelector
            .with_cross_validation(seed=seed, models=models)
            .set_input(label, checked).get_output())
    wf = OpWorkflow().set_input_dataset(df).set_result_features(pred)
    score_rows = [dict(x1=float(a), x2=float(b), x3=float(c))
                  for a, b, c in zip(x1[:32], x2[:32], x3[:32])]
    return wf, score_rows


def run_trace(output: str, dataset: str = "synthetic", rows: int = 600,
              seed: int = 42) -> Dict[str, str]:
    """Train under forced tracing+metrics and write the telemetry bundle
    (trace.json / spans.jsonl / metrics.prom / summary.json) to ``output``."""
    import json as _json

    from . import observability
    from .observability import export as obs_export
    from .observability import metrics as obs_metrics
    from .observability import trace as obs_trace

    obs_trace.enable_tracing(True)
    obs_metrics.enable_metrics(True)
    try:
        wf, score_rows = _trace_workflow(dataset, rows, seed)
        model = wf.train()
        if score_rows:
            # drive the serving path too, so the latency histograms and
            # micro-batch spans land in the bundle
            from .local import micro_batch_score_function
            scorer = micro_batch_score_function(model)
            scorer(score_rows)
        os.makedirs(output, exist_ok=True)
        files = {
            "trace.json": obs_export.write_chrome_trace(
                os.path.join(output, "trace.json")),
            "spans.jsonl": obs_export.write_jsonl(
                os.path.join(output, "spans.jsonl")),
            "metrics.prom": obs_export.write_prometheus(
                os.path.join(output, "metrics.prom")),
        }
        summary = observability.summarize()
        with open(os.path.join(output, "summary.json"), "w") as fh:
            _json.dump(summary, fh, indent=2, default=str)
        files["summary.json"] = os.path.join(output, "summary.json")
        print(f"wrote {', '.join(sorted(files))} to {output}/ "
              f"({summary['spanCount']} spans; open trace.json in "
              f"chrome://tracing or https://ui.perfetto.dev)")
        return files
    finally:
        obs_trace.enable_tracing(None)
        obs_metrics.enable_metrics(None)


def run_serve(model_path: str, seconds: float = 5.0, rps: float = 0.0,
              deadline_ms: Optional[float] = None, max_batch: int = 256,
              queue_max: int = 1024, name: str = "model",
              output: Optional[str] = None, seed: int = 42,
              listen: Optional[str] = None) -> Dict[str, Any]:
    """``op serve`` (docs/serving.md): load a saved model into the serving
    registry (warm plan caches from its MANIFEST), drive the open-loop
    synthetic load generator for ``seconds``, print the SLO / shed /
    breaker summary, and optionally write the telemetry bundle.

    ``rps=0`` auto-calibrates: a short saturating run measures what the
    runtime sustains in this process, and the measured load runs at half
    of it — sustained throughput with an SLO-shaped tail, not a shed
    report (pass an explicit --rps to study overload).

    ``--listen host:port`` serves over the network edge instead
    (docs/serving.md "Network edge"): the runtime sits behind a real
    asyncio listener and the socket load generator drives both wire
    framings (HTTP/JSON + binary) through it — port 0 picks a free
    port. Exits non-zero on any lost future or a broken accounting
    identity, same contract as ``op fleet``."""
    import json as _json
    import time as _time

    from .observability import export as obs_export
    from .observability import metrics as obs_metrics
    from .observability import trace as obs_trace
    from .serving import ModelRegistry, ServeConfig
    from .serving.loadgen import run_open_loop, synthetic_rows

    obs_trace.enable_tracing(True)
    obs_metrics.enable_metrics(True)
    try:
        cfg = ServeConfig.from_env()
        cfg.max_batch = max_batch
        cfg.max_queue = queue_max
        with ModelRegistry(cfg) as reg:
            rt = reg.load(name, model_path)
            rows = synthetic_rows(rt.model, 512, seed=seed)
            if rps <= 0:
                from .local import micro_batch_score_function
                mb = micro_batch_score_function(rt.model)
                batch = rows[:max_batch]
                mb(batch)  # compile warmup beyond the registry warm
                t0 = _time.perf_counter()
                for _ in range(3):
                    mb(batch)
                cap = 3 * len(batch) / (_time.perf_counter() - t0)
                cal = run_open_loop(rt, rows, min(1.0, seconds), cap)
                rps = max(10.0, 0.5 * cal["rowsPerSec"])
            edge_addr = None
            if listen:
                from .serving.loadgen import run_wire_open_loop
                from .serving.netedge import NetEdge
                lhost, _, lport = listen.rpartition(":")
                with NetEdge(rt, host=lhost or "127.0.0.1",
                             port=int(lport or 0), name=name) as edge:
                    edge_addr = "%s:%d" % edge.address
                    print(f"serving '{name}' on {edge_addr} "
                          f"(HTTP/JSON + binary framing)")
                    report = run_wire_open_loop(
                        *edge.address, rows, seconds, rps,
                        deadline_ms=deadline_ms, batch_rows=16)
            else:
                report = run_open_loop(rt, rows, seconds, rps,
                                       deadline_ms=deadline_ms)
            health = reg.health()
            # drift report (docs/serving.md): per-feature JS/fill vs the
            # training baseline + the verdict history. The monitor folds
            # on a row cadence; force a final verdict pass so a short run
            # still reports fresh numbers (None when the model dir
            # predates drift baselines or TG_DRIFT=0).
            drift_report = None
            if rt.drift_monitor is not None:
                try:
                    rt.drift_monitor.run_verdict()
                except Exception:
                    pass  # report whatever the last pass computed
                drift_report = rt.drift_monitor.report()
        summary = {"model": model_path, "rpsOffered": round(rps, 1),
                   "listen": edge_addr,
                   "load": report, "health": health["models"][name],
                   "drift": drift_report}
        print(_json.dumps(summary, indent=2, default=str))
        if output:
            os.makedirs(output, exist_ok=True)
            obs_export.write_chrome_trace(os.path.join(output, "trace.json"))
            obs_export.write_jsonl(os.path.join(output, "spans.jsonl"))
            obs_export.write_prometheus(
                os.path.join(output, "metrics.prom"))
            with open(os.path.join(output, "serve_summary.json"), "w") as fh:
                _json.dump(summary, fh, indent=2, default=str)
            print(f"wrote trace.json, spans.jsonl, metrics.prom, "
                  f"serve_summary.json to {output}/")
        if listen and (report["lost"] or report["failed"]
                       or not report["accountingOk"]):
            print(f"WIRE SOAK FAILED: lost={report['lost']} "
                  f"failed={report['failed']} "
                  f"accountingOk={report['accountingOk']}")
            raise SystemExit(1)
        return summary
    finally:
        obs_trace.enable_tracing(None)
        obs_metrics.enable_metrics(None)


def run_slo(model_path: str, seconds: float = 5.0, rps: float = 0.0,
            availability: Optional[float] = None,
            p99_ms: Optional[float] = None,
            window_s: Optional[float] = None,
            tenants: Optional[str] = None,
            intervals: int = 5, deadline_ms: Optional[float] = None,
            name: str = "model", output: Optional[str] = None,
            seed: int = 42) -> Dict[str, Any]:
    """``op slo`` (docs/observability.md "SLOs, budgets & burn rates"):
    load a saved model, register an SLO spec for it, drive the open-loop
    load generator for ``seconds`` in ``intervals`` slices, and print a
    scale-hint/budget-burn timeline plus the final per-objective
    verdicts. Exits non-zero when a page-severity burn-rate alert fired
    during the run — the CI-able "this model cannot hold its SLO at this
    load" check.

    ``--window-s`` scales the whole 30-day budget window down so a
    seconds-long run exercises the full alert ladder (default 3600);
    ``--tenants "a:3,b:1"`` adds a weighted multi-tenant traffic mix
    with per-tenant budgets."""
    import json as _json
    import sys as _sys

    from .observability import export as obs_export
    from .observability import slo as _slo
    from .observability import timeseries as _timeseries
    from .serving import ModelRegistry, ServeConfig
    from .serving.loadgen import run_open_loop, synthetic_rows

    window = float(window_s) if window_s else 3600.0
    # sample fast enough that the scaled alert windows (page long =
    # window/720) hold several samples during a seconds-long run
    every = max(min(seconds / max(intervals * 2, 1), 1.0), 0.05)
    saved_env = {k: os.environ.get(k)
                 for k in ("TG_SAMPLE_EVERY_S", "TG_SLO_WINDOW_S")}
    os.environ["TG_SAMPLE_EVERY_S"] = str(every)
    os.environ["TG_SLO_WINDOW_S"] = str(window)
    tenant_mix = None
    if tenants:
        tenant_mix = []
        for part in tenants.split(","):
            t, _, w = part.strip().partition(":")
            tenant_mix.append((t, float(w) if w else 1.0))
    spec_kw: Dict[str, Any] = {"window_s": window}
    if availability is not None:
        spec_kw["availability"] = availability
    if p99_ms is not None:
        spec_kw["latency_p99_ms"] = p99_ms
    _slo.register(_slo.SLOSpec(model=name, **spec_kw))
    if tenant_mix:
        for t, _w in tenant_mix:
            _slo.register(_slo.SLOSpec(model=name, tenant=t, **spec_kw))
    timeline: List[Dict[str, Any]] = []
    try:
        with ModelRegistry(ServeConfig.from_env()) as reg:
            rt = reg.load(name, model_path)
            rows = synthetic_rows(rt.model, 512, seed=seed)
            if rps <= 0:
                cal = run_open_loop(rt, rows, min(1.0, seconds),
                                    200.0, tenants=tenant_mix)
                rps = max(10.0, 0.5 * max(cal["rowsPerSec"], 20.0))
            slice_s = seconds / max(intervals, 1)
            agg = {"offered": 0, "completed": 0, "shedOverload": 0,
                   "shedDeadline": 0, "failed": 0, "lost": 0}
            for i in range(max(intervals, 1)):
                rep = run_open_loop(rt, rows, slice_s, rps,
                                    deadline_ms=deadline_ms,
                                    tenants=tenant_mix, tenant_seed=i)
                for k in agg:
                    agg[k] += rep.get(k, 0)
                if rt.sampler is not None:
                    rt.sampler.tick()
                rt._evaluate_slo(rt.sampler, None)
                snap = rt.slo_snapshot() or {}
                model_snap = snap.get(name, {})
                avail = (model_snap.get("objectives", {})
                         .get("availability", {}))
                hint = _slo.scale_hint(rt, snap)
                timeline.append({
                    "t": round((i + 1) * slice_s, 2),
                    "rowsPerSec": rep["rowsPerSec"],
                    "p99Ms": rep["p99Ms"],
                    "burnPageLong": round((avail.get("burn", {})
                                           .get("page", {})
                                           .get("long", 0.0)), 3),
                    "budgetRemaining": round(
                        avail.get("budgetRemaining", 1.0), 4),
                    "verdict": model_snap.get("worst", "n/a"),
                    "activeAlerts": model_snap.get("activeAlerts", []),
                    "scaleHint": hint["hint"],
                })
                print(_json.dumps({"slice": timeline[-1]}, default=str),
                      flush=True)
            final = rt.slo_snapshot()
            health = reg.health()
            fired = {sev: sum(t.fired.get(sev, 0)
                              for t in rt.slo_trackers)
                     for sev in _slo.SEVERITIES}
            summary = {
                "model": model_path, "rpsOffered": round(rps, 1),
                "windowS": window, "load": agg, "timeline": timeline,
                "slo": final,
                "scaleHint": health["models"][name]["scaleHint"],
                "scaleHints": health["scaleHints"],
                "tenants": health["models"][name].get("tenants"),
                "alertsFired": fired,
            }
            if output:
                os.makedirs(output, exist_ok=True)
                obs_export.write_prometheus(
                    os.path.join(output, "metrics.prom"),
                    rt.metrics)
                with open(os.path.join(output, "slo_summary.json"),
                          "w") as fh:
                    _json.dump(summary, fh, indent=2, default=str)
        print(_json.dumps(summary, indent=2, default=str))
        if fired.get("page", 0) > 0:
            print(f"SLO: page-severity burn-rate alert fired "
                  f"{fired['page']}x — budget cannot hold at this load",
                  flush=True)
            _sys.exit(1)
        return summary
    finally:
        _slo.reset()
        _timeseries.idle_join()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run_campaign(schedules: int = 0, seed: Optional[int] = None,
                 scenario: Optional[str] = None,
                 faults_json: Optional[str] = None,
                 output: Optional[str] = None,
                 no_minimize: bool = False) -> Dict[str, Any]:
    """``op campaign`` (docs/robustness.md "Chaos campaigns"): run a
    seeded chaos campaign — or re-run ONE schedule as a reproducer.

    Repro mode (the one-command repro a failing campaign emits): pass
    ``--faults '<json>'``, or set ``TG_CHAOS=1 TG_FAULTS='<json>'`` in the
    environment, together with ``--scenario``; the single schedule runs
    and the process exits non-zero when any invariant oracle fires.
    Campaign mode otherwise: ``--schedules`` randomized schedules
    (coverage singletons first), violations delta-debugged to minimal
    reproducers, report JSON on stdout (and ``campaign_report.json``
    under ``--output``)."""
    import json as _json
    import sys as _sys

    from .robustness.campaign import ChaosCampaign

    repro_blob = faults_json or (
        os.environ.get("TG_FAULTS")
        if os.environ.get("TG_CHAOS") and scenario else None)
    if repro_blob and not scenario:
        raise SystemExit(
            "campaign repro mode needs --scenario naming the harness "
            "the TG_FAULTS schedule runs against")
    eng = ChaosCampaign(
        seed=seed,
        scenarios=None if (repro_blob or scenario is None) else [scenario])
    try:
        if repro_blob:
            result = eng.run_schedule(
                {"scenario": scenario, "faults": _json.loads(repro_blob)})
            print(_json.dumps(result, indent=2, default=str))
            if result["violations"]:
                _sys.exit(1)
            return result
        report = eng.run(count=schedules or None,
                         minimize=not no_minimize)
        doc = report.to_json()
        print(_json.dumps(doc, indent=2, default=str))
        if output:
            os.makedirs(output, exist_ok=True)
            path = os.path.join(output, "campaign_report.json")
            with open(path, "w") as fh:
                _json.dump(doc, fh, indent=2, default=str)
            print(f"wrote {path}")
        if doc["violations"]:
            _sys.exit(1)
        return doc
    finally:
        eng.close()


def run_fleet(model_path: str, replicas: int = 2, seconds: float = 5.0,
              rps: float = 0.0, deadline_ms: Optional[float] = None,
              max_batch: int = 256, queue_max: int = 1024,
              kill: bool = False, use_subprocess: bool = False,
              name: str = "model", output: Optional[str] = None,
              seed: int = 42, models: int = 1) -> Dict[str, Any]:
    """``op fleet`` (docs/serving.md "Replica fleet & front door"): start
    ``replicas`` worker replicas of a saved model behind a front door,
    drive the open-loop load generator for ``seconds``, and print the
    fleet report — per-replica routing distribution, failovers,
    ejections, scale events, sheds, and the SLO tail. ``--kill`` murders
    one replica mid-soak (the zero-lost-requests drill: the run must
    still account every request). ``--models N`` registers the saved
    dir under N model names with the placement layer enabled
    (docs/serving.md "Multi-model placement & paging") and drives an
    equal-weight model mix, so routing/paging/eviction are exercised;
    the report then carries the per-model breakdown and the placement
    snapshot. Exits non-zero on ANY lost request or broken
    accounting."""
    import json as _json
    import threading as _threading
    import time as _time

    from .observability import export as obs_export
    from .observability import metrics as obs_metrics
    from .observability import trace as obs_trace
    from .persistence import load_model
    from .serving import FleetConfig, FrontDoor, ServeConfig
    from .serving.loadgen import run_open_loop, synthetic_rows

    obs_trace.enable_tracing(True)
    obs_metrics.enable_metrics(True)
    try:
        cfg = ServeConfig.from_env()
        cfg.max_batch = max_batch
        cfg.max_queue = queue_max
        fc = FleetConfig.from_env()
        if use_subprocess:
            fc.subprocess = True
        fc.max_replicas = max(fc.max_replicas, replicas)
        model = load_model(model_path)
        rows = synthetic_rows(model, 512, seed=seed)
        n_models = max(1, int(models))
        model_map = ({name: model_path} if n_models == 1 else
                     {f"{name}{i}": model_path
                      for i in range(1, n_models + 1)})
        placement = None
        model_mix = None
        if n_models > 1:
            from .serving import PlaceConfig
            placement = PlaceConfig.from_env()
            if placement.max_warm <= 0 and placement.device_budget <= 0:
                # no env bound: keep one model cold so paging is real
                placement = PlaceConfig(
                    max_warm=n_models - 1,
                    device_budget=placement.device_budget,
                    pagein_timeout_s=placement.pagein_timeout_s,
                    protect_slo=placement.protect_slo)
            model_mix = [(m, 1.0) for m in sorted(model_map)]
        with FrontDoor(model_map, replicas=replicas, config=cfg,
                       fleet_config=fc, warm=True,
                       placement=placement) as fd:
            if rps <= 0:
                from .local import micro_batch_score_function
                mb = micro_batch_score_function(model)
                batch = rows[:max_batch]
                mb(batch)  # compile warmup beyond the replica warms
                t0 = _time.perf_counter()
                for _ in range(3):
                    mb(batch)
                cap = 3 * len(batch) / (_time.perf_counter() - t0)
                cal = run_open_loop(fd, rows, min(1.0, seconds), cap)
                rps = max(10.0, 0.5 * cal["rowsPerSec"])
            killer = None
            if kill:
                def _mid_soak_kill():
                    active = [rid for rid, r in sorted(
                        fd._replicas.items()) if r.state == "active"]
                    if active:
                        fd.kill_replica(active[0])
                killer = _threading.Timer(seconds / 2.0, _mid_soak_kill)
                killer.daemon = True
                killer.start()
            try:
                report = run_open_loop(fd, rows, seconds, rps,
                                       deadline_ms=deadline_ms,
                                       models=model_mix)
            finally:
                if killer is not None:
                    killer.cancel()
            health = fd.health()
        summary = {"model": model_path, "replicas": replicas,
                   "models": sorted(model_map),
                   "rpsOffered": round(rps, 1), "load": report,
                   "fleet": report.get("fleet"),
                   "placement": (report.get("fleet") or {}).get("placement"),
                   "perModel": report.get("models"),
                   "routing": report.get("replicas"),
                   "ready": health["ready"],
                   "replicaStates": {rid: r.get("state")
                                     for rid, r in
                                     health["replicas"].items()}}
        print(_json.dumps(summary, indent=2, default=str))
        if output:
            os.makedirs(output, exist_ok=True)
            obs_export.write_prometheus(
                os.path.join(output, "metrics.prom"))
            with open(os.path.join(output, "fleet_summary.json"),
                      "w") as fh:
                _json.dump(summary, fh, indent=2, default=str)
            print(f"wrote metrics.prom, fleet_summary.json to {output}/")
        if report["lost"] or report["failed"] or not report["accountingOk"]:
            print(f"FLEET SOAK FAILED: lost={report['lost']} "
                  f"failed={report['failed']} "
                  f"accountingOk={report['accountingOk']}")
            raise SystemExit(1)
        return summary
    finally:
        obs_trace.enable_tracing(None)
        obs_metrics.enable_metrics(None)


def run_programs(path: str, gc: bool = False,
                 as_json: bool = False) -> Dict[str, Any]:
    """``op programs <model-dir | store-dir>`` (docs/serving.md "AOT
    cold start & the program store"): list the AOT program store's
    entries (key, component, size, age, hit count), verify every blob
    against its recorded sha256/size, and optionally GC past the
    ``TG_AOT_STORE_MAX``/``TG_AOT_STORE_MAX_BYTES`` bounds. A model dir
    (has MANIFEST.json) is resolved to its ``programs/`` subdirectory
    and cross-checked against the manifest ``programs`` section. Exits
    non-zero when any entry is corrupt."""
    import json as _json
    import sys as _sys
    import time as _time

    from .manifest import MANIFEST_FILE, CheckpointManifest
    from .programstore import PROGRAMS_DIR, ProgramStore

    store_dir = path
    manifest_entries: Optional[Dict[str, Any]] = None
    plan_idents: List[str] = []
    if os.path.isfile(os.path.join(path, MANIFEST_FILE)):
        store_dir = os.path.join(path, PROGRAMS_DIR)
        from .persistence import FORMAT_VERSION
        m, err = CheckpointManifest.load(path, FORMAT_VERSION)
        if err is None and isinstance(m.programs.get("entries"), dict):
            manifest_entries = dict(m.programs["entries"])
            plan_idents = [str(x) for x in m.programs.get("planIdents", ())]
    store = ProgramStore(store_dir)
    entries = store.entries()
    problems = store.verify()
    removed = store.gc() if gc else []
    if gc:
        entries = store.entries()
    now = _time.time()
    rows = []
    for kid, meta in sorted(entries.items()):
        rows.append({
            "key": kid,
            "component": meta.get("component"),
            "bucket": meta.get("bucket"),
            "jaxlib": meta.get("jaxlib"),
            "deviceKind": meta.get("deviceKind"),
            "sizeBytes": meta.get("size"),
            "ageS": round(now - float(meta.get("createdUnix", now)), 1),
            "hits": meta.get("hits", 0),
            "identity": meta.get("identity"),
        })
    report = {
        "dir": store_dir,
        "entries": rows,
        "totalBytes": store.total_bytes(),
        "planIdents": plan_idents,
        "manifestEntries": (len(manifest_entries)
                            if manifest_entries is not None else None),
        "corrupt": problems,
        "removedByGc": removed,
    }
    if manifest_entries is not None:
        # entries the manifest records but the store no longer holds —
        # a lookup for these will miss (absent) and re-trace
        report["manifestOnly"] = sorted(set(manifest_entries) -
                                        set(entries))
    if as_json:
        print(_json.dumps(report, indent=2, default=str))
    else:
        print(f"== AOT program store: {store_dir}")
        print(f"   entries: {len(rows)}  total "
              f"{report['totalBytes']} bytes"
              + (f"  (manifest records {report['manifestEntries']})"
                 if report["manifestEntries"] is not None else ""))
        for r in rows:
            print(f"   {r['key']:<24} {r['component']:<13} "
                  f"bucket={r['bucket']:<6} {r['sizeBytes']:>8}B  "
                  f"age={r['ageS']:>8.1f}s  hits={r['hits']:<4} "
                  f"[{r['jaxlib']} {r['deviceKind']}]")
        for kid in report.get("manifestOnly", []):
            print(f"   ! manifest-only (blob gone): {kid}")
        if removed:
            print(f"   gc removed: {removed}")
        if problems:
            print("-- CORRUPT ENTRIES --")
            for p in problems:
                print(f"   ! {p}")
        print(f"== verdict: {'CORRUPT' if problems else 'ok'} ==")
    if problems:
        _sys.exit(1)
    return report


def _doctor_ms(ts_ns: Optional[float], anchor_ns: Optional[float]) -> str:
    if ts_ns is None:
        return "       ?"
    if anchor_ns is None:
        return f"{ts_ns / 1e6:12.1f}"
    return f"{(ts_ns - anchor_ns) / 1e6:+12.1f}"


def _doctor_event_line(e: Dict[str, Any], anchor_ns: Optional[float]) -> str:
    attrs = e.get("attrs") or {}
    body = " ".join(f"{k}={v}" for k, v in sorted(attrs.items())
                    if v is not None)
    corr = f" [{e['corr']}]" if e.get("corr") else ""
    return (f"  {_doctor_ms(e.get('tsNs'), anchor_ns)} ms  "
            f"{e.get('kind', '?'):<22}{corr}  {body}"[:200])


def run_doctor(bundle: str, as_json: bool = False,
               tail: int = 40) -> Dict[str, Any]:
    """``op doctor <bundle>`` (docs/observability.md "Flight recorder &
    post-mortems"): render a post-mortem bundle into a human-readable
    incident report — trigger, environment, the trigger correlation id's
    full timeline, the recent ring tail, top metrics, the compiles &
    memory block (cause-classified program builds + predicted/measured
    device-byte peaks, schema v2), and the FaultLog buckets. ``bundle``
    may be a bundle file or a directory (the newest
    bundle inside is used). Exits non-zero when the bundle fails schema
    validation."""
    import json as _json
    import sys as _sys

    from .observability import postmortem as _postmortem

    path = bundle
    if os.path.isdir(path):
        bundles = _postmortem.list_bundles(path)
        if not bundles:
            raise SystemExit(f"no post-mortem bundles under {path}")
        path = bundles[-1]
    doc = _postmortem.read_bundle(path)
    problems = _postmortem.validate_bundle(doc)
    if as_json:
        out = {"bundle": path, "problems": problems, "doc": doc}
        print(_json.dumps(out, indent=2, default=str))
        if problems:
            _sys.exit(1)
        return out

    trig = doc.get("trigger", {}) or {}
    anchor = trig.get("tsNs")
    print(f"== post-mortem: {path}")
    print(f"   trigger : {trig.get('kind')}  (pid {doc.get('pid')}, "
          f"unix {trig.get('unixTime')})")
    if trig.get("corr"):
        print(f"   corr    : {trig['corr']}")
    detail = trig.get("detail") or {}
    for k, v in sorted(detail.items()):
        print(f"   {k:<8}: {v}")
    env = doc.get("environment", {}) or {}
    devs = env.get("devices") or []
    print(f"   env     : jax {env.get('jax')} / jaxlib {env.get('jaxlib')} "
          f"/ {env.get('backend')} x{len(devs)} "
          f"/ python {env.get('python')}")
    if problems:
        print("-- SCHEMA PROBLEMS --")
        for p in problems:
            print(f"   ! {p}")
    corr_events = doc.get("correlated") or []
    if corr_events:
        print(f"-- correlated timeline ({trig.get('corr')}; "
              f"{len(corr_events)} events; ms relative to trigger) --")
        for e in corr_events:
            print(_doctor_event_line(e, anchor))
    ring = (doc.get("recorder") or {}).get("events") or []
    shown = ring[-max(1, tail):]
    print(f"-- ring tail ({len(shown)}/{len(ring)} events; dropped "
          f"{(doc.get('recorder') or {}).get('dropped', 0)}) --")
    for e in shown:
        print(_doctor_event_line(e, anchor))
    # top metrics: the biggest counter series from the trigger site's
    # registry (serve-local when the trigger carried one, else global)
    metrics = doc.get("metrics") or doc.get("globalMetrics") or {}
    flat: List[Any] = []
    for name, series in metrics.items():
        for key, v in series.items():
            if isinstance(v, dict):
                lat = {q: v.get(q) for q in ("p50", "p95", "p99")
                       if v.get(q) is not None}
                flat.append((v.get("count", 0), name, key,
                             f"count={v.get('count')} {lat}"))
                for ex in (v.get("exemplars") or [])[:3]:
                    flat.append((v.get("count", 0), name, key,
                                 f"slowest {ex.get('value'):.4f}s -> "
                                 f"{ex.get('exemplar')}"))
            else:
                flat.append((float(v), name, key, f"{v}"))
    flat.sort(key=lambda t: -t[0])
    if flat:
        print("-- top metrics --")
        for _rank, name, key, desc in flat[:12]:
            print(f"   {name}{{{key}}}: {desc}")
    # compiles & memory (bundle schema v2; docs/observability.md
    # "Compile & memory ledger") — which requests/runs paid a program
    # build, why, and what the device allocations looked like
    led = doc.get("ledger") or {}
    mem = doc.get("deviceMemory") or {}
    if led or mem:
        print(f"-- compiles & memory ({led.get('builds', 0)} builds) --")
        for sub, causes in sorted((led.get("counts") or {}).items()):
            body = " ".join(f"{c}={n}" for c, n in sorted(causes.items()))
            print(f"   compiles[{sub}]: {body}")
        for rec in (led.get("tail") or [])[-8:]:
            corr = f" [{rec['corr']}]" if rec.get("corr") else ""
            diff = rec.get("diff") or []
            why = f"  ({'; '.join(diff)})" if diff else ""
            print(f"   {rec.get('subsystem', '?'):<7} "
                  f"{rec.get('cause', '?'):<16}{corr}  "
                  f"{rec.get('identity', '?')} "
                  f"{rec.get('seconds', 0.0):.3f}s{why}"[:200])
        for sub, s in sorted((mem.get("subsystems") or {}).items()):
            meas = s.get("measuredPeakBytes")
            measured = (f"measuredPeak={meas}B" if meas is not None
                        else "measured n/a")
            print(f"   mem[{sub}]: dispatches={s.get('dispatches')} "
                  f"predictedPeak={s.get('predictedPeakBytes')}B "
                  f"{measured}")
    # fleet (replica front door; docs/serving.md "Replica fleet & front
    # door") — replica states, routing distribution, failover/ejection
    # accounting from the tg_fleet_* series the bundle snapshotted
    fleet_series = {n: s for n, s in metrics.items()
                    if n.startswith("tg_fleet_")}
    if fleet_series or trig.get("kind") == "replica_lost":
        print("-- fleet --")
        for fname, series in sorted(fleet_series.items()):
            for key, v in sorted(series.items()):
                if isinstance(v, dict):
                    v = f"count={v.get('count')}"
                print(f"   {fname}{{{key}}}: {v}")
    # placement (bundle schema v5; docs/serving.md "Multi-model
    # placement & paging") — which models were resident where, page-in
    # p99, evictions, blind admits, refusals: the "did this replica
    # hold the only warm copy?" context
    place_doc = doc.get("placement") or {}
    place_series = {n: s for n, s in metrics.items()
                    if n.startswith("tg_place_")}
    if place_doc or place_series:
        print("-- placement --")
        for fleet_name, snap in sorted(place_doc.items()):
            resident = snap.get("resident") or {}
            for rid, names in sorted(resident.items()):
                print(f"   {fleet_name}/{rid}: resident="
                      f"{','.join(names) or '-'}")
            cold = snap.get("cold") or []
            if cold:
                print(f"   {fleet_name}: cold={','.join(cold)}")
            refused = snap.get("refused") or []
            if refused:
                print(f"   {fleet_name}: refused={','.join(refused)}")
            print(f"   {fleet_name}: pageIns={snap.get('pageIns')} "
                  f"evictions={snap.get('evictions')} "
                  f"blindAdmits={snap.get('blindAdmits')} "
                  f"pageInP99Ms={snap.get('pageInP99Ms')}")
        for fname, series in sorted(place_series.items()):
            for key, v in sorted(series.items()):
                if isinstance(v, dict):
                    v = f"count={v.get('count')}"
                print(f"   {fname}{{{key}}}: {v}")
    # network edge (docs/serving.md "Network edge") — connection /
    # request / shed accounting from the tg_net_* series the bundle
    # snapshotted (per-protocol, per-reason)
    net_series = {n: s for n, s in metrics.items()
                  if n.startswith("tg_net_")}
    if net_series:
        print("-- network --")
        for fname, series in sorted(net_series.items()):
            for key, v in sorted(series.items()):
                if isinstance(v, dict):
                    v = f"count={v.get('count')}"
                print(f"   {fname}{{{key}}}: {v}")
    # streaming (docs/streaming.md "The input engine") — was an
    # out-of-core train running, where its time went per stage
    # (read/transform/upload), how well the producer pool overlapped the
    # device link, and whether the transformed-chunk cache was earning
    # its RAM — from the tg_stream_* series the bundle snapshotted
    stream_series = {n: s for n, s in metrics.items()
                     if n.startswith("tg_stream_")}
    if stream_series:
        print("-- streaming --")
        stages = stream_series.get("tg_stream_stage_seconds") or {}
        for key, v in sorted(stages.items()):
            if isinstance(v, dict):
                lat = {q: v.get(q) for q in ("p50", "p95", "p99")
                       if v.get(q) is not None}
                print(f"   stage{{{key}}}: count={v.get('count')} {lat}")
        hits = miss = 0.0
        for key, v in (stream_series.get(
                "tg_stream_cache_hits_total") or {}).items():
            hits += float(v) if not isinstance(v, dict) else 0.0
        for key, v in (stream_series.get(
                "tg_stream_cache_misses_total") or {}).items():
            miss += float(v) if not isinstance(v, dict) else 0.0
        if hits or miss:
            rate = hits / max(hits + miss, 1.0)
            print(f"   cache: hits={hits:.0f} misses={miss:.0f} "
                  f"hitRate={rate:.3f}")
        for fname, series in sorted(stream_series.items()):
            if fname in ("tg_stream_stage_seconds",
                         "tg_stream_cache_hits_total",
                         "tg_stream_cache_misses_total"):
                continue
            for key, v in sorted(series.items()):
                if isinstance(v, dict):
                    v = f"count={v.get('count')}"
                print(f"   {fname}{{{key}}}: {v}")
    # SLO & budgets (bundle schema v3; docs/observability.md "SLOs,
    # budgets & burn rates") — was the budget already burning before
    # this incident, and what would the autoscaler have done?
    slo_doc = doc.get("slo") or {}
    if slo_doc:
        print("-- SLO & budgets --")
        for model, specs in sorted(slo_doc.items()):
            for key, snap in sorted((specs or {}).items()):
                objs = snap.get("objectives") or {}
                parts = []
                for obj, o in sorted(objs.items()):
                    v = o.get("verdict", "?")
                    rem = o.get("budgetRemaining")
                    rem_s = (f" budget={rem:.3f}"
                             if isinstance(rem, (int, float)) else "")
                    parts.append(f"{obj}={v}{rem_s}")
                active = snap.get("activeAlerts") or []
                alert_s = ("  ALERTS: " + ", ".join(
                    f"{a.get('severity')}:{a.get('objective')}"
                    for a in active)) if active else ""
                fired = snap.get("fired") or {}
                fired_s = (f"  fired={fired}"
                           if any(fired.values()) else "")
                print(f"   {key:<20} {' '.join(parts)}"
                      f"{alert_s}{fired_s}"[:200])
    samples = doc.get("samples") or []
    for src in samples[:4]:
        print(f"   sampler[{src.get('source', '?')}]: "
              f"{src.get('samples', 0)} samples / "
              f"{src.get('series', 0)} series @ "
              f"{src.get('everyS', '?')}s")
    # programs (bundle schema v4; docs/serving.md "AOT cold start & the
    # program store") — was this process serving deserialized AOT
    # programs, and had the store been hitting or falling back?
    aot = doc.get("aot") or {}
    if aot:
        st = aot.get("stats") or {}
        print(f"-- programs (AOT store: "
              f"{'on' if aot.get('enabled') else 'off'}) --")
        print(f"   hits={st.get('hitsTotal', 0)} "
              f"{st.get('hits') or {}}  "
              f"misses={st.get('missesTotal', 0)} "
              f"{st.get('misses') or {}}  "
              f"exports={st.get('exports', 0)}")
        for sess in (aot.get("sessions") or [])[:6]:
            print(f"   session[{sess.get('origin', '?')}]: "
                  f"{sess.get('entries', 0)} entries, "
                  f"{sess.get('loaded', 0)} loaded, "
                  f"{sess.get('planIdents', 0)} plan idents")
        aot_builds = {sub: causes.get("aot-miss", 0)
                      for sub, causes in (led.get("counts") or {}).items()
                      if causes.get("aot-miss")}
        if aot_builds:
            print(f"   aot-miss builds by subsystem: {aot_builds}")
    faults_doc = doc.get("faults") or {}
    buckets = {k: len(v) for k, v in faults_doc.items()
               if isinstance(v, list) and v}
    if buckets:
        print(f"-- fault log: {buckets} "
              f"(dropped {faults_doc.get('droppedReports', 0)})")
    verdict = "INVALID" if problems else "ok"
    print(f"== doctor verdict: {verdict} ==")
    if problems:
        _sys.exit(1)
    return {"bundle": path, "problems": problems}


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(prog="op",
                                description="transmogrifai_tpu CLI")
    sub = p.add_subparsers(dest="command", required=True)
    gen = sub.add_parser(
        "gen", help="generate a project from a CSV or Avro schema")
    gen.add_argument("--input", required=True, help="CSV or .avro data file")
    gen.add_argument("--response", required=True, help="response column")
    gen.add_argument("--output", required=True, help="output project dir")
    gen.add_argument("--name", default="GeneratedApp")
    gen.add_argument("--id-field", default=None)
    gen.add_argument("--schema", default=None,
                     help="Avro .avsc record schema declaring field types")
    gen.add_argument("--answers", default=None,
                     help="key=value answers file (problem=, type.<f>=, "
                          "role.<f>=) for non-interactive generation")
    tr = sub.add_parser(
        "trace", help="train an example dataset under tracing and write "
                      "trace.json + metrics.prom (docs/observability.md)")
    tr.add_argument("--output", required=True,
                    help="directory for trace.json / spans.jsonl / "
                         "metrics.prom / summary.json")
    tr.add_argument("--dataset", default="synthetic",
                    choices=["synthetic", "iris"],
                    help="synthetic needs no data files; iris requires the "
                         "bundled example dataset on disk")
    tr.add_argument("--rows", type=int, default=600,
                    help="synthetic dataset row count")
    tr.add_argument("--seed", type=int, default=42)
    sv = sub.add_parser(
        "serve", help="load a saved model and drive the resilient serving "
                      "runtime under synthetic open-loop load "
                      "(docs/serving.md)")
    sv.add_argument("--model", required=True,
                    help="saved model directory (OpWorkflowModel.save)")
    sv.add_argument("--seconds", type=float, default=5.0,
                    help="load duration")
    sv.add_argument("--rps", type=float, default=0.0,
                    help="offered requests/sec (0 = auto-calibrate to "
                         "~70%% of measured micro-batch capacity)")
    sv.add_argument("--deadline-ms", type=float, default=None,
                    help="per-request deadline; expired requests are shed "
                         "before dispatch")
    sv.add_argument("--max-batch", type=int, default=256,
                    help="continuous-batching flush size")
    sv.add_argument("--queue-max", type=int, default=1024,
                    help="admission bound (beyond it requests shed with "
                         "OverloadError)")
    sv.add_argument("--name", default="model", help="registry model name")
    sv.add_argument("--listen", default=None, metavar="HOST:PORT",
                    help="serve over the network edge: terminate "
                         "HTTP/JSON + binary framing on a real socket "
                         "and drive the socket load generator through "
                         "it (port 0 = pick a free port; exits non-zero "
                         "on any lost future or accounting break; "
                         "docs/serving.md \"Network edge\")")
    sv.add_argument("--output", default=None,
                    help="directory for the telemetry bundle (trace.json / "
                         "spans.jsonl / metrics.prom / serve_summary.json)")
    sv.add_argument("--seed", type=int, default=42)
    fl = sub.add_parser(
        "fleet", help="start N worker replicas of a saved model behind "
                      "a load-aware front door, drive the open-loop "
                      "soak, and print the per-replica + fleet report; "
                      "exits non-zero on any lost request "
                      "(docs/serving.md)")
    fl.add_argument("--model", required=True,
                    help="saved model directory (OpWorkflowModel.save)")
    fl.add_argument("--replicas", type=int, default=2,
                    help="worker replica count")
    fl.add_argument("--seconds", type=float, default=5.0,
                    help="load duration")
    fl.add_argument("--rps", type=float, default=0.0,
                    help="offered requests/sec (0 = auto-calibrate)")
    fl.add_argument("--deadline-ms", type=float, default=None)
    fl.add_argument("--max-batch", type=int, default=256)
    fl.add_argument("--queue-max", type=int, default=1024)
    fl.add_argument("--kill", action="store_true",
                    help="kill one replica mid-soak (zero-lost-requests "
                         "drill: the run must still account every "
                         "request)")
    fl.add_argument("--subprocess", action="store_true",
                    help="subprocess replicas (one OS process each; "
                         "TG_FLEET_SUBPROCESS)")
    fl.add_argument("--name", default="model", help="registry model name")
    fl.add_argument("--models", type=int, default=1,
                    help="register the saved model under N names with "
                         "the placement layer enabled and drive an "
                         "equal-weight model mix (routing + paging + "
                         "eviction; docs/serving.md \"Multi-model "
                         "placement & paging\")")
    fl.add_argument("--output", default=None,
                    help="directory for metrics.prom + "
                         "fleet_summary.json")
    fl.add_argument("--seed", type=int, default=42)
    so = sub.add_parser(
        "slo", help="load a saved model, drive open-loop load, and "
                    "report SLO verdicts, budget burn and scale-hint "
                    "timeline; exits non-zero when a page-severity "
                    "burn-rate alert fires (docs/observability.md)")
    so.add_argument("--model", required=True,
                    help="saved model directory (OpWorkflowModel.save)")
    so.add_argument("--seconds", type=float, default=5.0,
                    help="total load duration")
    so.add_argument("--rps", type=float, default=0.0,
                    help="offered requests/sec (0 = auto-calibrate)")
    so.add_argument("--availability", type=float, default=None,
                    help="availability target (default "
                         "TG_SLO_AVAILABILITY or 0.999)")
    so.add_argument("--p99-ms", type=float, default=None,
                    help="latency objective: windowed p99 target in ms "
                         "(unset = availability/freshness only)")
    so.add_argument("--window-s", type=float, default=None,
                    help="scaled SLO budget window in seconds (default "
                         "3600 — the 30-day methodology compressed so "
                         "a seconds-long run exercises the alert "
                         "ladder)")
    so.add_argument("--tenants", default=None,
                    help='weighted tenant mix, e.g. "a:3,b:1" — adds '
                         "per-tenant budgets and a per-tenant report")
    so.add_argument("--intervals", type=int, default=5,
                    help="timeline resolution (load slices)")
    so.add_argument("--deadline-ms", type=float, default=None)
    so.add_argument("--name", default="model", help="registry model name")
    so.add_argument("--output", default=None,
                    help="directory for slo_summary.json + metrics.prom "
                         "(windowed series included)")
    so.add_argument("--seed", type=int, default=42)
    cp = sub.add_parser(
        "campaign", help="run a seeded chaos campaign — randomized "
                         "multi-fault schedules against real scenario "
                         "harnesses with invariant oracles and automatic "
                         "schedule minimization (docs/robustness.md)")
    cp.add_argument("--schedules", type=int, default=0,
                    help="schedule budget (0 = TG_CAMPAIGN_SCHEDULES or "
                         "40; coverage singletons for every registered "
                         "site come first)")
    cp.add_argument("--seed", type=int, default=None,
                    help="campaign seed (default TG_CAMPAIGN_SEED or 0); "
                         "same seed => same schedules => same fault "
                         "sequence")
    cp.add_argument("--scenario", default=None,
                    help="restrict to one scenario harness (train | sweep "
                         "| serve | serve_heal | stream | fleet | net | "
                         "transfer); required in repro mode")
    cp.add_argument("--faults", default=None,
                    help="repro mode: a TG_FAULTS-style JSON schedule to "
                         "run ONCE against --scenario (also picked up "
                         "from TG_CHAOS=1 TG_FAULTS=... env — the "
                         "one-command repro a failing campaign emits); "
                         "exits non-zero on any invariant violation")
    cp.add_argument("--output", default=None,
                    help="directory for campaign_report.json")
    cp.add_argument("--no-minimize", action="store_true",
                    help="skip delta-debug minimization of violating "
                         "schedules")
    pg = sub.add_parser(
        "programs", help="list/verify/gc an AOT program store — a model "
                         "dir's programs/ + MANIFEST `programs` section "
                         "or a raw TG_AOT_STORE dir; exits non-zero on "
                         "corrupt entries (docs/serving.md)")
    pg.add_argument("path",
                    help="model directory (MANIFEST.json present) or a "
                         "program-store directory")
    pg.add_argument("--gc", action="store_true",
                    help="evict oldest entries past TG_AOT_STORE_MAX / "
                         "TG_AOT_STORE_MAX_BYTES")
    pg.add_argument("--json", action="store_true",
                    help="machine-readable report")
    dr = sub.add_parser(
        "doctor", help="render a flight-recorder post-mortem bundle into "
                       "a human-readable incident report "
                       "(docs/observability.md)")
    dr.add_argument("bundle",
                    help="bundle file (postmortem_*.json) or a directory "
                         "of bundles (the newest one is rendered)")
    dr.add_argument("--json", action="store_true",
                    help="machine-readable output (bundle + validation "
                         "problems) instead of the rendered report")
    dr.add_argument("--tail", type=int, default=40,
                    help="ring events to show in the recent-timeline tail")
    a = p.parse_args(argv)
    if a.command == "gen":
        generate(a.input, a.response, a.output, a.name, a.id_field,
                 schema=a.schema, answers=a.answers)
        print(f"generated project in {a.output}/ "
              f"(app.py, README.md, test_app.py)")
    elif a.command == "trace":
        run_trace(a.output, dataset=a.dataset, rows=a.rows, seed=a.seed)
    elif a.command == "serve":
        run_serve(a.model, seconds=a.seconds, rps=a.rps,
                  deadline_ms=a.deadline_ms, max_batch=a.max_batch,
                  queue_max=a.queue_max, name=a.name, output=a.output,
                  seed=a.seed, listen=a.listen)
    elif a.command == "fleet":
        run_fleet(a.model, replicas=a.replicas, seconds=a.seconds,
                  rps=a.rps, deadline_ms=a.deadline_ms,
                  max_batch=a.max_batch, queue_max=a.queue_max,
                  kill=a.kill, use_subprocess=a.subprocess,
                  name=a.name, output=a.output, seed=a.seed,
                  models=a.models)
    elif a.command == "slo":
        run_slo(a.model, seconds=a.seconds, rps=a.rps,
                availability=a.availability, p99_ms=a.p99_ms,
                window_s=a.window_s, tenants=a.tenants,
                intervals=a.intervals, deadline_ms=a.deadline_ms,
                name=a.name, output=a.output, seed=a.seed)
    elif a.command == "campaign":
        run_campaign(schedules=a.schedules, seed=a.seed,
                     scenario=a.scenario, faults_json=a.faults,
                     output=a.output, no_minimize=a.no_minimize)
    elif a.command == "programs":
        run_programs(a.path, gc=a.gc, as_json=a.json)
    elif a.command == "doctor":
        run_doctor(a.bundle, as_json=a.json, tail=a.tail)


if __name__ == "__main__":
    main()
