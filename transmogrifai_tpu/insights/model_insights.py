"""ModelInsights — the full post-training report.

Mirrors the reference (reference:
core/src/main/scala/com/salesforce/op/ModelInsights.scala — extractFromStages
:436, prettyPrint :99): walk the fitted workflow model's stages and assemble
(1) a label summary, (2) per-feature derived-column insights (correlation,
Cramér's V, variance, model contribution) attributed back to raw features via
vector metadata, (3) the selected-model summary with its validation sweep, and
(4) run metadata (blacklists, RawFeatureFilter results, version info).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


@dataclass
class DerivedColumnInsights:
    """One vector-slot's insight row (reference Insights per derived feature)."""
    name: str
    parent_feature: str
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    correlation: Optional[float] = None
    cramers_v: Optional[float] = None
    mutual_info: Optional[float] = None
    variance: Optional[float] = None
    mean: Optional[float] = None
    min: Optional[float] = None
    max: Optional[float] = None
    contribution: Optional[float] = None
    dropped: bool = False
    drop_reasons: List[str] = field(default_factory=list)


@dataclass
class FeatureInsights:
    """All derived columns of one raw feature (reference FeatureInsights)."""
    feature_name: str
    feature_type: str
    derived: List[DerivedColumnInsights] = field(default_factory=list)

    @property
    def max_abs_contribution(self) -> float:
        vals = [abs(d.contribution) for d in self.derived
                if d.contribution is not None]
        return max(vals) if vals else 0.0


@dataclass
class LabelSummary:
    name: str
    is_classification: bool
    sample_size: int = 0
    distribution: Optional[Dict[str, float]] = None  # classification counts
    mean: Optional[float] = None
    variance: Optional[float] = None


@dataclass
class ModelInsights:
    """The report (reference ModelInsights.scala)."""
    label: LabelSummary
    features: List[FeatureInsights]
    selected_model: Optional[Dict[str, Any]]
    model_validation_results: List[Dict[str, Any]]
    blacklisted_features: List[str]
    raw_feature_filter_results: Optional[Dict[str, Any]]
    version_info: Dict[str, str]
    #: cross-feature redundancy: column pairs whose |corr| exceeds the
    #: redundancy threshold, from the SanityChecker's full (d, d) matrix
    #: (``correlations="full"``; reference SanityChecker.scala:634-638
    #: computes the same matrix — empty under the label-only default)
    cross_feature_redundancy: List[Dict[str, Any]] = field(
        default_factory=list)
    #: per categorical group: the (feature value × label) pointwise mutual
    #: information table (reference OpStatistics.contingencyStats PMI)
    categorical_pmi: Dict[str, List[List[float]]] = field(
        default_factory=dict)
    #: DataSplitter/DataBalancer/DataCutter decisions recorded at fit time
    #: (reference ModelSelectorSummary splitter metadata)
    splitter_summary: Dict[str, Any] = field(default_factory=dict)

    #: |correlation| above which a kept column pair is reported redundant
    REDUNDANCY_THRESHOLD = 0.9
    #: cap on reported redundancy pairs (sorted by |corr| descending)
    REDUNDANCY_TOP_K = 50

    # -- extraction (reference extractFromStages :436) -----------------------
    @staticmethod
    def extract(model) -> "ModelInsights":
        from ..impl.preparators.sanity_checker import SanityCheckerModel
        from ..impl.selector.model_selector import SelectedModel
        from ..utils.version import version_info

        checker: Optional[SanityCheckerModel] = None
        selected: Optional[SelectedModel] = None
        for st in model.stages:
            if isinstance(st, SanityCheckerModel) and checker is None:
                checker = st
            if isinstance(st, SelectedModel) and selected is None:
                selected = st

        label = ModelInsights._label_summary(model, selected)
        features = ModelInsights._feature_insights(model, checker, selected)
        sel_json: Optional[Dict[str, Any]] = None
        val_results: List[Dict[str, Any]] = []
        if selected is not None:
            s = selected.summary
            sel_json = {
                "bestModelType": s.best_model_type,
                "bestHyperparameters": s.best_hyper,
                "validationType": s.validation_type,
                "validationMetric": s.validation_metric,
                "bestMetricValue": s.best_metric_value,
                "trainEvaluation": getattr(s, "train_evaluation", {}),
                "holdoutEvaluation": getattr(s, "holdout_evaluation", {}),
                "problem": s.problem,
            }
            for r in s.validation_results:
                val_results.append({
                    "modelType": r.family,
                    "numConfigurations": len(r.grid),
                    "meanMetrics": [float(v) for v in np.asarray(r.mean_metrics)],
                    "grid": r.grid,
                })
        rff = getattr(model, "rff_results", None)
        redundancy: List[Dict[str, Any]] = []
        pmi: Dict[str, Any] = {}
        splitter_summary: Dict[str, Any] = {}
        if checker is not None:
            s = checker.summary
            redundancy = ModelInsights._redundancy_pairs(s)
            pmi = dict(s.get("pointwiseMutualInfo", {}) or {})
        if selected is not None:
            splitter_summary = dict(
                getattr(selected.summary, "splitter_summary", {}) or {})
            if sel_json is not None:
                sel_json["splitterSummary"] = splitter_summary
        return ModelInsights(
            label=label,
            features=features,
            selected_model=sel_json,
            model_validation_results=val_results,
            blacklisted_features=[f.name for f in model.blacklisted_features],
            raw_feature_filter_results=rff.to_json() if rff is not None else None,
            version_info=version_info(),
            cross_feature_redundancy=redundancy,
            categorical_pmi=pmi,
            splitter_summary=splitter_summary,
        )

    @staticmethod
    def _redundancy_pairs(summary) -> List[Dict[str, Any]]:
        """Kept-column pairs with |corr| ≥ REDUNDANCY_THRESHOLD from the
        checker's full feature-feature matrix (None under the label-only
        correlation default)."""
        fc = summary.get("featureCorrelations")
        if fc is None:
            return []
        names: List[str] = list(summary.get("names", []))
        C = np.asarray(fc, dtype=np.float64)
        if C.ndim != 2 or C.shape[0] != C.shape[1]:
            return []
        thr = ModelInsights.REDUNDANCY_THRESHOLD
        iu, ju = np.triu_indices(C.shape[0], k=1)
        with np.errstate(invalid="ignore"):
            vals = C[iu, ju]
        hit = np.nonzero(np.abs(np.nan_to_num(vals)) >= thr)[0]
        order = hit[np.argsort(-np.abs(vals[hit]))]
        out = []
        for k in order[:ModelInsights.REDUNDANCY_TOP_K]:
            i, j = int(iu[k]), int(ju[k])
            out.append({
                "feature1": names[i] if i < len(names) else f"c{i}",
                "feature2": names[j] if j < len(names) else f"c{j}",
                "correlation": round(float(vals[k]), 6),
            })
        return out

    @staticmethod
    def _label_summary(model, selected) -> LabelSummary:
        label_f = next((f for f in model.raw_features if f.is_response), None)
        name = label_f.name if label_f is not None else "label"
        is_cls = True
        if selected is not None:
            is_cls = selected.summary.problem in ("binary", "multiclass")
        table = getattr(model, "train_table", None)
        if table is None or label_f is None or name not in table.column_names:
            return LabelSummary(name=name, is_classification=is_cls)
        y = np.asarray(table[name].values, dtype=np.float64).reshape(-1)
        if is_cls:
            vals, counts = np.unique(y, return_counts=True)
            dist = {str(v): int(c) for v, c in zip(vals.tolist(), counts.tolist())}
            return LabelSummary(name=name, is_classification=True,
                                sample_size=int(y.size), distribution=dist)
        return LabelSummary(name=name, is_classification=False,
                            sample_size=int(y.size), mean=float(y.mean()),
                            variance=float(y.var()))

    @staticmethod
    def _feature_insights(model, checker, selected) -> List[FeatureInsights]:
        per_raw: Dict[str, FeatureInsights] = {}
        raw_types = {f.name: f.type_name for f in model.raw_features}
        if checker is None:
            return []
        s = checker.summary
        names: List[str] = s.get("names", [])
        corr = s.get("correlationsWithLabel", [None] * len(names))
        dropped = set(s.get("dropped", []))
        reasons: Dict[str, List[str]] = s.get("reasons", {})
        cramers: Dict[str, float] = s.get("cramersV", {})
        mutual: Dict[str, float] = s.get("mutualInfo", {}) or {}

        # column → raw-feature attribution via the vector-slot name prefix
        # (vector metadata column names start with the parent feature name)
        contributions = ModelInsights._contributions(checker, selected, names)

        for i, name in enumerate(names):
            parent = name.split("_", 1)[0]
            d = DerivedColumnInsights(
                name=name, parent_feature=parent,
                correlation=(None if corr[i] is None else float(corr[i])),
                variance=float(s["variance"][i]) if "variance" in s else None,
                mean=float(s["mean"][i]) if "mean" in s else None,
                min=float(s["min"][i]) if "min" in s else None,
                max=float(s["max"][i]) if "max" in s else None,
                contribution=contributions.get(name),
                dropped=name in dropped,
                drop_reasons=list(reasons.get(name, [])),
            )
            for group, v in cramers.items():
                gname = group.split("::")[0]
                if parent == gname:
                    d.cramers_v = float(v)
                    if group in mutual:
                        d.mutual_info = float(mutual[group])
                    break
            fi = per_raw.setdefault(parent, FeatureInsights(
                feature_name=parent,
                feature_type=raw_types.get(parent, "unknown")))
            fi.derived.append(d)
        return sorted(per_raw.values(),
                      key=lambda f: -f.max_abs_contribution)

    @staticmethod
    def _contributions(checker, selected, names: List[str]) -> Dict[str, float]:
        """Per-column model contribution: |coefficient| for linear families,
        split-gain importances for trees (reference contribution extraction
        from the winning model)."""
        if selected is None:
            return {}
        kept = checker.keep_indices if checker is not None else range(len(names))
        kept_names = [names[i] for i in kept]
        fitted = selected.fitted
        try:
            from ..models.api import MODEL_REGISTRY
            family = MODEL_REGISTRY[fitted.family]
            imp = getattr(family, "feature_importances", None)
            if imp is not None:
                vals = np.asarray(imp(fitted)).reshape(-1)
            else:
                return {}
        except Exception:
            return {}
        if vals.size < len(kept_names):
            # tree split-frequency vectors stop at the highest used feature
            vals = np.pad(vals, (0, len(kept_names) - vals.size))
        elif vals.size > len(kept_names):
            return {}
        return {n: float(v) for n, v in zip(kept_names, vals)}

    # -- rendering (reference prettyPrint :99) -------------------------------
    def to_json(self) -> Dict[str, Any]:
        def enc(o):
            if isinstance(o, (DerivedColumnInsights, FeatureInsights,
                              LabelSummary)):
                return {k: enc(v) for k, v in vars(o).items()}
            if isinstance(o, list):
                return [enc(x) for x in o]
            if isinstance(o, dict):
                return {k: enc(v) for k, v in o.items()}
            if isinstance(o, (np.floating, np.integer)):
                return o.item()
            if isinstance(o, float) and not np.isfinite(o):
                return None
            return o
        return {
            "label": enc(self.label),
            "features": enc(self.features),
            "selectedModel": enc(self.selected_model),
            "modelValidationResults": enc(self.model_validation_results),
            "blacklistedFeatures": self.blacklisted_features,
            "rawFeatureFilterResults": enc(self.raw_feature_filter_results),
            "versionInfo": self.version_info,
            "crossFeatureRedundancy": enc(self.cross_feature_redundancy),
            "categoricalPointwiseMutualInfo": enc(self.categorical_pmi),
            "splitterSummary": enc(self.splitter_summary),
        }

    def to_json_string(self) -> str:
        return json.dumps(self.to_json(), indent=2)

    def pretty_print(self, top_k: int = 15) -> str:
        lines: List[str] = ["=" * 60, "Model Insights", "=" * 60]
        l = self.label
        lines.append(f"Label: {l.name} "
                     f"({'classification' if l.is_classification else 'regression'}, "
                     f"n={l.sample_size})")
        if l.distribution:
            lines.append(f"  distribution: {l.distribution}")
        if self.selected_model:
            sm = self.selected_model
            lines.append(f"Best model: {sm['bestModelType']} "
                         f"({sm['validationMetric']}="
                         f"{sm['bestMetricValue']:.4f})")
            lines.append(f"  hyperparameters: {sm['bestHyperparameters']}")
            if sm.get("holdoutEvaluation"):
                show = {k: round(v, 4) for k, v in sm["holdoutEvaluation"].items()
                        if isinstance(v, (int, float))}
                lines.append(f"  holdout: {show}")
        rows = []
        for fi in self.features:
            for d in fi.derived:
                rows.append(d)
        rows.sort(key=lambda d: -(abs(d.contribution)
                                  if d.contribution is not None else -1))
        from ..utils.table_format import format_table
        table_rows = [
            [(f"{d.contribution:+.4f}" if d.contribution is not None
              else "n/a"),
             (f"{d.correlation:+.3f}" if d.correlation is not None
              else "n/a"),
             d.name + (" [DROPPED]" if d.dropped else "")]
            for d in rows[:top_k]]
        lines.append(format_table(["contribution", "correlation", "feature"],
                                  table_rows,
                                  title="Top feature contributions"))
        if self.splitter_summary:
            lines.append(f"Splitter: {self.splitter_summary}")
        if self.cross_feature_redundancy:
            lines.append("Redundant column pairs (|corr| >= "
                         f"{self.REDUNDANCY_THRESHOLD}):")
            for p in self.cross_feature_redundancy[:10]:
                lines.append(f"  {p['feature1']} ~ {p['feature2']}: "
                             f"{p['correlation']:+.4f}")
        if self.blacklisted_features:
            lines.append(f"Blacklisted raw features: {self.blacklisted_features}")
        return "\n".join(lines)
