from .model_insights import ModelInsights  # noqa: F401
from .record_insights import RecordInsightsCorr, RecordInsightsLOCO  # noqa: F401
