"""Per-record explanations: LOCO and correlation-based insights.

Mirrors the reference (reference:
core/.../impl/insights/RecordInsightsLOCO.scala:61-97 — leave-one-covariate-out:
zero each active vector slot (grouped for text/date siblings), re-score, and
report the top-K score diffs; RecordInsightsCorr.scala). The TPU re-design
batches the whole thing: for n rows and G metadata groups, one device pass
scores the (n × (G+1)) zeroed variants — the vmap-friendly structure the
row-at-a-time Spark UDF could never use.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..stages.base import AllowLabelAsInput, Transformer
from ..table import Column, FeatureTable
from ..types import OPVector, TextMap
from ..vector_metadata import VectorMetadata


def _score_of(parts: Dict[str, np.ndarray]) -> np.ndarray:
    """Scalar score per row from prediction parts: P(class 1) for binary,
    max-class probability for multiclass, raw prediction for regression
    (reference LOCO diffs the probability vector)."""
    if "probability" in parts:
        prob = np.asarray(parts["probability"])
        if prob.ndim == 2 and prob.shape[1] >= 2:
            return prob[:, 1] if prob.shape[1] == 2 else prob.max(axis=1)
    return np.asarray(parts["prediction"]).reshape(-1)


class RecordInsightsLOCO(AllowLabelAsInput, Transformer):
    """OPVector → TextMap of {column name: score diff} per row.

    Construct with the fitted SelectedModel (the winning model stage); wire its
    feature-vector input feature with ``set_input``.
    """

    input_types = (OPVector,)
    output_type = TextMap

    #: device-memory budget for one variant block (bytes of f32): group ×
    #: row chunks are sized so the zeroed-variant matrix never exceeds this
    VARIANT_BLOCK_BYTES = 256 << 20

    def __init__(self, model_stage, top_k: int = 20, uid=None):
        super().__init__("loco", uid)
        self.model_stage = model_stage
        self.top_k = top_k

    def _groups(self, vm: Optional[VectorMetadata], d: int
                ) -> List[Tuple[str, List[int]]]:
        """Metadata feature groups (text/date siblings zero together,
        reference RecordInsightsLOCO grouping)."""
        if vm is None:
            return [(f"c{i}", [i]) for i in range(d)]
        out: List[Tuple[str, List[int]]] = []
        for group, idxs in vm.index_of_group().items():
            out.append((group, list(idxs)))
        return out

    def transform_column(self, table: FeatureTable) -> Column:
        from ..models.api import MODEL_REGISTRY
        import jax.numpy as jnp

        vec_f = self.input_features[0]
        col = table[vec_f.name]
        X = np.asarray(col.values, dtype=np.float32)
        n, d = X.shape
        vm = col.metadata.get("vector_meta")
        if vm is not None:
            self._vm = vm          # remembered for the metadata-less row dual
        elif getattr(self, "_vm", None) is not None and self._vm.size == d:
            vm = self._vm
        groups = self._groups(vm, d)
        g = len(groups)

        fitted = self.model_stage.fitted
        family = MODEL_REGISTRY[fitted.family]

        Xd = jnp.asarray(X)                # the ONE host→device upload
        base = _score_of(family.predict_one(fitted, Xd))

        # device-side LOCO: the zeroed variants are built ON DEVICE as
        # X[None] * keep_mask[:, None, :] in (group × row)-chunked blocks
        # bounded by VARIANT_BLOCK_BYTES — the full (g, n, d) stack is never
        # materialized anywhere (the reference's row-at-a-time UDF analog
        # RecordInsightsLOCO.scala:61-97; round-2's host np.repeat needed
        # O(g·n·d) host RAM — ~100+ GB at 1M×543 with hundreds of groups)
        keep = np.ones((g, d), np.float32)
        for v, (_, idxs) in enumerate(groups):
            keep[v, idxs] = 0.0
        keep_d = jnp.asarray(keep)
        rows_per_block = max(1, int(self.VARIANT_BLOCK_BYTES // (4 * d)))
        gc = max(1, min(g, rows_per_block // max(n, 1)) or 1)
        rc = min(n, rows_per_block)        # row chunk when a group > budget
        self._peak_variant_bytes = 0
        diffs = np.empty((g, n), np.float32)
        for g0 in range(0, g, gc):
            g1 = min(g0 + gc, g)
            for r0 in range(0, n, rc):
                r1 = min(r0 + rc, n)
                block = (Xd[None, r0:r1, :]
                         * keep_d[g0:g1, None, :])        # (gb, rb, d) device
                gb, rb = g1 - g0, r1 - r0
                self._peak_variant_bytes = max(
                    self._peak_variant_bytes, 4 * gb * rb * d)
                s = _score_of(family.predict_one(
                    fitted, block.reshape(gb * rb, d)))
                diffs[g0:g1, r0:r1] = (base[None, r0:r1]
                                       - s.reshape(gb, rb))
        # positive → slot pushed score up

        names = [name for name, _ in groups]
        out = np.empty(n, dtype=object)
        k = min(self.top_k, g)
        order = np.argsort(-np.abs(diffs), axis=0)[:k]   # (k, n)
        for i in range(n):
            top = {}
            for v in order[:, i]:
                if diffs[v, i] != 0.0:
                    top[names[v]] = round(float(diffs[v, i]), 6)
            out[i] = top
        return Column(TextMap, out, np.array([bool(o) for o in out]))

    def transform_row(self, row: Dict[str, Any]) -> Any:
        one = FeatureTable(
            {f.name: Column.of_values(f.feature_type, [row.get(f.name)])
             for f in self.input_features}, 1)
        return self.transform_column(one).values[0]


class RecordInsightsCorr(AllowLabelAsInput, Transformer):
    """OPVector → TextMap of {column name: value × corr(score, column)}.

    The correlation-flavored cousin (reference RecordInsightsCorr.scala):
    contributions are the row's standardized slot values scaled by each slot's
    correlation with the model score over the scoring batch.
    """

    input_types = (OPVector,)
    output_type = TextMap

    def __init__(self, model_stage, top_k: int = 20, uid=None):
        super().__init__("recordInsightsCorr", uid)
        self.model_stage = model_stage
        self.top_k = top_k

    def transform_column(self, table: FeatureTable) -> Column:
        from ..models.api import MODEL_REGISTRY
        from ..ops.stats import pearson_correlation
        import jax.numpy as jnp

        vec_f = self.input_features[0]
        col = table[vec_f.name]
        X = np.asarray(col.values, dtype=np.float32)
        n, d = X.shape
        vm = col.metadata.get("vector_meta")
        names = (vm.column_names() if vm is not None
                 else [f"c{i}" for i in range(d)])

        fitted = self.model_stage.fitted
        family = MODEL_REGISTRY[fitted.family]
        score = _score_of(family.predict_one(fitted, jnp.asarray(X)))

        corr = np.asarray(pearson_correlation(jnp.asarray(X),
                                              jnp.asarray(score)))
        corr = np.nan_to_num(corr)
        mean = X.mean(axis=0)
        std = X.std(axis=0)
        std[std == 0] = 1.0
        contrib = ((X - mean) / std) * corr[None, :]    # (n, d)

        out = np.empty(n, dtype=object)
        k = min(self.top_k, d)
        order = np.argsort(-np.abs(contrib), axis=1)[:, :k]
        for i in range(n):
            top = {}
            for j in order[i]:
                if contrib[i, j] != 0.0:
                    top[names[j]] = round(float(contrib[i, j]), 6)
            out[i] = top
        return Column(TextMap, out, np.array([bool(o) for o in out]))

    def transform_row(self, row: Dict[str, Any]) -> Any:
        raise ValueError(
            "RecordInsightsCorr needs a scoring batch to estimate "
            "correlations; use the columnar path")
