"""Per-record explanations: LOCO and correlation-based insights.

Mirrors the reference (reference:
core/.../impl/insights/RecordInsightsLOCO.scala:61-97 — leave-one-covariate-out:
zero each active vector slot (grouped for text/date siblings), re-score, and
report the top-K score diffs; RecordInsightsCorr.scala). The TPU re-design
batches the whole thing: for n rows and G metadata groups, one device pass
scores the (n × (G+1)) zeroed variants — the vmap-friendly structure the
row-at-a-time Spark UDF could never use.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..stages.base import AllowLabelAsInput, Transformer
from ..table import Column, FeatureTable
from ..types import OPVector, TextMap
from ..vector_metadata import VectorMetadata


class TopKMaps:
    """Lazy array of per-row ``{column name: contribution}`` dicts.

    The top-k assembly is fully vectorized (argsort + take_along_axis on the
    whole batch); Python dicts materialize only on element ACCESS. At the
    1M-row scale the batched device scoring already handles, building a dict
    per row eagerly was the dominant serve-path cost (reference analog
    RecordInsightsLOCO.scala:61-97 builds per-row maps inside a Spark UDF —
    a row-at-a-time design this columnar layout replaces).

    names: group/column name vocabulary; idx: (n, k) int indices into it,
    -1 = unused slot; vals: (n, k) contribution values, slot order =
    descending |contribution| (dict insertion order preserves it).
    """

    def __init__(self, names: Sequence[str], idx: np.ndarray,
                 vals: np.ndarray):
        self._names = list(names)
        self._idx = idx
        self._vals = vals
        self._dense: Optional[np.ndarray] = None
        self.ndim = 1
        self.dtype = np.dtype(object)

    @property
    def shape(self):
        return (self._idx.shape[0],)

    def __len__(self) -> int:
        return int(self._idx.shape[0])

    def __getitem__(self, i):
        if isinstance(i, (int, np.integer)):
            return {self._names[j]: float(v)
                    for j, v in zip(self._idx[i], self._vals[i]) if j >= 0}
        return TopKMaps(self._names, self._idx[i], self._vals[i])

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __array__(self, dtype=None, copy=None):
        # cached: consumers like np.asarray(col.values)[i] inside per-row
        # loops must not re-materialize the whole column per row
        if self._dense is None:
            out = np.empty(len(self), dtype=object)
            for i in range(len(self)):
                out[i] = self[i]
            self._dense = out
        return self._dense

    def tolist(self) -> List[Dict[str, float]]:
        return list(self)

    def any_mask(self) -> np.ndarray:
        """(n,) bool: row has at least one nonzero contribution."""
        return (self._idx >= 0).any(axis=1)


def _topk_maps_column(names: Sequence[str], contrib_rows: np.ndarray,
                      k: int) -> Column:
    """contrib_rows: (n, g) per-row contributions → TextMap column of lazy
    top-k dicts (vectorized: one argsort over the batch, no per-row loop)."""
    k = min(k, contrib_rows.shape[1])
    order = np.argsort(-np.abs(contrib_rows), axis=1)[:, :k]    # (n, k)
    vals = np.take_along_axis(contrib_rows, order, axis=1)      # (n, k)
    # filter on the RAW contribution (a tiny-but-nonzero slot stays in the
    # map, rounding to 0.0 in its value — the eager path's semantics), THEN
    # round for display
    idx = np.where(vals != 0.0, order, -1).astype(np.int32)
    vals = np.round(vals.astype(np.float64), 6)
    maps = TopKMaps(names, idx, vals)
    return Column(TextMap, maps, maps.any_mask())


def _score_of(parts: Dict[str, np.ndarray]) -> np.ndarray:
    """Scalar score per row from prediction parts: P(class 1) for binary,
    max-class probability for multiclass, raw prediction for regression
    (reference LOCO diffs the probability vector)."""
    if "probability" in parts:
        prob = np.asarray(parts["probability"])
        if prob.ndim == 2 and prob.shape[1] >= 2:
            return prob[:, 1] if prob.shape[1] == 2 else prob.max(axis=1)
    return np.asarray(parts["prediction"]).reshape(-1)


class RecordInsightsLOCO(AllowLabelAsInput, Transformer):
    """OPVector → TextMap of {column name: score diff} per row.

    Construct with the fitted SelectedModel (the winning model stage); wire its
    feature-vector input feature with ``set_input``.
    """

    input_types = (OPVector,)
    output_type = TextMap

    #: device-memory budget for one variant block (bytes of f32): group ×
    #: row chunks are sized so the zeroed-variant matrix never exceeds this
    VARIANT_BLOCK_BYTES = 256 << 20

    def __init__(self, model_stage, top_k: int = 20, uid=None):
        super().__init__("loco", uid)
        self.model_stage = model_stage
        self.top_k = top_k

    def _groups(self, vm: Optional[VectorMetadata], d: int
                ) -> List[Tuple[str, List[int]]]:
        """Metadata feature groups (text/date siblings zero together,
        reference RecordInsightsLOCO grouping)."""
        if vm is None:
            return [(f"c{i}", [i]) for i in range(d)]
        out: List[Tuple[str, List[int]]] = []
        for group, idxs in vm.index_of_group().items():
            out.append((group, list(idxs)))
        return out

    def transform_column(self, table: FeatureTable) -> Column:
        from ..models.api import MODEL_REGISTRY
        import jax.numpy as jnp

        vec_f = self.input_features[0]
        col = table[vec_f.name]
        X = np.asarray(col.values, dtype=np.float32)
        n, d = X.shape
        vm = col.metadata.get("vector_meta")
        if vm is not None:
            self._vm = vm          # remembered for the metadata-less row dual
        elif getattr(self, "_vm", None) is not None and self._vm.size == d:
            vm = self._vm
        groups = self._groups(vm, d)
        g = len(groups)

        fitted = self.model_stage.fitted
        family = MODEL_REGISTRY[fitted.family]

        Xd = jnp.asarray(X)                # the ONE host→device upload
        base = _score_of(family.predict_one(fitted, Xd))

        # device-side LOCO: the zeroed variants are built ON DEVICE as
        # X[None] * keep_mask[:, None, :] in (group × row)-chunked blocks
        # bounded by VARIANT_BLOCK_BYTES — the full (g, n, d) stack is never
        # materialized anywhere (the reference's row-at-a-time UDF analog
        # RecordInsightsLOCO.scala:61-97; round-2's host np.repeat needed
        # O(g·n·d) host RAM — ~100+ GB at 1M×543 with hundreds of groups)
        keep = np.ones((g, d), np.float32)
        for v, (_, idxs) in enumerate(groups):
            keep[v, idxs] = 0.0
        keep_d = jnp.asarray(keep)
        rows_per_block = max(1, int(self.VARIANT_BLOCK_BYTES // (4 * d)))
        gc = max(1, min(g, rows_per_block // max(n, 1)) or 1)
        rc = min(n, rows_per_block)        # row chunk when a group > budget
        self._peak_variant_bytes = 0
        diffs = np.empty((g, n), np.float32)
        for g0 in range(0, g, gc):
            g1 = min(g0 + gc, g)
            for r0 in range(0, n, rc):
                r1 = min(r0 + rc, n)
                block = (Xd[None, r0:r1, :]
                         * keep_d[g0:g1, None, :])        # (gb, rb, d) device
                gb, rb = g1 - g0, r1 - r0
                self._peak_variant_bytes = max(
                    self._peak_variant_bytes, 4 * gb * rb * d)
                s = _score_of(family.predict_one(
                    fitted, block.reshape(gb * rb, d)))
                diffs[g0:g1, r0:r1] = (base[None, r0:r1]
                                       - s.reshape(gb, rb))
        # positive → slot pushed score up

        names = [name for name, _ in groups]
        return _topk_maps_column(names, diffs.T, self.top_k)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        one = FeatureTable(
            {f.name: Column.of_values(f.feature_type, [row.get(f.name)])
             for f in self.input_features}, 1)
        return self.transform_column(one).values[0]


class RecordInsightsCorr(AllowLabelAsInput, Transformer):
    """OPVector → TextMap of {column name: value × corr(score, column)}.

    The correlation-flavored cousin (reference RecordInsightsCorr.scala):
    contributions are the row's standardized slot values scaled by each slot's
    correlation with the model score over the scoring batch.
    """

    input_types = (OPVector,)
    output_type = TextMap

    def __init__(self, model_stage, top_k: int = 20, uid=None):
        super().__init__("recordInsightsCorr", uid)
        self.model_stage = model_stage
        self.top_k = top_k

    def transform_column(self, table: FeatureTable) -> Column:
        from ..models.api import MODEL_REGISTRY
        from ..ops.stats import pearson_correlation
        import jax.numpy as jnp

        vec_f = self.input_features[0]
        col = table[vec_f.name]
        X = np.asarray(col.values, dtype=np.float32)
        n, d = X.shape
        vm = col.metadata.get("vector_meta")
        names = (vm.column_names() if vm is not None
                 else [f"c{i}" for i in range(d)])

        fitted = self.model_stage.fitted
        family = MODEL_REGISTRY[fitted.family]
        score = _score_of(family.predict_one(fitted, jnp.asarray(X)))

        corr = np.asarray(pearson_correlation(jnp.asarray(X),
                                              jnp.asarray(score)))
        corr = np.nan_to_num(corr)
        mean = X.mean(axis=0)
        std = X.std(axis=0)
        std[std == 0] = 1.0
        contrib = ((X - mean) / std) * corr[None, :]    # (n, d)

        return _topk_maps_column(names, contrib, self.top_k)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        raise ValueError(
            "RecordInsightsCorr needs a scoring batch to estimate "
            "correlations; use the columnar path")
