"""Per-record explanations: LOCO and correlation-based insights.

Mirrors the reference (reference:
core/.../impl/insights/RecordInsightsLOCO.scala:61-97 — leave-one-covariate-out:
zero each active vector slot (grouped for text/date siblings), re-score, and
report the top-K score diffs; RecordInsightsCorr.scala). The TPU re-design
batches the whole thing: for n rows and G metadata groups, one device pass
scores the (n × (G+1)) zeroed variants — the vmap-friendly structure the
row-at-a-time Spark UDF could never use.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..stages.base import AllowLabelAsInput, Transformer
from ..table import Column, FeatureTable
from ..types import OPVector, TextMap
from ..vector_metadata import VectorMetadata


def _score_of(parts: Dict[str, np.ndarray]) -> np.ndarray:
    """Scalar score per row from prediction parts: P(class 1) for binary,
    max-class probability for multiclass, raw prediction for regression
    (reference LOCO diffs the probability vector)."""
    if "probability" in parts:
        prob = np.asarray(parts["probability"])
        if prob.ndim == 2 and prob.shape[1] >= 2:
            return prob[:, 1] if prob.shape[1] == 2 else prob.max(axis=1)
    return np.asarray(parts["prediction"]).reshape(-1)


class RecordInsightsLOCO(AllowLabelAsInput, Transformer):
    """OPVector → TextMap of {column name: score diff} per row.

    Construct with the fitted SelectedModel (the winning model stage); wire its
    feature-vector input feature with ``set_input``.
    """

    input_types = (OPVector,)
    output_type = TextMap

    def __init__(self, model_stage, top_k: int = 20, uid=None):
        super().__init__("loco", uid)
        self.model_stage = model_stage
        self.top_k = top_k

    def _groups(self, vm: Optional[VectorMetadata], d: int
                ) -> List[Tuple[str, List[int]]]:
        """Metadata feature groups (text/date siblings zero together,
        reference RecordInsightsLOCO grouping)."""
        if vm is None:
            return [(f"c{i}", [i]) for i in range(d)]
        out: List[Tuple[str, List[int]]] = []
        for group, idxs in vm.index_of_group().items():
            out.append((group, list(idxs)))
        return out

    def transform_column(self, table: FeatureTable) -> Column:
        from ..models.api import MODEL_REGISTRY
        import jax.numpy as jnp

        vec_f = self.input_features[0]
        col = table[vec_f.name]
        X = np.asarray(col.values, dtype=np.float32)
        n, d = X.shape
        vm = col.metadata.get("vector_meta")
        if vm is not None:
            self._vm = vm          # remembered for the metadata-less row dual
        elif getattr(self, "_vm", None) is not None and self._vm.size == d:
            vm = self._vm
        groups = self._groups(vm, d)
        g = len(groups)

        fitted = self.model_stage.fitted
        family = MODEL_REGISTRY[fitted.family]

        base = _score_of(family.predict_one(fitted, jnp.asarray(X)))

        # batched LOCO: variants[v] = X with group v zeroed; one device pass
        # over the (g+1 skipped base) stacked matrix
        variants = np.repeat(X[None, :, :], g, axis=0)
        for v, (_, idxs) in enumerate(groups):
            variants[v][:, idxs] = 0.0
        flat = variants.reshape(g * n, d)
        scores = _score_of(family.predict_one(fitted, jnp.asarray(flat)))
        scores = scores.reshape(g, n)
        diffs = base[None, :] - scores     # positive → slot pushed score up

        names = [name for name, _ in groups]
        out = np.empty(n, dtype=object)
        k = min(self.top_k, g)
        order = np.argsort(-np.abs(diffs), axis=0)[:k]   # (k, n)
        for i in range(n):
            top = {}
            for v in order[:, i]:
                if diffs[v, i] != 0.0:
                    top[names[v]] = round(float(diffs[v, i]), 6)
            out[i] = top
        return Column(TextMap, out, np.array([bool(o) for o in out]))

    def transform_row(self, row: Dict[str, Any]) -> Any:
        one = FeatureTable(
            {f.name: Column.of_values(f.feature_type, [row.get(f.name)])
             for f in self.input_features}, 1)
        return self.transform_column(one).values[0]


class RecordInsightsCorr(AllowLabelAsInput, Transformer):
    """OPVector → TextMap of {column name: value × corr(score, column)}.

    The correlation-flavored cousin (reference RecordInsightsCorr.scala):
    contributions are the row's standardized slot values scaled by each slot's
    correlation with the model score over the scoring batch.
    """

    input_types = (OPVector,)
    output_type = TextMap

    def __init__(self, model_stage, top_k: int = 20, uid=None):
        super().__init__("recordInsightsCorr", uid)
        self.model_stage = model_stage
        self.top_k = top_k

    def transform_column(self, table: FeatureTable) -> Column:
        from ..models.api import MODEL_REGISTRY
        from ..ops.stats import pearson_correlation
        import jax.numpy as jnp

        vec_f = self.input_features[0]
        col = table[vec_f.name]
        X = np.asarray(col.values, dtype=np.float32)
        n, d = X.shape
        vm = col.metadata.get("vector_meta")
        names = (vm.column_names() if vm is not None
                 else [f"c{i}" for i in range(d)])

        fitted = self.model_stage.fitted
        family = MODEL_REGISTRY[fitted.family]
        score = _score_of(family.predict_one(fitted, jnp.asarray(X)))

        corr = np.asarray(pearson_correlation(jnp.asarray(X),
                                              jnp.asarray(score)))
        corr = np.nan_to_num(corr)
        mean = X.mean(axis=0)
        std = X.std(axis=0)
        std[std == 0] = 1.0
        contrib = ((X - mean) / std) * corr[None, :]    # (n, d)

        out = np.empty(n, dtype=object)
        k = min(self.top_k, d)
        order = np.argsort(-np.abs(contrib), axis=1)[:, :k]
        for i in range(n):
            top = {}
            for j in order[i]:
                if contrib[i, j] != 0.0:
                    top[names[j]] = round(float(contrib[i, j]), 6)
            out[i] = top
        return Column(TextMap, out, np.array([bool(o) for o in out]))

    def transform_row(self, row: Dict[str, Any]) -> Any:
        raise ValueError(
            "RecordInsightsCorr needs a scoring batch to estimate "
            "correlations; use the columnar path")
