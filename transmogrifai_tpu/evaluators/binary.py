"""Binary classification evaluators (reference:
core/.../evaluators/OpBinaryClassificationEvaluator.scala,
OpBinScoreEvaluator.scala)."""
from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..ops.metrics import (
    aupr, aupr_masked, auroc, auroc_masked, binary_confusion,
    log_loss, log_loss_masked, threshold_metrics,
)
from ..table import FeatureTable
from ..utils.padding import bucket_for
from .base import OpEvaluatorBase


class OpBinaryClassificationEvaluator(OpEvaluatorBase):
    """Precision/Recall/F1/AuROC/AuPR/Error + confusion + threshold curves
    (reference OpBinaryClassificationEvaluator.evaluateAll:68)."""

    default_metric = "AuPR"
    larger_better = True

    def __init__(self, num_threshold_bins: int = 100, **kw):
        super().__init__(**kw)
        self.num_threshold_bins = num_threshold_bins

    def evaluate_all(self, table: FeatureTable) -> Dict[str, float]:
        label, parts = self._extract(table)
        prob = parts.get("probability")
        scores = prob[:, 1] if prob is not None and prob.shape[1] > 1 else \
            parts["prediction"]
        # rows bucket-padded (mask False, score below every threshold) so
        # the metric programs are shared across dataset sizes
        n = len(label)
        n_pad = bucket_for(n)
        lab = np.zeros(n_pad, np.float32)
        lab[:n] = label
        sc = np.full(n_pad, -1.0, np.float32)
        sc[:n] = scores
        mask = np.zeros(n_pad, bool)
        mask[:n] = True
        return self._metrics(jnp.asarray(lab), jnp.asarray(sc),
                             jnp.asarray(mask))

    def evaluate_arrays(self, label, scores, probability=None) -> float:
        s = probability if probability is not None else scores
        return float(aupr(jnp.asarray(s), jnp.asarray(label)))

    def _metrics(self, label, scores, mask) -> Dict[str, float]:
        w = mask.astype(scores.dtype)
        pred = (scores >= 0.5).astype(scores.dtype) * w
        pos = (label > 0.5).astype(scores.dtype) * w
        tp = float((pred * pos).sum())
        fp = float((pred * (w - pos)).sum())
        fn = float(((w - pred) * pos).sum())
        tn = float(w.sum()) - tp - fp - fn
        precision = tp / (tp + fp) if tp + fp > 0 else 0.0
        recall = tp / (tp + fn) if tp + fn > 0 else 0.0
        f1 = 2 * precision * recall / (precision + recall) if precision + recall > 0 else 0.0
        n = tp + tn + fp + fn
        # padded rows score -1 → never >= any threshold in [0, 1]
        thr, p_curve, r_curve, f1_curve = threshold_metrics(
            scores, label, num_bins=self.num_threshold_bins)
        return {
            "Precision": precision, "Recall": recall, "F1": f1,
            "AuROC": float(auroc_masked(scores, label, mask)),
            "AuPR": float(aupr_masked(scores, label, mask)),
            "Error": (fp + fn) / n if n > 0 else 0.0,
            "TP": tp, "TN": tn, "FP": fp, "FN": fn,
            "LogLoss": float(log_loss_masked(scores, label, mask)),
            "thresholds": np.asarray(thr).tolist(),
            "precisionByThreshold": np.asarray(p_curve).tolist(),
            "recallByThreshold": np.asarray(r_curve).tolist(),
            "f1ByThreshold": np.asarray(f1_curve).tolist(),
        }

    def evaluate(self, table: FeatureTable) -> float:
        return float(self.evaluate_all(table)[self.default_metric])


class OpBinScoreEvaluator(OpEvaluatorBase):
    """Calibration-bin metrics (reference OpBinScoreEvaluator.scala): score
    bins → average score vs conversion rate, plus Brier score."""

    default_metric = "BrierScore"
    larger_better = False

    def __init__(self, num_bins: int = 100, **kw):
        super().__init__(**kw)
        self.num_bins = num_bins

    def evaluate_all(self, table: FeatureTable) -> Dict[str, float]:
        label, parts = self._extract(table)
        prob = parts.get("probability")
        scores = prob[:, 1] if prob is not None and prob.shape[1] > 1 else \
            parts["prediction"]
        scores = np.asarray(scores, dtype=np.float64)
        label = np.asarray(label, dtype=np.float64)
        bins = np.clip((scores * self.num_bins).astype(int), 0, self.num_bins - 1)
        counts = np.bincount(bins, minlength=self.num_bins).astype(np.float64)
        score_sum = np.bincount(bins, weights=scores, minlength=self.num_bins)
        label_sum = np.bincount(bins, weights=label, minlength=self.num_bins)
        nz = np.maximum(counts, 1.0)
        return {
            "BrierScore": float(((scores - label) ** 2).mean()),
            "binCenters": ((np.arange(self.num_bins) + 0.5) / self.num_bins).tolist(),
            "numberOfDataPoints": counts.tolist(),
            "averageScore": (score_sum / nz).tolist(),
            "averageConversionRate": (label_sum / nz).tolist(),
        }
