"""Evaluator factory (reference: core/.../evaluators/Evaluators.scala)."""
from __future__ import annotations

from .binary import OpBinScoreEvaluator, OpBinaryClassificationEvaluator
from .multi import OpMultiClassificationEvaluator
from .regression import OpRegressionEvaluator


class Evaluators:
    class BinaryClassification:
        @staticmethod
        def auPR() -> OpBinaryClassificationEvaluator:
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "AuPR"
            return ev

        @staticmethod
        def auROC() -> OpBinaryClassificationEvaluator:
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "AuROC"
            return ev

        @staticmethod
        def precision() -> OpBinaryClassificationEvaluator:
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "Precision"
            return ev

        @staticmethod
        def recall() -> OpBinaryClassificationEvaluator:
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "Recall"
            return ev

        @staticmethod
        def f1() -> OpBinaryClassificationEvaluator:
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "F1"
            return ev

        @staticmethod
        def error() -> OpBinaryClassificationEvaluator:
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "Error"
            ev.larger_better = False
            return ev

        @staticmethod
        def brier_score() -> OpBinScoreEvaluator:
            return OpBinScoreEvaluator()

    class MultiClassification:
        @staticmethod
        def f1() -> OpMultiClassificationEvaluator:
            return OpMultiClassificationEvaluator()

        @staticmethod
        def error() -> OpMultiClassificationEvaluator:
            ev = OpMultiClassificationEvaluator()
            ev.default_metric = "Error"
            ev.larger_better = False
            return ev

        @staticmethod
        def precision() -> OpMultiClassificationEvaluator:
            ev = OpMultiClassificationEvaluator()
            ev.default_metric = "Precision"
            return ev

        @staticmethod
        def recall() -> OpMultiClassificationEvaluator:
            ev = OpMultiClassificationEvaluator()
            ev.default_metric = "Recall"
            return ev

    class Regression:
        @staticmethod
        def rmse() -> OpRegressionEvaluator:
            return OpRegressionEvaluator()

        @staticmethod
        def mse() -> OpRegressionEvaluator:
            ev = OpRegressionEvaluator()
            ev.default_metric = "MeanSquaredError"
            return ev

        @staticmethod
        def mae() -> OpRegressionEvaluator:
            ev = OpRegressionEvaluator()
            ev.default_metric = "MeanAbsoluteError"
            return ev

        @staticmethod
        def r2() -> OpRegressionEvaluator:
            ev = OpRegressionEvaluator()
            ev.default_metric = "R2"
            ev.larger_better = True
            return ev
