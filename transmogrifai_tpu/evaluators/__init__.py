from .base import OpEvaluatorBase, prediction_parts
from .binary import OpBinaryClassificationEvaluator, OpBinScoreEvaluator
from .multi import OpMultiClassificationEvaluator
from .regression import OpRegressionEvaluator
from .factory import Evaluators
