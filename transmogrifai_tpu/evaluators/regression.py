"""Regression evaluator (reference:
core/.../evaluators/OpRegressionEvaluator.scala)."""
from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from ..ops.metrics import regression_metrics
from ..table import FeatureTable
from .base import OpEvaluatorBase


class OpRegressionEvaluator(OpEvaluatorBase):
    """RMSE/MSE/MAE/R² (reference OpRegressionEvaluator.scala:107)."""

    default_metric = "RootMeanSquaredError"
    larger_better = False

    def evaluate_all(self, table: FeatureTable) -> Dict[str, float]:
        label, parts = self._extract(table)
        pred = parts["prediction"]
        return {k: float(v) for k, v in regression_metrics(
            jnp.asarray(pred), jnp.asarray(label)).items()}

    def evaluate_arrays(self, label, scores, probability=None) -> float:
        return float(regression_metrics(
            jnp.asarray(scores), jnp.asarray(label))["RootMeanSquaredError"])
