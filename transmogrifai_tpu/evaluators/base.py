"""Evaluator bases (reference: core/.../evaluators/OpEvaluatorBase.scala:113-235).

Evaluators read a fitted Prediction column — stored columnar as an (n, k)
float array with a ``keys`` tuple — plus the label column, and compute metric
dicts with jitted kernels.
"""
from __future__ import annotations

import abc
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..features import Feature
from ..table import Column, FeatureTable
from ..types import Prediction


def prediction_parts(col: Column) -> Dict[str, np.ndarray]:
    """Split a prediction column into prediction / probability / rawPrediction
    arrays (the analog of the reference's flattening of the Prediction map
    into columns, OpEvaluatorBase.scala:186-235)."""
    keys = tuple(col.metadata.get("keys", ()))
    vals = np.asarray(col.values)
    if not keys:
        # plain scalar column used as a prediction
        return {"prediction": vals.reshape(len(col))}
    out: Dict[str, Any] = {}
    key_idx = {k: i for i, k in enumerate(keys)}
    if Prediction.PredictionName in key_idx:
        out["prediction"] = vals[:, key_idx[Prediction.PredictionName]]
    for prefix in (Prediction.ProbabilityName, Prediction.RawPredictionName):
        idxs = sorted(
            ((int(k.rsplit("_", 1)[1]), i) for k, i in key_idx.items()
             if k.startswith(prefix + "_")),
        )
        if idxs:
            out[prefix] = vals[:, [i for _, i in idxs]]
    return out


class OpEvaluatorBase(abc.ABC):
    """Base evaluator: binds label/prediction feature names
    (reference OpEvaluatorBase.scala:113-180)."""

    #: the single metric used for model selection
    default_metric: str = ""
    #: larger-is-better for the default metric?
    larger_better: bool = True

    def __init__(self, label_col: Optional[str] = None,
                 prediction_col: Optional[str] = None):
        self.label_col = label_col
        self.prediction_col = prediction_col

    def set_label_col(self, feature_or_name) -> "OpEvaluatorBase":
        self.label_col = getattr(feature_or_name, "name", feature_or_name)
        return self

    def set_prediction_col(self, feature_or_name) -> "OpEvaluatorBase":
        self.prediction_col = getattr(feature_or_name, "name", feature_or_name)
        return self

    def _extract(self, table: FeatureTable) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        if self.label_col is None or self.prediction_col is None:
            raise ValueError("evaluator needs label_col and prediction_col")
        label = np.asarray(table[self.label_col].values, dtype=np.float32).reshape(-1)
        parts = prediction_parts(table[self.prediction_col])
        return label, parts

    @abc.abstractmethod
    def evaluate_all(self, table: FeatureTable) -> Dict[str, float]:
        """Compute all metrics for this evaluator."""

    def evaluate(self, table: FeatureTable) -> float:
        """The single default metric (used by ModelSelector)."""
        return float(self.evaluate_all(table)[self.default_metric])

    def evaluate_arrays(self, label: np.ndarray, scores: np.ndarray,
                        probability: Optional[np.ndarray] = None) -> float:
        """Array-level fast path used inside CV loops (no table plumbing)."""
        raise NotImplementedError
