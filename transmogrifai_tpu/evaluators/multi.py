"""Multiclass evaluator (reference:
core/.../evaluators/OpMultiClassificationEvaluator.scala)."""
from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from ..ops.metrics import multiclass_log_loss, multiclass_metrics
from ..table import FeatureTable
from .base import OpEvaluatorBase


class OpMultiClassificationEvaluator(OpEvaluatorBase):
    """Error / weighted Precision / Recall / F1, plus top-N threshold metrics
    (reference OpMultiClassificationEvaluator.scala; calculateThresholdMetrics
    :154-232 reduced to topK correctness curves)."""

    default_metric = "F1"
    larger_better = True

    def __init__(self, top_ns=(1, 3), **kw):
        super().__init__(**kw)
        self.top_ns = tuple(top_ns)

    def evaluate_all(self, table: FeatureTable) -> Dict[str, float]:
        label, parts = self._extract(table)
        pred = np.asarray(parts["prediction"], dtype=np.int32)
        label_idx = label.astype(np.int32)
        num_classes = int(max(pred.max(initial=0), label_idx.max(initial=0))) + 1
        out = {k: float(v) for k, v in multiclass_metrics(
            jnp.asarray(pred), jnp.asarray(label_idx), num_classes).items()}
        prob = parts.get("probability")
        if prob is not None:
            out["LogLoss"] = float(multiclass_log_loss(
                jnp.asarray(prob), jnp.asarray(label_idx)))
            order = np.argsort(-prob, axis=1)
            for n in self.top_ns:
                topn = order[:, :n]
                hit = (topn == label_idx[:, None]).any(axis=1)
                out[f"TopN_{n}_Accuracy"] = float(hit.mean())
        return out

    def evaluate_arrays(self, label, scores, probability=None) -> float:
        pred = np.asarray(scores, dtype=np.int32)
        label_idx = np.asarray(label, dtype=np.int32)
        num_classes = int(max(pred.max(initial=0), label_idx.max(initial=0))) + 1
        return float(multiclass_metrics(
            jnp.asarray(pred), jnp.asarray(label_idx), num_classes)["F1"])
