"""Multiclass evaluator (reference:
core/.../evaluators/OpMultiClassificationEvaluator.scala)."""
from __future__ import annotations

from typing import Dict

import jax.numpy as jnp
import numpy as np

from ..ops.metrics import multiclass_log_loss, multiclass_metrics
from ..table import FeatureTable
from .base import OpEvaluatorBase


class OpMultiClassificationEvaluator(OpEvaluatorBase):
    """Error / weighted Precision / Recall / F1, plus top-N threshold metrics
    (reference OpMultiClassificationEvaluator.scala; calculateThresholdMetrics
    :154-232 reduced to topK correctness curves)."""

    default_metric = "F1"
    larger_better = True

    def __init__(self, top_ns=(1, 3), thresholds=None, **kw):
        super().__init__(**kw)
        self.top_ns = tuple(top_ns)
        #: reference default: 0.0 to 1.0 by 0.1
        self.thresholds = tuple(
            thresholds if thresholds is not None
            else np.round(np.arange(0.0, 1.0001, 0.1), 2).tolist())

    def evaluate_all(self, table: FeatureTable) -> Dict[str, float]:
        label, parts = self._extract(table)
        pred = np.asarray(parts["prediction"], dtype=np.int32)
        label_idx = label.astype(np.int32)
        num_classes = int(max(pred.max(initial=0), label_idx.max(initial=0))) + 1
        out = {k: float(v) for k, v in multiclass_metrics(
            jnp.asarray(pred), jnp.asarray(label_idx), num_classes).items()}
        prob = parts.get("probability")
        if prob is not None:
            out["LogLoss"] = float(multiclass_log_loss(
                jnp.asarray(prob), jnp.asarray(label_idx)))
            order = np.argsort(-prob, axis=1)
            for n in self.top_ns:
                topn = order[:, :n]
                hit = (topn == label_idx[:, None]).any(axis=1)
                out[f"TopN_{n}_Accuracy"] = float(hit.mean())
            out["ThresholdMetrics"] = self.threshold_metrics(prob, label_idx)
        return out

    def threshold_metrics(self, prob: np.ndarray,
                          label_idx: np.ndarray) -> Dict[str, object]:
        """Per-threshold top-N correct / incorrect / no-prediction counts
        (reference calculateThresholdMetrics :154-232): a prediction is MADE
        at threshold t when max prob ≥ t; a made prediction is correct for
        topN when the true label ranks in the top N scores."""
        prob = np.asarray(prob, dtype=np.float64)
        label_idx = np.asarray(label_idx, dtype=np.int64)
        thr = np.asarray(self.thresholds, dtype=np.float64)
        made = prob.max(axis=1)[:, None] >= thr[None, :]      # (n, T)
        order = np.argsort(-prob, axis=1)
        correct = {}
        incorrect = {}
        no_pred = {}
        n_rows = prob.shape[0]
        for n in self.top_ns:
            hit = (order[:, :n] == label_idx[:, None]).any(axis=1)[:, None]
            correct[n] = (hit & made).sum(axis=0).tolist()
            incorrect[n] = (~hit & made).sum(axis=0).tolist()
            no_pred[n] = (n_rows - made.sum(axis=0)).tolist()
        return {
            "topNs": list(self.top_ns),
            "thresholds": thr.tolist(),
            "correctCounts": correct,
            "incorrectCounts": incorrect,
            "noPredictionCounts": no_pred,
        }

    def evaluate_arrays(self, label, scores, probability=None) -> float:
        pred = np.asarray(scores, dtype=np.int32)
        label_idx = np.asarray(label, dtype=np.int32)
        num_classes = int(max(pred.max(initial=0), label_idx.max(initial=0))) + 1
        return float(multiclass_metrics(
            jnp.asarray(pred), jnp.asarray(label_idx), num_classes)["F1"])
