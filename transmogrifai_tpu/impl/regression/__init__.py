from .isotonic import IsotonicRegressionCalibrator  # noqa: F401
