"""Isotonic regression calibrator.

Mirrors the reference's IsotonicRegressionCalibrator (reference:
core/.../impl/regression/IsotonicRegressionCalibrator.scala — wraps Spark
``IsotonicRegression`` to calibrate scores against a binary label).

The fit is classic pool-adjacent-violators (PAV). PAV is inherently
sequential, but it runs over the *distinct sorted scores* — after an initial
device-side sort + segment reduction the host loop touches only the pooled
blocks, so the O(n) part stays columnar. Prediction is linear interpolation
between breakpoints (Spark semantics), which is a jittable ``jnp.interp``.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ...stages.base import AllowLabelAsInput, BinaryEstimator, Transformer
from ...table import Column, FeatureTable
from ...types import RealNN


def pav_fit(scores: np.ndarray, labels: np.ndarray,
            weights: Optional[np.ndarray] = None):
    """Pool-adjacent-violators: returns (boundaries, values) — the isotonic
    step/interpolation points, increasing in both arrays."""
    order = np.argsort(scores, kind="stable")
    x = np.asarray(scores, np.float64)[order]
    y = np.asarray(labels, np.float64)[order]
    w = np.ones_like(y) if weights is None else \
        np.asarray(weights, np.float64)[order]
    # blocks as (sum_wy, sum_w, x_min, x_max)
    blocks: list = []
    for xi, yi, wi in zip(x, y, w):
        blocks.append([yi * wi, wi, xi, xi])
        while len(blocks) >= 2 and (
                blocks[-2][0] * blocks[-1][1] >=
                blocks[-1][0] * blocks[-2][1]):  # mean[-2] >= mean[-1]
            b = blocks.pop()
            blocks[-1][0] += b[0]
            blocks[-1][1] += b[1]
            blocks[-1][3] = b[3]
        # merge identical x so boundaries stay strictly increasing
    bounds, vals = [], []
    for swy, sw, x0, x1 in blocks:
        v = swy / max(sw, 1e-12)
        if bounds and x0 <= bounds[-1]:
            vals[-1] = (vals[-1] + v) / 2.0
            continue
        if x0 == x1:
            bounds.append(x0)
            vals.append(v)
        else:
            bounds.extend([x0, x1])
            vals.extend([v, v])
    return np.asarray(bounds, np.float32), np.asarray(vals, np.float32)


class IsotonicCalibratorModel(AllowLabelAsInput, Transformer):
    output_type = RealNN

    def __init__(self, boundaries: np.ndarray, values: np.ndarray, uid=None):
        super().__init__("calibrate", uid)
        self.boundaries = boundaries
        self.values = values
        self.summary_metadata: Dict[str, Any] = {
            "boundaries": boundaries.tolist(), "predictions": values.tolist()}

    def _interp(self, s):
        import jax.numpy as jnp
        if len(self.boundaries) == 0:
            return jnp.zeros_like(s)
        return jnp.interp(s, jnp.asarray(self.boundaries),
                          jnp.asarray(self.values))

    def transform_column(self, table: FeatureTable) -> Column:
        import jax.numpy as jnp
        _, score_f = self.input_features
        s = jnp.asarray(np.asarray(table[score_f.name].values, np.float32))
        return Column(RealNN, np.asarray(self._interp(s)), None)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        _, score_f = self.input_features
        v = row.get(score_f.name)
        if v is None:
            return None
        import jax.numpy as jnp
        return float(self._interp(jnp.asarray([float(v)], jnp.float32))[0])


class IsotonicRegressionCalibrator(AllowLabelAsInput, BinaryEstimator):
    """Estimator2[RealNN label, RealNN score] → RealNN calibrated score."""

    def __init__(self, isotonic: bool = True, uid=None):
        def fit_fn(label_col, score_col):
            y = np.asarray(label_col.values, np.float64)
            s = np.asarray(score_col.values, np.float64)
            m = label_col.valid_mask() & score_col.valid_mask()
            if isotonic:
                b, v = pav_fit(s[m], y[m])
            else:
                # antitonic: fit on negated scores, mirror back so the stored
                # boundaries stay increasing for jnp.interp
                b, v = pav_fit(-s[m], y[m])
                b, v = -b[::-1], v[::-1]
            return {"boundaries": np.ascontiguousarray(b),
                    "values": np.ascontiguousarray(v)}

        super().__init__(
            "calibrate", fit_fn, RealNN,
            make_model=lambda st: IsotonicCalibratorModel(
                st["boundaries"], st["values"]),
            input_types=(RealNN, RealNN), uid=uid)
        self.isotonic = isotonic
