"""Data splitters: test reservation + class balancing / cutting.

(reference: core/.../impl/tuning/Splitter.scala:62-100, DataSplitter.scala,
DataBalancer.scala, DataCutter.scala)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np


@dataclass
class PreparedData:
    """Outcome of pre-validation preparation: row indices into the original
    arrays (resampling expressed as indices, possibly repeated for upsampling)
    plus metadata about what was done."""
    indices: np.ndarray
    summary: Dict[str, Any] = field(default_factory=dict)
    label_mapping: Optional[Dict[int, int]] = None  # DataCutter re-indexing


class Splitter:
    """Base: reserve a test fraction, prepare train data
    (reference Splitter.scala:62-100)."""

    def __init__(self, reserve_test_fraction: float = 0.1, seed: int = 42):
        if not 0.0 <= reserve_test_fraction < 1.0:
            raise ValueError("reserve_test_fraction must be in [0, 1)")
        self.reserve_test_fraction = reserve_test_fraction
        self.seed = seed
        self.summary: Dict[str, Any] = {}

    def split(self, n: int) -> Tuple[np.ndarray, np.ndarray]:
        """(train_idx, test_idx) random split."""
        rng = np.random.RandomState(self.seed)
        perm = rng.permutation(n)
        n_test = int(round(n * self.reserve_test_fraction))
        return np.sort(perm[n_test:]), np.sort(perm[:n_test])

    def pre_validation_prepare(self, y: np.ndarray) -> PreparedData:
        """Estimate and apply balancing/cutting on the train split
        (reference preValidationPrepare). Default: identity."""
        return PreparedData(indices=np.arange(len(y)))

    def validation_prepare(self, y: np.ndarray) -> PreparedData:
        """Preparation applied before the final refit on full train data
        (reference validationPrepare). Default: same as pre-validation."""
        return self.pre_validation_prepare(y)


class DataSplitter(Splitter):
    """Plain random split, regression problems (reference DataSplitter.scala:62-85)."""


class DataBalancer(Splitter):
    """Binary classification balancer (reference DataBalancer.scala:125-163,
    estimate :208): if the positive fraction is below ``sample_fraction``,
    down-sample the majority class (and optionally up-sample the minority) so
    positives make up ~sample_fraction of the result, capped at
    ``max_training_sample`` rows."""

    def __init__(self, sample_fraction: float = 0.1,
                 max_training_sample: int = 1_000_000,
                 already_balanced_fraction_cutoff: float = 0.3, **kw):
        super().__init__(**kw)
        self.sample_fraction = sample_fraction
        self.max_training_sample = max_training_sample
        self.already_balanced_fraction_cutoff = already_balanced_fraction_cutoff

    def pre_validation_prepare(self, y: np.ndarray) -> PreparedData:
        rng = np.random.RandomState(self.seed)
        pos_idx = np.nonzero(y > 0.5)[0]
        neg_idx = np.nonzero(y <= 0.5)[0]
        n_pos, n_neg = len(pos_idx), len(neg_idx)
        n = n_pos + n_neg
        small, big = (pos_idx, neg_idx) if n_pos <= n_neg else (neg_idx, pos_idx)
        frac = len(small) / max(n, 1)
        summary: Dict[str, Any] = {
            "positiveCount": int(n_pos), "negativeCount": int(n_neg),
            "minorityFraction": frac, "balanced": False,
        }
        if frac >= min(self.sample_fraction, self.already_balanced_fraction_cutoff) \
                or len(small) == 0:
            idx = np.arange(n)
            if n > self.max_training_sample:
                idx = np.sort(rng.choice(n, self.max_training_sample, replace=False))
                summary["downsampledTo"] = self.max_training_sample
            self.summary = summary
            return PreparedData(indices=idx, summary=summary)
        # downsample majority so minority fraction ≈ sample_fraction
        target_big = int(len(small) * (1.0 - self.sample_fraction) / self.sample_fraction)
        target_big = max(min(target_big, len(big)), len(small))
        big_keep = rng.choice(big, target_big, replace=False)
        idx = np.sort(np.concatenate([small, big_keep]))
        if len(idx) > self.max_training_sample:
            idx = np.sort(rng.choice(idx, self.max_training_sample, replace=False))
        summary.update({"balanced": True,
                        "downsampledMajorityTo": int(target_big),
                        "resultSize": int(len(idx))})
        self.summary = summary
        return PreparedData(indices=idx, summary=summary)


class DataCutter(Splitter):
    """Multiclass label cutter (reference DataCutter.scala:85,170): keep at
    most ``max_label_categories`` labels and only labels with at least
    ``min_label_fraction``; drop rows with other labels and re-index labels
    to a dense 0..K-1 range."""

    def __init__(self, max_label_categories: int = 100,
                 min_label_fraction: float = 0.0, **kw):
        super().__init__(**kw)
        if min_label_fraction >= 0.5:
            raise ValueError("min_label_fraction must be < 0.5")
        self.max_label_categories = max_label_categories
        self.min_label_fraction = min_label_fraction

    def pre_validation_prepare(self, y: np.ndarray) -> PreparedData:
        labels, counts = np.unique(y.astype(np.int64), return_counts=True)
        frac = counts / counts.sum()
        order = np.argsort(-counts)
        kept = [labels[i] for i in order[: self.max_label_categories]
                if frac[i] >= self.min_label_fraction]
        kept_set = set(int(k) for k in kept)
        if not kept_set:
            raise ValueError("DataCutter dropped all labels")
        mask = np.isin(y.astype(np.int64), list(kept_set))
        mapping = {int(lab): i for i, lab in enumerate(sorted(kept_set))}
        summary = {"labelsKept": sorted(kept_set),
                   "labelsDropped": sorted(set(int(l) for l in labels) - kept_set),
                   "rowsKept": int(mask.sum())}
        self.summary = summary
        return PreparedData(indices=np.nonzero(mask)[0], summary=summary,
                            label_mapping=mapping)
