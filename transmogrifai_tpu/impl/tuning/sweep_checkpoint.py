"""Sweep-level checkpointing: per-candidate results survive preemption.

Stage checkpoints (persistence.py) make the DAG resumable at estimator
granularity — but the ModelSelector is ONE estimator whose fit sweeps
families × grids × folds, the most expensive single fit of the train path.
A preemption mid-sweep used to lose every already-evaluated candidate.

This module persists one record per evaluated candidate batch (a model
family's whole fused branch — the unit of execution on device) into
``sweep_<selector-uid>.json`` inside the workflow checkpoint dir, committed
atomically through the shared :class:`~..manifest.CheckpointManifest`. A
resumed ``train()`` replays matching records (fold metrics restored
bit-exactly via the recorded dtype) and dispatches only the remainder; the
winner selection then recomputes deterministically from the merged metrics.

Records are keyed by a candidate fingerprint — family, canonical grid,
fold/metric configuration, row count and a sha256 of the label vector and
fold assignment — so a checkpoint from different data, folds, or sweep
fidelity can never be replayed onto this run.

The reference has no analog: Spark re-runs the whole selector fit from
lineage. Persist-and-skip is strictly stronger for hour-long sweeps on
preemptible capacity.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ...manifest import CheckpointManifest
from ...robustness.policy import FaultLog, FaultReport

SWEEP_STATE_VERSION = 1


def candidate_key(family: str, grid: List[Dict[str, Any]],
                  fingerprint: Dict[str, Any]) -> str:
    """Stable fingerprint of one family's sweep branch: the family, its
    canonical grid, and the run fingerprint (fold config, metric, data
    hashes). Any difference → different key → no replay."""
    doc = {"family": family,
           "grid": [sorted((k, repr(v)) for k, v in g.items()) for g in grid],
           "fingerprint": {k: fingerprint[k] for k in sorted(fingerprint)}}
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode("utf-8")).hexdigest()


def params_hash(hyper: Dict[str, Any]) -> str:
    """sha256 of one candidate's canonical hyperparameter dict — the
    identity a restored record is matched and audited by."""
    return hashlib.sha256(json.dumps(
        sorted((k, repr(v)) for k, v in hyper.items())).encode()).hexdigest()


class SweepCheckpoint:
    """Durable per-candidate sweep state for one selector stage.

    ``get``/``put`` operate on whole-family records::

        {"family": "OpGBTClassifier",
         "grid": [...hyper dicts...],
         "paramsHashes": ["<sha256 per grid point>"],
         "metricName": "AuPR",
         "foldMetrics": [[...], ...],   # (F, G), null for non-finite
         "dtype": "float32",            # restores metrics bit-exactly
         "quarantined": false,          # family branch threw pre-dispatch
         "reason": null}

    Every ``put`` rewrites the state file atomically and commits it through
    the directory manifest, so the file always holds a consistent prefix of
    the sweep and a torn write is impossible.
    """

    def __init__(self, ckpt_dir: str, owner_uid: str,
                 manifest: Optional[CheckpointManifest] = None):
        from ...persistence import open_checkpoint_manifest
        self.ckpt_dir = ckpt_dir
        self.owner_uid = owner_uid
        self.fname = f"sweep_{owner_uid}.json"
        self.path = os.path.join(ckpt_dir, self.fname)
        self.manifest = manifest or open_checkpoint_manifest(ckpt_dir)
        self._state: Dict[str, Any] = {"sweepStateVersion": SWEEP_STATE_VERSION,
                                       "candidates": {}}
        self._load()

    def _load(self) -> None:
        if not os.path.isfile(self.path):
            return
        reason = None
        if self.manifest.sweeps.get(self.owner_uid):
            reason = self.manifest.verify_file(self.fname)
        elif self.manifest.files or self.manifest.stages:
            reason = "sweep state has no manifest completion record"
        if reason is not None:
            FaultLog.record(FaultReport(
                site="persistence.sweep", kind="checkpoint_skipped",
                detail={"uid": self.owner_uid, "file": self.path,
                        "reason": reason, "error": reason}))
            return
        try:
            with open(self.path) as fh:
                doc = json.load(fh)
            if doc.get("sweepStateVersion") != SWEEP_STATE_VERSION:
                raise ValueError(
                    f"sweep state version {doc.get('sweepStateVersion')!r}")
            self._state = doc
        except (OSError, ValueError) as e:
            FaultLog.record(FaultReport(
                site="persistence.sweep", kind="checkpoint_skipped",
                detail={"uid": self.owner_uid, "file": self.path,
                        "reason": f"{type(e).__name__}: {e}",
                        "error": f"{type(e).__name__}: {e}"}))

    # -- record access -------------------------------------------------------
    def get(self, cand_key: str) -> Optional[Dict[str, Any]]:
        return self._state["candidates"].get(cand_key)

    def put(self, cand_key: str, record: Dict[str, Any]) -> None:
        from ...manifest import atomic_write_bytes
        self._state["candidates"][cand_key] = record
        data = json.dumps(self._state).encode("utf-8")
        sha = atomic_write_bytes(self.path, data)
        self.manifest.record_file(self.fname, sha, len(data))
        self.manifest.complete_sweep(self.owner_uid, self.fname)
        self.manifest.save()

    # -- metric (de)hydration ------------------------------------------------
    @staticmethod
    def encode_metrics(fold_metrics: np.ndarray) -> Dict[str, Any]:
        """JSON-safe (F, G) metrics: non-finite → null/str markers, dtype
        kept so decoding reproduces the array bit-for-bit (float32 → python
        float widens exactly; json repr round-trips float64 exactly)."""
        fm = np.asarray(fold_metrics)

        def enc(v: float):
            if np.isnan(v):
                return None
            if np.isinf(v):
                return "inf" if v > 0 else "-inf"
            return float(v)
        return {"foldMetrics": [[enc(v) for v in row] for row in fm],
                "dtype": str(fm.dtype)}

    @staticmethod
    def decode_metrics(record: Dict[str, Any]) -> np.ndarray:
        def dec(v):
            if v is None:
                return np.nan
            if v == "inf":
                return np.inf
            if v == "-inf":
                return -np.inf
            return v
        rows = [[dec(v) for v in row] for row in record["foldMetrics"]]
        return np.asarray(rows, dtype=np.dtype(record.get("dtype",
                                                          "float64")))
