"""Validators: cross-validation and train/validation split over vmapped grids.

The TPU re-design of the reference's thread-pool validator
(reference: core/.../impl/tuning/OpValidator.scala:270-322 — one Scala Future
per model × fold, pool of 8; OpCrossValidation.scala:139-181 kFold;
OpTrainValidationSplit.scala:40-80): here folds become static 0/1 row-mask
vectors, and the whole |folds| × |grid| sweep for a model family is ONE
``fit_batch`` call — a single jitted, vmapped XLA program whose inner matmuls
tile onto the MXU. Parallelism is not 8 threads; it is the full batch dimension
on device, shardable across chips over the 'model' mesh axis.
"""
from __future__ import annotations

import functools
import logging
import os
import warnings

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...models.api import FittedParams, ModelFamily
from ...observability import blackbox as _blackbox
from ...observability import devicemem as _devicemem
from ...observability import ledger as _obs_ledger
from ...observability import metrics as _obs_metrics
from ...observability import trace as _obs_trace
from ...observability.trace import span as _obs_span, tracing_enabled
from ...robustness import faults
from ...robustness.guards import (
    AllCandidatesFailedError, quarantine_non_finite,
)
from ...robustness.policy import FaultLog, FaultReport
from ...utils.fidelity import ROUND4_MAX_EVAL_ROWS, round4_defaults
from ...utils.padding import bucket_for

logger = logging.getLogger(__name__)
from ...ops.metrics import (
    aupr_masked, auroc_masked, binary_threshold_metrics_masked,
    log_loss_masked, multiclass_metrics_masked, regression_metrics_masked,
)


@dataclass
class ValidationResult:
    """Per-(family, grid-point) averaged validation metric
    (reference ModelSelectorSummary validation results)."""
    family: str
    grid: List[Dict[str, Any]]
    metric_name: str
    fold_metrics: np.ndarray        # (F, G)
    mean_metrics: np.ndarray        # (G,)

    def to_json(self):
        return {
            "modelType": self.family,
            "metricName": self.metric_name,
            "grid": self.grid,
            "foldMetrics": self.fold_metrics.tolist(),
            "meanMetrics": self.mean_metrics.tolist(),
        }


@dataclass
class BestEstimator:
    """Winner of validation (reference OpValidator.wrapBestEstimator :147).
    ``quarantined`` carries the records of candidates excluded from
    selection (non-finite metrics or a fit that threw) — they surface in
    ``ModelSelectorSummary`` with their failure reasons."""
    family_name: str
    hyper: Dict[str, Any]
    metric_value: float
    results: List[ValidationResult] = field(default_factory=list)
    quarantined: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class PendingValidation:
    """A queued-but-unsynced validate(): every family's device programs are
    dispatched; ``resolve()`` materializes the metrics and picks the winner.
    Lets workflow-level CV queue ALL folds' programs back-to-back before a
    single host sync (the reference's analog: concurrent fold Futures,
    OpValidator.applyDAG :228-256)."""
    _finish: Any

    def resolve(self) -> BestEstimator:
        return self._finish()


@functools.lru_cache(maxsize=None)
def _metric_fn(problem: str, metric: str, batched_y: bool = False,
               binned: "Optional[bool]" = None):
    """Jitted batched metric over (B, n) scores with (B, n) val masks,
    honoring the evaluator's requested metric name (reference: the validator
    optimizes whatever evaluator the selector was configured with).
    ``batched_y``: labels are (B, n) per-config (the fold-sliced scoring
    path, where each config's rows are its own fold's validation rows)
    instead of one shared (n,) vector."""
    y_ax = 0 if batched_y else None
    if problem == "binary":
        if metric in ("AuPR", "AuROC"):
            base = {"AuPR": aupr_masked, "AuROC": auroc_masked}[metric]
            if binned is not None:
                from functools import partial as _partial
                base = _partial(base, binned=binned)
            return jax.jit(jax.vmap(base, in_axes=(0, y_ax, 0)))
        if metric in ("Precision", "Recall", "F1", "Error"):
            def one_b(scores, y, mask):
                return binary_threshold_metrics_masked(scores, y, mask)[metric]
            return jax.jit(jax.vmap(one_b, in_axes=(0, y_ax, 0)))
        if metric == "LogLoss":
            return jax.jit(jax.vmap(log_loss_masked, in_axes=(0, y_ax, 0)))
        raise ValueError(f"unknown binary validation metric '{metric}'")
    if problem == "multiclass":
        if metric not in ("F1", "Precision", "Recall", "Error"):
            raise ValueError(f"unknown multiclass validation metric '{metric}'")

        def one(probs, y, mask, num_classes):
            pred = probs.argmax(axis=-1).astype(jnp.int32)
            return multiclass_metrics_masked(
                pred, y.astype(jnp.int32), mask, num_classes)[metric]
        return jax.jit(jax.vmap(one, in_axes=(0, y_ax, 0, None)),
                       static_argnums=(3,))
    if problem == "regression":
        if metric not in ("RootMeanSquaredError", "MeanSquaredError",
                          "MeanAbsoluteError", "R2"):
            raise ValueError(f"unknown regression validation metric '{metric}'")

        def one_r(pred, y, mask):
            return regression_metrics_masked(pred, y, mask)[metric]
        return jax.jit(jax.vmap(one_r, in_axes=(0, y_ax, 0)))
    raise ValueError(problem)


#: fused per-family sweep programs, keyed by (family, grid, fold/metric
#: config) — reused across validate() calls so bench reps and repeated
#: workflow fits pay one compile. LRU-bounded: each entry pins a jitted
#: executable plus its tiled host grid constants, so a long-lived process
#: fitting many distinct grids would otherwise grow compiled-program memory
#: without bound (eviction just re-pays the pre-existing compile cost)
_FUSED_CACHE: "OrderedDict[Any, Any]" = OrderedDict()
_FUSED_CACHE_MAX = int(os.environ.get("TG_FUSED_CACHE_MAX", "32"))


def _arg_nbytes(a) -> int:
    """Device bytes of one dispatch argument (shape × itemsize)."""
    try:
        itemsize = int(np.dtype(getattr(a, "dtype", np.float32)).itemsize)
    except TypeError:
        itemsize = 4
    return int(np.prod(np.shape(a))) * itemsize


def _fused_cache_get(key):
    prog = _FUSED_CACHE.get(key)
    if prog is not None:
        _FUSED_CACHE.move_to_end(key)
    return prog


def _fused_cache_put(key, prog) -> None:
    _FUSED_CACHE[key] = prog
    _FUSED_CACHE.move_to_end(key)
    while len(_FUSED_CACHE) > _FUSED_CACHE_MAX:
        evicted_key, _ = _FUSED_CACHE.popitem(last=False)
        # the compile ledger classifies the eventual rebuild of this key
        # as cache-eviction instead of an unexplained cold build
        _obs_ledger.record_eviction(_obs_ledger.cache_key_hash(evicted_key))


def _hist_mesh_ctx(family, mesh):
    """Histogram-engine mesh context for a family's program trace/export:
    tree families (``uses_hist_engine``) pin their K-blocked contraction's
    row blocks to the 'data' axis; everything else is a no-op context."""
    if mesh is not None and getattr(family, "uses_hist_engine", False):
        from ...histeng import engine_mesh
        return engine_mesh(mesh)
    import contextlib
    return contextlib.nullcontext()


def clear_mesh_programs() -> None:
    """Drop mesh-keyed fused programs. Each pins a ``jax.sharding.Mesh``
    plus per-device executable buffers; the test harness asserts none leak
    across tests (a stale program keyed to a dead 8-device test mesh would
    silently hold every device's buffers alive for the whole session)."""
    from jax.sharding import Mesh
    for k in [k for k in _FUSED_CACHE
              if any(isinstance(e, Mesh) for e in k)]:
        _FUSED_CACHE.pop(k, None)


def mesh_program_keys():
    """Cache keys of mesh-compiled fused programs (no-leak fixture probe)."""
    from jax.sharding import Mesh
    return [k for k in _FUSED_CACHE
            if any(isinstance(e, Mesh) for e in k)]


def _make_fused_program(family, garr_np, G: int, F: int, problem: str,
                        metric_name: str, num_classes: int, exact: bool,
                        sliced: bool, binned, mesh=None, x_ndim: int = 2):
    """ONE jitted program for a family's whole sweep branch: build the fold
    weights from the per-row fold ids, fit all F·G configs, score each
    fold's validation partition, and reduce to the padded metric vector.

    Fusing the branch removes the per-executable dispatch bubbles of the
    eager glue (measured ~2.7 ms × ~900 small executables on the tunneled
    TPU backend — the glue, not the math, was ~45% of the default sweep's
    wall-clock) and lets XLA dead-code-eliminate every fitted parameter the
    sweep never reads (only the metric vector leaves the program; e.g. tree
    raw-threshold tables exist solely for the refit path). The grid arrays
    are host constants, so the tree families' per-depth bucketing stays
    static under the trace.

    ``mesh``: compile the same branch as one GSPMD program with explicit
    ``NamedSharding`` in/out specs — rows over 'data', the (F·G) config
    batch over 'model' (families with ``shardable=False`` keep their
    configs whole and only shard rows). The fold train-weights are built
    INSIDE the trace from the uint8 fold-id vector, so no (F, n) tensor is
    ever assembled on the host or device_put per family. The metric stage
    is re-sharded config-parallel/row-replicated (``P('model', None)``):
    the sort/cumulative-scan chain of AuROC/AuPR is partitioner-hostile
    along the row axis (XLA's SPMD pass miscompiles the composed
    scan+concat sequence when rows are sharded — see docs/parallel.md),
    and per-config metrics over replicated rows are both correct and the
    natural parallel axis. Families with ``traced_grid_ok`` take their
    tiled grid as ONE packed (keys, F·G) f32 device argument, sharded over
    'model' and DONATED — XLA may alias the block for per-family scratch
    instead of re-allocating; tree families keep host-constant grids (their
    per-depth bucketing must stay static under the trace). Returns
    ``(prog, grid_keys)`` where ``grid_keys`` is None for constant-grid
    families and the packed-block key order otherwise.
    """
    B_true = F * G
    B_m = -(-B_true // 32) * 32
    metric = _metric_fn(problem, metric_name, batched_y=sliced, binned=binned)
    tiled = {k: np.tile(v, F) for k, v in garr_np.items()}
    shardable = getattr(family, "shardable", True) if mesh is not None \
        else True
    traced_grid = (mesh is not None and shardable
                   and getattr(family, "traced_grid_ok", False))
    grid_keys = tuple(sorted(tiled)) if traced_grid else None

    def prog(X, y, ids_d, *rest):
        # call convention: [Xf, yf, fvalid] when sliced, then [gblock]
        # when the family takes its grid as a traced (donated) argument
        Xf = yf = fvalid = gblock = None
        if sliced:
            Xf, yf, fvalid = rest[0], rest[1], rest[2]
            rest = rest[3:]
        if traced_grid:
            gblock = rest[0]
        f_iota = jnp.arange(F, dtype=jnp.uint8)[:, None]
        train_w = ((ids_d[None, :] != f_iota)
                   & (ids_d[None, :] != jnp.uint8(F + 1))
                   ).astype(jnp.float32)                    # (F, n)
        W = jnp.repeat(train_w, G, axis=0)                  # (F*G, n)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            W = jax.lax.with_sharding_constraint(
                W, NamedSharding(mesh, P("model" if shardable else None,
                                         "data")))
        # gblock's config axis is zero-padded up to the 'model'-shard
        # multiple (device_put demands divisibility); slice before the fit
        g = ({k: gblock[i][:B_true] for i, k in enumerate(grid_keys)}
             if traced_grid else tiled)
        params = (family.fit_batch(X, y, W, g, num_classes) if exact
                  else family.sweep_fit_batch(X, y, W, g, num_classes))
        if sliced:
            per_fold = [
                family.predict_batch(
                    family.slice_params(params, f * G, (f + 1) * G),
                    Xf[f], num_classes)
                for f in range(F)
            ]
            scores = jnp.concatenate(per_fold, axis=0)      # (F*G, nf[, C])
            Y = jnp.repeat(yf, G, axis=0)
            VM = jnp.repeat(fvalid, G, axis=0)
        else:
            scores = family.predict_batch(params, X, num_classes)
            Y = y
            VM = jnp.repeat(ids_d[None, :] == f_iota, G, axis=0)
        if B_m != B_true:
            scores = jnp.pad(scores, ((0, B_m - B_true),)
                             + ((0, 0),) * (scores.ndim - 1))
            VM = jnp.pad(VM, ((0, B_m - B_true), (0, 0)))
            if sliced:
                Y = jnp.pad(Y, ((0, B_m - B_true), (0, 0)))
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            cfg_sh = NamedSharding(
                mesh, P("model", *([None] * (scores.ndim - 1))))
            row_sh = NamedSharding(mesh, P("model", None))
            scores = jax.lax.with_sharding_constraint(scores, cfg_sh)
            VM = jax.lax.with_sharding_constraint(VM, row_sh)
            # Y: (B, nf) per-config labels when sliced, the shared (n,)
            # vector otherwise — either way the metric stage needs its row
            # axis REPLICATED (see the partitioner note above)
            Y = jax.lax.with_sharding_constraint(
                Y, row_sh if sliced else NamedSharding(mesh, P(None)))
        if problem == "multiclass":
            return metric(scores, Y, VM, num_classes)
        return metric(scores, Y, VM)

    if mesh is None:
        return jax.jit(prog), grid_keys
    from jax.sharding import NamedSharding, PartitionSpec as P
    row = lambda nd: NamedSharding(mesh, P("data", *([None] * (nd - 1))))
    in_sh = [row(x_ndim), row(1), row(1)]
    if sliced:
        # Xf feeds the row-parallel per-fold predicts → rows over 'data';
        # yf / fvalid are consumed ONLY by the config-parallel metric
        # stage, which needs rows replicated — uploading them sharded just
        # buys an all-gather (and an XLA "involuntary rematerialization"
        # warning) inside every family's program
        in_sh += [NamedSharding(mesh, P(None, "data",
                                        *([None] * (x_ndim - 1)))),
                  NamedSharding(mesh, P(None)),
                  NamedSharding(mesh, P(None))]
    donate = ()
    if traced_grid:
        in_sh.append(NamedSharding(mesh, P(None, "model")))
        donate = (len(in_sh) - 1,)
    return jax.jit(prog, in_shardings=tuple(in_sh),
                   out_shardings=NamedSharding(mesh, P(None)),
                   donate_argnums=donate), grid_keys


class OpValidator:
    """Shared validation machinery (reference OpValidator.scala).

    ``mesh``: optional ``jax.sharding.Mesh`` with ('data', 'model') axes —
    rows shard over 'data' and the config batch over 'model' (for families
    whose fit is a single vmapped program; sequential-scan families keep
    their configs whole and still get row sharding). The reference's analog
    is its 8-thread Future pool (OpValidator.scala:318-333); here the
    parallel axes are mesh axes and XLA inserts the psum collectives."""

    #: sentinel: "caller did not choose" — the constructor resolves it to
    #: 32768 (round-5 default) or 65536 under TG_SWEEP_FIDELITY=round4
    _EVAL_ROWS_DEFAULT = -1

    def __init__(self, seed: int = 42, stratify: bool = False, mesh=None,
                 max_eval_rows: "Optional[int]" = _EVAL_ROWS_DEFAULT,
                 exact_sweep_fits: bool = False):
        if max_eval_rows == self._EVAL_ROWS_DEFAULT:
            max_eval_rows = (ROUND4_MAX_EVAL_ROWS if round4_defaults()
                             else 32768)
        self.seed = seed
        self.stratify = stratify
        self.mesh = mesh
        #: fold-sliced validation scoring evaluates each configuration on at
        #: most this many of its fold's rows (deterministic strided
        #: subsample). Metric ESTIMATES only — refit, holdout and train
        #: evaluations always use full data. None = score every validation
        #: row (exact reference parity); the default trades ~3e-3 of AuROC
        #: estimator noise for a ~10x cut in sweep predict time at 1M+ rows.
        #: Measured fidelity of the default vs the exact setting:
        #: docs/benchmarks.md "Sweep fidelity".
        self.max_eval_rows = max_eval_rows
        #: True = CV candidates fit through ``fit_batch`` (full precision /
        #: full split-search sample) instead of ``sweep_fit_batch``'s
        #: throughput approximations — exact reference semantics
        #: (OpValidator.getSummary:270-312 full-data fits) at several times
        #: the sweep cost
        self.exact_sweep_fits = exact_sweep_fits

    # -- fold construction ---------------------------------------------------
    def make_splits(self, y: np.ndarray) -> np.ndarray:
        """(F, n) boolean VALIDATION masks; train mask = ~val."""
        raise NotImplementedError

    def _kfold_masks(self, y: np.ndarray, k: int) -> np.ndarray:
        n = len(y)
        rng = np.random.RandomState(self.seed)
        masks = np.zeros((k, n), dtype=bool)
        if self.stratify:
            # per-class round-robin folds (reference stratified kFold union
            # OpCrossValidation.scala:139-181)
            for lab in np.unique(y):
                idx = np.nonzero(y == lab)[0]
                idx = rng.permutation(idx)
                for f in range(k):
                    masks[f, idx[f::k]] = True
        else:
            perm = rng.permutation(n)
            for f in range(k):
                masks[f, perm[f::k]] = True
        return masks

    # -- the sweep -----------------------------------------------------------
    def validate(self, models: Sequence[Tuple[ModelFamily, List[Dict[str, Any]]]],
                 X: jnp.ndarray, y: jnp.ndarray, problem: str,
                 metric_name: str, larger_better: bool, num_classes: int,
                 val_masks: Optional[np.ndarray] = None,
                 fold_sliced: Optional[bool] = None,
                 resolve: bool = True):
        """Run the full |families| × |grid| × |folds| sweep. Each family is one
        vmapped fit_batch + predict_batch + batched-metric program.

        ``val_masks`` overrides the fold construction with explicit (F, n)
        boolean validation masks — used by the workflow-level CV path, which
        must evaluate one externally-prepared fold at a time. ``fold_sliced``
        forces the per-fold row-gather scoring path on/off (default: on —
        under a mesh the gathered fold tensors are re-sharded over 'data')."""
        if val_masks is None:
            val_masks = self.make_splits(np.asarray(y))  # (F, n)
        F, n = val_masks.shape
        vm_np = np.asarray(val_masks)
        # sweep-level checkpointing (wired by the workflow through the
        # selector): fingerprint this run BEFORE padding so a persisted
        # candidate record can only replay onto identical data/folds/config
        sweep_ckpt = getattr(self, "_sweep_ckpt", None)
        fingerprint = None
        if sweep_ckpt is not None:
            import hashlib as _hashlib
            fingerprint = {
                "n": int(n), "F": int(F), "problem": problem,
                "d": int(X.shape[-1]) if X.ndim > 1 else 1,
                "metric": metric_name, "numClasses": int(num_classes),
                "largerBetter": bool(larger_better),
                "exact": bool(self.exact_sweep_fits),
                "maxEvalRows": self.max_eval_rows,
                "yhash": _hashlib.sha256(
                    np.ascontiguousarray(np.asarray(y)[:n])
                    .tobytes()).hexdigest(),
                "foldHash": _hashlib.sha256(
                    np.ascontiguousarray(vm_np).tobytes()).hexdigest(),
            }
        # cost-model gate (docs/parallel.md): engaging the mesh costs
        # collectives + cross-device layout on EVERY fit/predict/metric of
        # the sweep; when the per-chip slice is too small to amortize that,
        # transparently downgrade to the single-device fused path — which
        # is bit-identical to running with no mesh at all (same programs,
        # same buckets). The decision is observable: tg_mesh_downgrade_total
        # + a sweep.mesh_downgrade span event carrying the measured sizes.
        mesh = self.mesh
        if mesh is not None:
            from ...parallel.mesh import sweep_mesh_decision
            n_configs = F * sum(len(g) for _, g in models)
            engage, detail = sweep_mesh_decision(mesh, n, n_configs)
            if not engage:
                _obs_metrics.inc_counter(
                    "tg_mesh_downgrade_total", 1.0,
                    help="sweeps downgraded to the single-device fused path "
                         "by the mesh cost model")
                _obs_trace.add_event("sweep.mesh_downgrade", **detail)
                logger.info("mesh sweep downgraded to single-device: %s",
                            detail)
                mesh = None
        # bucket the row count so every fit/predict/metric program is reused
        # across datasets/folds/stages (utils/padding.py); under a mesh the
        # bucket also aligns to the data axis for equal shards. Pad rows
        # carry zero weight and False val masks — results are unchanged.
        n_data = mesh.shape["data"] if mesh is not None else 1
        n_pad = bucket_for(n, multiple_of=n_data)
        if n_pad != n:
            X = jnp.pad(X, ((0, n_pad - n),) + ((0, 0),) * (X.ndim - 1))
            y = jnp.pad(y, (0, n_pad - n))
        # ship ONE byte per row and expand masks on device: each row sits in
        # at most one validation fold (TVS leaves train-only rows at id=F),
        # so the (F, n) float/bool masks never cross the host<->device link
        # (n bytes vs 5Fn — the link is the bottleneck on tunneled devices)
        if F > 1 and int(vm_np.sum(axis=0).max()) > 1:
            raise ValueError(
                "validation masks must be disjoint (each row in at most one "
                "fold); overlapping masks would silently leak validation "
                "rows into other folds' training sets under the fold-id "
                "encoding")
        fold_ids = np.where(vm_np.any(axis=0), vm_np.argmax(axis=0),
                            F).astype(np.uint8)
        ids_d = jnp.asarray(fold_ids)
        if n_pad != n:  # sentinel F+1: never trains, never validates
            ids_d = jnp.pad(ids_d, (0, n_pad - n), constant_values=F + 1)
        # fold-sliced scoring: every (fold, config) pair only needs ITS
        # fold's validation rows, so predict + metric run on the gathered
        # per-fold partitions (~n/F rows each, capped at max_eval_rows)
        # instead of all n rows and a mask — an F x cut on the heavy tree
        # predicts. Under a mesh the gathered fold tensors are re-placed
        # with their row axis sharded over 'data' (round-3 forced full-row
        # masked scoring here, silently dropping the eval-row cap — the
        # mesh sweep then did MORE per-chip predict work than one chip).
        if fold_sliced is None:
            fold_sliced = True
        # the fold gather is built lazily, on the first family that uses it
        # (fold_sliced_predict, default on: with the max_eval_rows cap the
        # gathered rows beat full-row masked scoring even for single-matmul
        # predicts; the gather is shared across families)
        _fold_cache: Dict[str, Any] = {}

        def _fold_data():
            if "Xf" not in _fold_cache:
                cap = self.max_eval_rows
                counts = vm_np.sum(axis=1)
                nf = int(counts.max()) if F > 0 else 0
                if cap is not None and nf > cap:
                    nf = cap
                nf_b = bucket_for(max(nf, 1), multiple_of=n_data)
                fidx = np.zeros((F, nf_b), np.int32)
                fvalid = np.zeros((F, nf_b), bool)
                for f in range(F):
                    rows = np.nonzero(vm_np[f])[0]
                    if cap is not None and len(rows) > cap:
                        # deterministic strided subsample: validation METRIC
                        # estimates use <= cap rows per fold (std of AuROC at
                        # 65k rows ~2e-3 — far below fold-to-fold variance);
                        # the winner's holdout/train evaluations and refit
                        # always use full data
                        rows = rows[np.linspace(0, len(rows) - 1, cap)
                                    .astype(np.int64)]
                    fidx[f, :len(rows)] = rows
                    fvalid[f, :len(rows)] = True
                fidx_d = jnp.asarray(fidx.reshape(-1))
                Xf = X[fidx_d].reshape((F, nf_b) + X.shape[1:])
                yf = y[fidx_d].reshape(F, nf_b)
                fvalid_d = jnp.asarray(fvalid)
                if mesh is not None:
                    # Xf rows shard over 'data' (feeds the row-parallel
                    # per-fold predicts); yf / fvalid replicate — they are
                    # only read by the config-parallel metric stage. Placed
                    # ONCE into the sweep-scoped cache and shared by every
                    # family's fused program.
                    from jax.sharding import NamedSharding
                    from jax.sharding import PartitionSpec as P

                    from ...parallel.distributed import retrying_device_put
                    Xf = retrying_device_put(
                        Xf, NamedSharding(
                            mesh, P(None, "data", *([None] * (X.ndim - 1)))),
                        site="sweep.fold_upload")
                    yf = retrying_device_put(
                        yf, NamedSharding(mesh, P(None)),
                        site="sweep.fold_upload")
                    fvalid_d = retrying_device_put(
                        fvalid_d, NamedSharding(mesh, P(None)),
                        site="sweep.fold_upload")
                _fold_cache["Xf"] = Xf
                _fold_cache["yf"] = yf
                _fold_cache["valid"] = fvalid_d
            return (_fold_cache["Xf"], _fold_cache["yf"],
                    _fold_cache["valid"])
        # pin binned-vs-exact AuROC/AuPR to the PRE-slice row count so
        # fold-sliced and full-row scoring choose the same algorithm
        # (_metric_fn itself is memoized at module level)
        from ...ops.metrics import _BINNED_MIN_N

        def _binned(sliced: bool):
            return (n_pad >= _BINNED_MIN_N) if sliced else None

        if mesh is not None:
            # sweep-scoped device cache: X / y / fold-id bytes are placed
            # with their mesh sharding ONCE and shared by every family's
            # fused program (the per-family device_put of (F·G, n) weight
            # tensors is gone — fold masks are built inside each trace from
            # the uint8 id vector)
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ...parallel.distributed import retrying_device_put
            row_sh = NamedSharding(mesh, P("data"))
            X = retrying_device_put(
                X, NamedSharding(mesh, P("data", *([None] * (X.ndim - 1)))),
                site="sweep.table_upload")
            y = retrying_device_put(y, row_sh, site="sweep.table_upload")
            ids_d = retrying_device_put(ids_d, row_sh,
                                        site="sweep.table_upload")

        def _dispatch(family, grid):
            """One family's sweep branch with adaptive degradation under
            memory pressure: resource exhaustion (XLA RESOURCE_EXHAUSTED /
            host MemoryError — or the ``oom.sweep`` chaos site) splits the
            packed (F·G) config grid in half and dispatches the halves as
            their own fused programs, recursively down to single configs;
            the per-config fold metrics merge back by concatenation along
            the config axis (each config's metric is independent of its
            batch-mates, so the merged (F, G) matrix is identical to the
            unsplit program's). The family is DOWNSHIFTED, not
            quarantined; only a single config that still exhausts — or any
            non-resource throw — propagates to the quarantine handler
            below."""
            try:
                faults.inject("oom.sweep", key=family.name)
                return _dispatch_once(family, grid)
            except Exception as e:
                from ...robustness import resources
                if (resources.classify_exhaustion(e) is None
                        or len(grid) < 2):
                    raise
                mid = len(grid) // 2
                resources.record_downshift(
                    "oom.sweep", family=family.name, configs=len(grid),
                    splitConfigs=[mid, len(grid) - mid],
                    error=f"{type(e).__name__}: {e}"[:200])
                logger.warning(
                    "sweep branch for %s exhausted memory at %d configs; "
                    "splitting the grid into %d + %d",
                    family.name, len(grid), mid, len(grid) - mid)
                _, _, m1, _, G1 = _dispatch(family, grid[:mid])
                _, _, m2, _, G2 = _dispatch(family, grid[mid:])
                # metric monoid merge: un-pad each half to its (F, Gi)
                # matrix and concatenate along the config axis — the
                # merged flat vector is exactly the unsplit program's
                # [:B_true] slice (finish() reshapes it to (F, G))
                m = jnp.concatenate(
                    [m1.reshape(-1)[:F * G1].reshape(F, G1),
                     m2.reshape(-1)[:F * G2].reshape(F, G2)],
                    axis=1).reshape(-1)
                return (family.name, list(grid), m, F * (G1 + G2), G1 + G2)

        def _dispatch_once(family, grid):
            """One family's sweep branch → a pending (name, grid, metric
            program output, B_true, G) entry. Runs under the quarantine
            try/except below: a throw here (trace error, diverging fused
            fit, injected fault) quarantines the family instead of
            aborting the sweep. With or without a mesh the branch is ONE
            fused jitted program (see _make_fused_program); the mesh
            variant carries explicit NamedSharding in/out specs and is
            cached under a mesh-inclusive key."""
            from ...manifest import sentinel_phase
            # crash evidence: a kill past this point happened inside a
            # fused sweep dispatch (run sentinel, docs/robustness.md)
            sentinel_phase("device_sweep")
            if getattr(family, "uses_hist_engine", False):
                # chaos site hist.build: a raise quarantines THIS family
                # (same recovery as validator.family_fit) before any of
                # its histogram programs build or dispatch
                from ...histeng import chaos_gate
                chaos_gate(family.name)
            G = len(grid)
            sliced_f = fold_sliced and getattr(family, "fold_sliced_predict",
                                               True)
            binned_f = _binned(sliced_f)
            grid_repr = repr([sorted(g.items()) for g in grid])
            key = (family, grid_repr,
                   F, G, problem, metric_name, num_classes,
                   self.exact_sweep_fits, sliced_f, binned_f, mesh,
                   X.ndim)
            import hashlib as _hl
            fp_doc = {
                "F": int(F), "G": int(G), "problem": problem,
                "metric": metric_name,
                "numClasses": int(num_classes),
                "exact": bool(self.exact_sweep_fits),
                "sliced": bool(sliced_f), "binned": binned_f,
                "xNdim": int(X.ndim),
                "mesh": mesh is not None,
                "grid": _hl.sha256(grid_repr.encode()).hexdigest()[:12],
            }
            aot_fp = None
            # Mesh storability mirrors _make_fused_program's grid logic:
            # families that take a traced DONATED grid block (shardable +
            # traced_grid_ok) are not exportable; everything else — all
            # single-device programs, and mesh programs with host-constant
            # grids (the tree families, shardable=False) — is a pure
            # function of family × fp_doc × row bucket. Mesh fingerprints
            # additionally pin the axis sizes and device count: an export
            # from a different topology must never be a hit.
            mesh_storable = mesh is not None and not (
                getattr(family, "shardable", True)
                and getattr(family, "traced_grid_ok", False))
            if mesh is None or mesh_storable:
                import json as _json
                doc = {"family": family.name, **fp_doc}
                if mesh is not None:
                    doc["meshAxes"] = {k: int(v)
                                       for k, v in mesh.shape.items()}
                    doc["devices"] = int(np.prod(
                        [int(v) for v in mesh.shape.values()]))
                aot_fp = "sweep-" + _hl.sha256(
                    _json.dumps(doc, sort_keys=True).encode()
                    ).hexdigest()[:16]
            entry = _fused_cache_get(key)
            newly_built = False
            if entry is None and aot_fp is not None:
                # a store hit (cross-process sweep cache: TG_AOT_STORE /
                # a capture scope) skips the trace; misses classify the
                # build below as aot-miss
                from ...programstore import store as _pstore
                fn = _pstore.lookup(
                    aot_fp, int(X.shape[0]), component="sweep",
                    ledger_key=_obs_ledger.cache_key_hash(key))
                if fn is not None:
                    entry = (fn, None)
                    _fused_cache_put(key, entry)
            if entry is None:
                import time as _time
                garr_np = {k: np.asarray(v)
                           for k, v in family.grid_to_arrays(grid).items()}
                t0_build = _time.perf_counter()
                entry = _make_fused_program(
                    family, garr_np, G, F, problem, metric_name,
                    num_classes, self.exact_sweep_fits, sliced_f,
                    binned_f, mesh=mesh, x_ndim=X.ndim)
                _fused_cache_put(key, entry)
                newly_built = True
                # compile ledger: one fused program per family branch —
                # the fingerprint carries every traced dimension, so a
                # near-miss rebuild names exactly which one changed
                # (docs/observability.md "Compile & memory ledger")
                _obs_ledger.record_build(
                    "sweep",
                    identity=(f"sweep/{family.name}"
                              + ("/mesh" if mesh is not None else "")),
                    key=_obs_ledger.cache_key_hash(key),
                    fingerprint=fp_doc,
                    bucket=int(X.shape[0]),
                    donation=entry[1],
                    seconds=_time.perf_counter() - t0_build,
                    configs=G, folds=F)
            prog, grid_keys = entry
            args = [X, y, ids_d]
            if sliced_f:
                args += list(_fold_data())
            if grid_keys is not None:
                # per-family scratch: the tiled grid packed into ONE
                # (keys, F·G) f32 block, uploaded sharded over 'model' and
                # DONATED into the program — one transfer per family and a
                # buffer XLA may alias instead of re-allocating. Never
                # reused after the call (donation safety).
                from jax.sharding import NamedSharding, PartitionSpec as P

                from ...parallel.distributed import retrying_device_put
                garr_np = {k: np.asarray(v)
                           for k, v in family.grid_to_arrays(grid).items()}
                gb = np.stack([np.tile(garr_np[k], F) for k in grid_keys]
                              ).astype(np.float32)
                n_model = mesh.shape["model"]
                gb_pad = -(-gb.shape[1] // n_model) * n_model
                if gb_pad != gb.shape[1]:
                    # zero-padded tail so the config axis divides the
                    # 'model' shards; the program slices it off before the
                    # fit (an unpadded block fails device_put outright)
                    gb = np.pad(gb, ((0, 0), (0, gb_pad - gb.shape[1])))
                args.append(retrying_device_put(
                    jnp.asarray(gb), NamedSharding(mesh, P(None, "model")),
                    site="sweep.grid_upload"))
            # device-memory observatory: argument bytes plus the (F·G, n)
            # fold-weight tensor the trace builds on device — the branch's
            # dominant allocations, predicted before dispatch
            predicted = (sum(_arg_nbytes(a) for a in args)
                         + F * G * int(X.shape[0]) * 4)
            _devicemem.record_dispatch("sweep", predicted,
                                       bucket=int(X.shape[0]))
            # defer host materialization: every family's full program queues
            # on the device back-to-back, then ONE sync reads all metrics
            # (a per-family sync costs a link round-trip each)
            with warnings.catch_warnings():
                # donated grid blocks too small for XLA to alias (tiny CPU
                # grids) emit a first-compile "donated buffers were not
                # usable" warning — expected, not actionable
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not usable")
                # the engine mesh context must surround the TRACE — which
                # happens here, at the program's first call, not at
                # _make_fused_program (jit is lazy) — so tree histogram
                # row blocks pin to the 'data' axis (histeng.engine_mesh)
                with _hist_mesh_ctx(family, mesh):
                    m = prog(*args)
            _devicemem.sample_measured("sweep")
            if newly_built and aot_fp is not None:
                # populate: a freshly traced branch program is offered to
                # the active capture scopes / TG_AOT_STORE so the next
                # process deserializes instead of tracing (one flag
                # check when nothing is active). Export re-traces, so the
                # engine mesh context applies here too.
                from ...programstore import store as _pstore
                with _hist_mesh_ctx(family, mesh):
                    _pstore.offer_segment(
                        aot_fp, int(X.shape[0]), prog, tuple(args),
                        component="sweep",
                        identity=(f"sweep/{family.name}"
                                  + ("/mesh" if mesh is not None else "")))
            return (family.name, list(grid), m, F * G, G)

        # per-candidate quarantine at family granularity: a family's whole
        # branch is one fused program, so a throw (trace error, diverging
        # fit, injected fault) poisons all its configs — record the reason,
        # keep a NaN placeholder, and let the sweep continue on the other
        # families (the reference survives this via Spark task retries +
        # lineage; only all-candidates-failed raises, aggregated, below)
        pending: List[Any] = []
        fit_failures: Dict[int, str] = {}
        #: host-resident (F, G) metrics by family index — filled by sweep
        #: checkpoint restore AND by the eager per-family fetch that
        #: checkpointing requires (durability costs the single-sync
        #: batching: each family's metrics must reach the host — and disk —
        #: before the next family runs, or a preemption loses them)
        host_metrics: Dict[int, np.ndarray] = {}
        for fi, (family, grid) in enumerate(models):
            ckey = None
            if sweep_ckpt is not None:
                from .sweep_checkpoint import SweepCheckpoint, candidate_key
                ckey = candidate_key(family.name, list(grid), fingerprint)
                rec = sweep_ckpt.get(ckey)
                if rec is not None:
                    fm = SweepCheckpoint.decode_metrics(rec)
                    if fm.shape == (F, len(grid)):
                        host_metrics[fi] = fm
                        if rec.get("quarantined"):
                            fit_failures[fi] = (rec.get("reason")
                                                or "restored quarantined "
                                                   "candidate")
                        pending.append((family.name, list(grid), None,
                                        F * len(grid), len(grid)))
                        FaultLog.record(FaultReport(
                            site="sweep.candidate", kind="restored",
                            detail={"family": family.name,
                                    "configs": len(grid),
                                    "candidateKey": ckey[:16],
                                    "quarantined": bool(
                                        rec.get("quarantined"))}))
                        logger.info(
                            "sweep resume: restored %d %s candidate(s) "
                            "from checkpoint", len(grid), family.name)
                        continue
            # sweep span per candidate family: grid size, folds, metric,
            # and the compile-cache hit/miss delta of dispatching this
            # branch (utils/jax_cache.py listener) — the attribution the
            # 0.381x mesh regression lacked (compile vs execute)
            with _obs_span("sweep.family", cat="sweep", family=family.name,
                           configs=len(grid), folds=F,
                           metric=metric_name) as sweep_span:
                # flight-recorder: each family dispatch, stamped with the
                # owning run's correlation id (workflow.train) — a sweep
                # post-mortem shows which family the incident interrupted
                _blackbox.record("sweep.family", family=family.name,
                                 configs=len(grid), folds=F)
                cs0 = None
                if tracing_enabled():
                    from ...utils.jax_cache import cache_stats
                    cs0 = cache_stats()
                try:
                    # deterministic preemption point: the process dies
                    # between family branches — already-persisted
                    # candidates survive
                    faults.inject("preempt.sweep", key=family.name)
                    faults.inject("validator.family_fit", key=family.name)
                    pending.append(_dispatch(family, grid))
                except Exception as e:
                    reason = f"fit raised {type(e).__name__}: {e}"
                    logger.warning("quarantining model family %s: %s",
                                   family.name, reason)
                    pending.append((family.name, list(grid), None,
                                    F * len(grid), len(grid)))
                    fit_failures[fi] = reason
                    sweep_span.add_event("sweep.family_quarantined",
                                         family=family.name, reason=reason)
                if cs0 is not None:
                    from ...utils.jax_cache import cache_stats
                    cs1 = cache_stats()
                    sweep_span.set_attr(
                        cacheHits=cs1["hits"] - cs0["hits"],
                        cacheMisses=cs1["misses"] - cs0["misses"])
            if sweep_ckpt is not None:
                from ...parallel.distributed import fetch_to_host
                from .sweep_checkpoint import SweepCheckpoint, params_hash
                fam_name, grid_l, m, B_true, G = pending[-1]
                if m is not None:
                    fm_host = np.asarray(
                        fetch_to_host(m)).reshape(-1)[:B_true].reshape(F, G)
                    # drop the device handle: finish() reads the host copy
                    pending[-1] = (fam_name, grid_l, None, B_true, G)
                    host_metrics[fi] = fm_host
                else:
                    fm_host = np.full((F, len(grid)), np.nan)
                sweep_ckpt.put(ckey, {
                    "family": fam_name,
                    "grid": [dict(g) for g in grid_l],
                    "paramsHashes": [params_hash(g) for g in grid_l],
                    "metricName": metric_name,
                    **SweepCheckpoint.encode_metrics(fm_host),
                    "quarantined": fi in fit_failures,
                    "reason": fit_failures.get(fi),
                })

        # fuse every family's metric vector into ONE device array so finish()
        # pays a single host transfer (measured ~70-130ms per warm transfer
        # over the tunneled backend — a per-family np.asarray was ~0.4s of
        # pure link latency on the 4-family default sweep)
        valid_m = [p[2] for p in pending if p[2] is not None]
        all_m = (jnp.concatenate([m.reshape(-1) for m in valid_m])
                 if len(valid_m) > 1 else None)

        def finish() -> BestEstimator:
            import time as _time

            from ...parallel.distributed import fetch_to_host

            # build the result list locally (not the closed-over `results`)
            # so resolving a PendingValidation twice cannot duplicate entries
            results: List[ValidationResult] = []
            quarantined: List[Dict[str, Any]] = []
            best: Optional[BestEstimator] = None
            # the device->host metric fetch is the sweep's "transfer" phase;
            # its histogram lets bench.py split compile/execute/transfer
            t0_fetch = _time.perf_counter()
            m_host = fetch_to_host(all_m) if all_m is not None else None
            if m_host is not None:
                _obs_metrics.observe(
                    "tg_sweep_transfer_seconds",
                    _time.perf_counter() - t0_fetch,
                    help="device->host validation-metric fetch per sweep")
            off = 0
            for fi, (fam_name, grid_l, m, B_true, G) in enumerate(pending):
                if fi in host_metrics:  # restored / eagerly persisted
                    fold_metrics = host_metrics[fi]
                elif m is None:  # the family's fit threw before dispatch
                    fold_metrics = np.full((F, G), np.nan, dtype=np.float64)
                elif m_host is not None:
                    m_fam = m_host[off:off + m.size]
                    off += m.size
                    fold_metrics = m_fam[:B_true].reshape(F, G)
                else:
                    m_fam = fetch_to_host(m).reshape(-1)
                    fold_metrics = m_fam[:B_true].reshape(F, G)
                fold_metrics = faults.poison("validator.fold_metrics",
                                             fold_metrics, key=fam_name)
                # non-finite guard: quarantine diverged configs instead of
                # letting NaN elect itself (np.argmax ranks NaN as the max)
                mean_metrics, masked_means, records = quarantine_non_finite(
                    fam_name, grid_l, fold_metrics, metric_name,
                    larger_better, reason=fit_failures.get(fi))
                quarantined.extend(records)
                results.append(ValidationResult(
                    family=fam_name, grid=grid_l, metric_name=metric_name,
                    fold_metrics=fold_metrics, mean_metrics=mean_metrics))
                if not np.isfinite(mean_metrics).any():
                    continue  # whole family quarantined
                g_best = int(np.argmax(masked_means) if larger_better
                             else np.argmin(masked_means))
                value = float(mean_metrics[g_best])
                better = best is None or (
                    (value > best.metric_value) if larger_better
                    else (value < best.metric_value))
                if better:
                    best = BestEstimator(fam_name, dict(grid_l[g_best]), value)
            if best is None:
                raise AllCandidatesFailedError(quarantined)
            best.results = results
            best.quarantined = quarantined
            _obs_trace.add_event("sweep.winner", family=best.family_name,
                                 metricValue=float(best.metric_value))
            return best

        if resolve:
            return finish()
        return PendingValidation(finish)


class OpCrossValidation(OpValidator):
    """k-fold CV (reference OpCrossValidation.scala, default 3 folds)."""

    def __init__(self, num_folds: int = 3, **kw):
        super().__init__(**kw)
        if num_folds < 2:
            raise ValueError("num_folds must be >= 2")
        self.num_folds = num_folds

    def make_splits(self, y: np.ndarray) -> np.ndarray:
        return self._kfold_masks(y, self.num_folds)


class OpTrainValidationSplit(OpValidator):
    """Single train/validation split (reference OpTrainValidationSplit.scala,
    default ratio 0.75)."""

    def __init__(self, train_ratio: float = 0.75, **kw):
        super().__init__(**kw)
        if not 0.0 < train_ratio < 1.0:
            raise ValueError("train_ratio must be in (0, 1)")
        self.train_ratio = train_ratio

    def make_splits(self, y: np.ndarray) -> np.ndarray:
        n = len(y)
        rng = np.random.RandomState(self.seed)
        val = np.zeros((1, n), dtype=bool)
        if self.stratify:
            for lab in np.unique(y):
                idx = rng.permutation(np.nonzero(y == lab)[0])
                n_val = int(round(len(idx) * (1.0 - self.train_ratio)))
                val[0, idx[:n_val]] = True
        else:
            perm = rng.permutation(n)
            val[0, perm[: int(round(n * (1.0 - self.train_ratio)))]] = True
        return val
