from .splitters import Splitter, DataSplitter, DataBalancer, DataCutter
from .validators import OpCrossValidation, OpTrainValidationSplit, OpValidator
