"""Typed, versioned SanityChecker summary.

Mirrors the reference's typed metadata (de)serialization (reference:
core/.../impl/preparators/SanityCheckerMetadata.scala — SanityCheckerSummary
with named sub-records and a round-trippable schema): a dataclass schema
with an explicit ``schemaVersion``, instead of the loose dict of round 1.
Dict-style access (``summary["dropped"]``) is kept for compatibility with
existing consumers (ModelInsights, tests, user code)."""
from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

#: bump when the serialized layout changes; from_json upgrades older versions
SCHEMA_VERSION = 3


@dataclass
class ColumnStatistics:
    """Per-column stats (reference SanityCheckerMetadata ColumnStatistics)."""
    names: List[str] = field(default_factory=list)
    count: List[float] = field(default_factory=list)
    mean: List[float] = field(default_factory=list)
    variance: List[float] = field(default_factory=list)
    min: List[float] = field(default_factory=list)
    max: List[float] = field(default_factory=list)


@dataclass
class CategoricalGroupStats:
    """Per-group contingency stats (reference CategoricalGroupStats:
    Cramér's V, mutual information and per-cell pointwise mutual
    information, reference OpStatistics.contingencyStats:300)."""
    cramers_v: Dict[str, float] = field(default_factory=dict)
    mutual_info: Dict[str, float] = field(default_factory=dict)
    #: per group: (m feature values, L labels) PMI matrix as nested lists
    pointwise_mutual_info: Dict[str, List[List[float]]] = field(
        default_factory=dict)


@dataclass
class SanityCheckerSummary:
    """The full fitted summary (reference SanityCheckerSummary.scala)."""
    stats: ColumnStatistics = field(default_factory=ColumnStatistics)
    categorical: CategoricalGroupStats = field(
        default_factory=CategoricalGroupStats)
    correlations_with_label: List[Optional[float]] = field(
        default_factory=list)
    correlation_type: str = "pearson"
    dropped: List[str] = field(default_factory=list)
    reasons: Dict[str, List[str]] = field(default_factory=dict)
    sample_size: int = 0
    #: full (d, d) feature-feature correlation matrix (np.ndarray, NaN for
    #: constant columns), only populated when the checker ran with
    #: correlations="full" (reference SanityChecker.scala:634-638
    #: featureLabelCorrOnly=false). Persisted via the model's array store;
    #: included in to_json only up to _JSON_CORR_MAX_D columns.
    feature_correlations: Optional[Any] = None
    schema_version: int = SCHEMA_VERSION

    #: widest matrix to inline in summary JSON (25M-element nested lists for
    #: a 5k-column hashed-text vector would dominate plan.json)
    _JSON_CORR_MAX_D = 512

    # -- dict-compat view (consumers predate the typed schema) --------------
    _ALIASES = {
        "names": lambda s: s.stats.names,
        "count": lambda s: s.stats.count,
        "mean": lambda s: s.stats.mean,
        "variance": lambda s: s.stats.variance,
        "min": lambda s: s.stats.min,
        "max": lambda s: s.stats.max,
        "correlationsWithLabel": lambda s: s.correlations_with_label,
        "correlationType": lambda s: s.correlation_type,
        "cramersV": lambda s: s.categorical.cramers_v,
        "mutualInfo": lambda s: s.categorical.mutual_info,
        "pointwiseMutualInfo": lambda s: s.categorical.pointwise_mutual_info,
        "featureCorrelations": lambda s: s._corr_json(),
        "dropped": lambda s: s.dropped,
        "reasons": lambda s: s.reasons,
        "sampleSize": lambda s: s.sample_size,
        "schemaVersion": lambda s: s.schema_version,
    }

    def __getitem__(self, key: str) -> Any:
        try:
            return self._ALIASES[key](self)
        except KeyError:
            raise KeyError(key) from None

    def get(self, key: str, default: Any = None) -> Any:
        try:
            return self[key]
        except KeyError:
            return default

    def __contains__(self, key: str) -> bool:
        return key in self._ALIASES

    def keys(self):
        return self._ALIASES.keys()

    # -- (de)serialization ---------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        return {
            "schemaVersion": self.schema_version,
            "stats": asdict(self.stats),
            "categorical": asdict(self.categorical),
            "correlationsWithLabel": self.correlations_with_label,
            "correlationType": self.correlation_type,
            "dropped": list(self.dropped),
            "reasons": dict(self.reasons),
            "sampleSize": self.sample_size,
            "featureCorrelations": self._corr_json(),
        }

    def _corr_json(self) -> Optional[List[List[Optional[float]]]]:
        fc = self.feature_correlations
        if fc is None:
            return None
        import numpy as _np
        fc = _np.asarray(fc, dtype=_np.float64)
        if fc.shape[0] > self._JSON_CORR_MAX_D:
            return None  # too wide to inline; the ndarray itself persists
        return [[None if _np.isnan(v) else round(float(v), 6) for v in r]
                for r in fc]

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "SanityCheckerSummary":
        version = d.get("schemaVersion", 1)
        if version == 1:
            # round-1 loose dict: flat stat arrays, camelCase keys
            return cls(
                stats=ColumnStatistics(
                    names=list(d.get("names", [])),
                    count=list(d.get("count", [])),
                    mean=list(d.get("mean", [])),
                    variance=list(d.get("variance", [])),
                    min=list(d.get("min", [])),
                    max=list(d.get("max", []))),
                categorical=CategoricalGroupStats(
                    cramers_v=dict(d.get("cramersV", {}))),
                correlations_with_label=list(
                    d.get("correlationsWithLabel", [])),
                correlation_type=d.get("correlationType", "pearson"),
                dropped=list(d.get("dropped", [])),
                reasons=dict(d.get("reasons", {})),
                sample_size=int(d.get("sampleSize", 0)),
            )
        if version in (2, SCHEMA_VERSION):
            # v2 → v3: categorical gained mutual_info/pointwise_mutual_info
            # (default empty) and the optional featureCorrelations matrix
            return cls(
                stats=ColumnStatistics(**d["stats"]),
                categorical=CategoricalGroupStats(**d["categorical"]),
                correlations_with_label=list(d["correlationsWithLabel"]),
                correlation_type=d["correlationType"],
                dropped=list(d["dropped"]),
                reasons=dict(d["reasons"]),
                sample_size=int(d["sampleSize"]),
                feature_correlations=d.get("featureCorrelations"),
            )
        raise ValueError(
            f"unknown SanityChecker summary schemaVersion {version}")
