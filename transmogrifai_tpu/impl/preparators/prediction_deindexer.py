"""PredictionDeIndexer — indexed predictions back to label strings.

Mirrors the reference stage (reference:
core/.../impl/preparators/PredictionDeIndexer.scala:86): a BinaryEstimator
over (indexed response, indexed prediction) that reads the label/index
mapping from the response COLUMN's metadata (attached by
OpStringIndexerModel, the analog of the reference's NominalAttribute schema
metadata) and emits the prediction's original string label; out-of-range
predictions decode to the reserved unseen name."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ...stages.base import AllowLabelAsInput, Estimator, Transformer
from ...table import Column, FeatureTable
from ...types import RealNN, Text


class PredictionDeIndexer(AllowLabelAsInput, Estimator):
    input_types = (RealNN, RealNN)
    output_type = Text

    def __init__(self, unseen_name: str = "UnseenLabel", uid=None):
        super().__init__("idx2str", uid)
        self.unseen_name = unseen_name

    def fit(self, table: FeatureTable) -> Transformer:
        resp_f = self.input_features[0]
        labels = table[resp_f.name].metadata.get("labels")
        if labels is None:
            # fallback: the fitted indexer stage itself (pre-columnar wiring)
            origin = getattr(resp_f, "origin_stage", None)
            labels = getattr(origin, "summary_metadata", {}).get("labels") \
                if origin is not None else None
        if labels is None:
            raise ValueError(
                f"the feature {resp_f.name!r} does not carry any label/index "
                f"mapping in its metadata — index it with OpStringIndexer "
                f"first (reference PredictionDeIndexer error)")
        # the fallback (stage summary) path may carry a literal None for a
        # trained-null label; render it like the column-metadata path does
        labels = ["null" if l is None else l for l in labels]
        model = PredictionDeIndexerModel(labels=labels,
                                         unseen_name=self.unseen_name)
        model.summary_metadata = {"labels": list(labels)}
        return self._finalize_model(model)


class PredictionDeIndexerModel(AllowLabelAsInput, Transformer):
    output_type = Text

    def __init__(self, labels: List[str], unseen_name: str = "UnseenLabel",
                 uid=None):
        super().__init__("idx2str", uid)
        self.labels = list(labels)
        self.unseen_name = unseen_name

    def _decode(self, v: Optional[float]) -> str:
        if v is None or (isinstance(v, float) and np.isnan(v)):
            return self.unseen_name
        # round, not truncate: float noise (1.9999999) must decode to 2,
        # and -0.3 must stay out-of-range rather than truncating to 0
        i = int(round(float(v)))
        return self.labels[i] if 0 <= i < len(self.labels) \
            else self.unseen_name

    def transform_column(self, table: FeatureTable) -> Column:
        pred_f = self.input_features[1]
        col = table[pred_f.name]
        valid = col.valid_mask()
        raw = np.asarray(col.values, dtype=np.float64).reshape(-1)
        out = [self._decode(raw[i] if valid[i] else None)
               for i in range(len(raw))]
        return Column.of_values(Text, out)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        pred_f = self.input_features[1]
        return self._decode(row.get(pred_f.name))
