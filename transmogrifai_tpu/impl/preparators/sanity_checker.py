"""SanityChecker — automated feature validation.

TPU re-design of the reference SanityChecker
(reference: core/.../impl/preparators/SanityChecker.scala — sampling :524-529 &
limits :720-739, colStats :574-576, correlations :634-638, categorical
association stats categoricalTests :420-516, removal reasons
ColumnStatistics.reasonsToRemove :783-832, index-keep model transformFn
:707-717, summary metadata :678).

Everything numeric happens in a handful of jitted kernels over the feature
matrix: one fused stats pass (count/mean/var/min/max), one correlation kernel
(Pearson or Spearman vs label), and one MXU matmul per categorical group for
contingency tables — replacing Spark's colStats/corr/reduceByKey jobs.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import jax.numpy as jnp
import numpy as np

from ...ops.stats import (
    col_stats, contingency_stats, contingency_table, pearson_correlation,
    pearson_correlation_matrix, spearman_correlation,
)
from ...stages.base import (AllowLabelAsInput, Estimator, PendingFit,
                            Transformer)
from ...table import Column, FeatureTable
from ...types import OPVector, RealNN
from ...vector_metadata import VectorColumnMetadata, VectorMetadata
from .sanity_checker_metadata import (
    CategoricalGroupStats, ColumnStatistics, SanityCheckerSummary,
)

#: feature types whose shared-hash slots protect_text_shared_hash exempts
_TEXT_PARENT_TYPES = ("Text", "TextArea", "TextMap", "TextAreaMap",
                      "TextList")  # .tf()/HashingVectorizer slots


def _is_text_shared_hash(c: VectorColumnMetadata) -> bool:
    """Shared-hash text slot (reference SanityChecker.isTextSharedHash :840:
    text-derived, not an indicator). In this codebase's metadata convention
    hashed slots carry ``descriptor_value='hash_<j>'`` (and keep their
    grouping so null-indicator siblings share the feature group), so the
    test is: text parent, hash descriptor, no indicator value."""
    return (c.parent_feature_type in _TEXT_PARENT_TYPES
            and c.indicator_value is None
            and (c.descriptor_value or "").startswith("hash_"))


def _contingency_stats_np(t: np.ndarray) -> Dict[str, Any]:
    """Association stats on a small (m, L) contingency table, host-side
    (same math as ops.stats.contingency_stats — the tables are tiny, so
    numpy beats a device dispatch per group). Includes mutual information
    and per-cell pointwise mutual information (reference
    OpStatistics.contingencyStats:300)."""
    t = t.astype(np.float64)
    n = max(t.sum(), 1.0)
    row = t.sum(axis=1)
    col = t.sum(axis=0)
    expected = row[:, None] * col[None, :] / n
    chi2 = np.where(expected > 0,
                    (t - expected) ** 2 / np.maximum(expected, 1e-30),
                    0.0).sum()
    min_dim = max(min((row > 0).sum(), (col > 0).sum()) - 1, 1)
    conf = np.where(row[:, None] > 0,
                    t / np.maximum(row[:, None], 1e-30), 0.0)
    p = t / n
    denom = (row[:, None] / n) * (col[None, :] / n)
    pmi = np.where((p > 0) & (denom > 0),
                   np.log2(np.maximum(p, 1e-300)
                           / np.maximum(denom, 1e-300)), 0.0)
    return {
        "cramers_v": float(np.sqrt(chi2 / (n * min_dim))),
        "max_rule_confidence": conf.max(axis=1),
        "support": row / n,
        "mutual_info": float((p * pmi).sum()),
        "pointwise_mutual_info": pmi,
    }


class SanityCheckerDefaults:
    """(reference SanityCheckerParams defaults :59-226, object SanityChecker
    :720-739 — ProtectTextSharedHash=False matches the reference object
    default; round 1 of this build had it True, undocumented). One
    deliberate deviation: RemoveBadFeatures defaults True here (False in
    the reference object, but every reference example/selector flow turns
    it on — removal is the stage's purpose in this framework's default
    pipelines)."""
    CheckSample = 1.0
    SampleLowerLimit = 1_000
    SampleUpperLimit = 1_000_000
    MaxCorrelation = 0.95
    MinCorrelation = 0.0
    MaxCramersV = 0.95
    MinVariance = 1e-5
    MinRequiredRuleSupport = 1.0
    MaxRuleConfidence = 1.0
    RemoveFeatureGroup = True
    ProtectTextSharedHash = False
    RemoveBadFeatures = True
    CorrelationTypeSpearman = False


class SanityChecker(AllowLabelAsInput, Estimator):
    """BinaryEstimator[RealNN, OPVector] → OPVector: drops features whose
    statistics flag leakage or uselessness."""

    input_types = (RealNN, OPVector)
    output_type = OPVector

    def __init__(self,
                 check_sample: float = SanityCheckerDefaults.CheckSample,
                 sample_lower_limit: int = SanityCheckerDefaults.SampleLowerLimit,
                 sample_upper_limit: int = SanityCheckerDefaults.SampleUpperLimit,
                 protect_text_shared_hash: bool = SanityCheckerDefaults.ProtectTextSharedHash,
                 max_correlation: float = SanityCheckerDefaults.MaxCorrelation,
                 min_correlation: float = SanityCheckerDefaults.MinCorrelation,
                 max_cramers_v: float = SanityCheckerDefaults.MaxCramersV,
                 min_variance: float = SanityCheckerDefaults.MinVariance,
                 max_rule_confidence: float = SanityCheckerDefaults.MaxRuleConfidence,
                 min_required_rule_support: float = SanityCheckerDefaults.MinRequiredRuleSupport,
                 remove_bad_features: bool = SanityCheckerDefaults.RemoveBadFeatures,
                 remove_feature_group: bool = SanityCheckerDefaults.RemoveFeatureGroup,
                 correlation_type_spearman: bool = SanityCheckerDefaults.CorrelationTypeSpearman,
                 correlations: str = "label",
                 seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__("sanityCheck", uid)
        self.check_sample = check_sample
        self.sample_lower_limit = sample_lower_limit
        self.sample_upper_limit = sample_upper_limit
        self.protect_text_shared_hash = protect_text_shared_hash
        self.max_correlation = max_correlation
        self.min_correlation = min_correlation
        self.max_cramers_v = max_cramers_v
        self.min_variance = min_variance
        self.max_rule_confidence = max_rule_confidence
        self.min_required_rule_support = min_required_rule_support
        self.remove_bad_features = remove_bad_features
        self.remove_feature_group = remove_feature_group
        self.correlation_type_spearman = correlation_type_spearman
        if correlations not in ("label", "full"):
            raise ValueError(
                f"correlations must be 'label' or 'full', got {correlations!r}")
        #: "label" computes only label-vs-feature correlations; "full" also
        #: records the (d, d) feature-feature matrix in the summary
        #: (reference SanityChecker.scala:634-638 featureLabelCorrOnly)
        self.correlations = correlations
        self.seed = seed
        self.mesh = None

    def set_mesh(self, mesh) -> "SanityChecker":
        """Run the stats pass (colStats + correlations + contingency counts)
        over rows sharded on the mesh's 'data' axis — the TPU-native analog
        of the reference's distributed colStats/reduceByKey
        (SanityChecker.scala:574-576, :433-440). XLA inserts the psum
        collectives; pad rows carry mask=False."""
        self.mesh = mesh
        return self

    # -- fit ------------------------------------------------------------------
    def fit(self, table: FeatureTable) -> Transformer:
        return self.fit_queued(table).finish_now()

    def fit_queued(self, table: FeatureTable) -> PendingFit:
        """Queued-fit protocol (stages/base.py): dispatch every device stat
        program (col stats, label correlation, optional full matrix,
        contingency counts) and defer the single host transfer + column
        decisions to finish — workflow-level CV queues all F folds' checker
        fits before one sync (reference OpValidator.applyDAG :228-256 runs
        fold DAG copies on concurrent Futures)."""
        label_f, vec_f = self.input_features
        y = np.asarray(table[label_f.name].values, dtype=np.float32).reshape(-1)
        col = table[vec_f.name]
        vm: Optional[VectorMetadata] = col.metadata.get("vector_meta")
        # the feature matrix stays on device end to end — at millions of rows
        # a host round-trip would dwarf the stats kernels themselves
        Xd_all = jnp.asarray(col.values, dtype=jnp.float32)
        n, d = Xd_all.shape

        # sampling (reference fraction :524-529: the requested check_sample
        # fraction is clamped so the sample never goes below
        # sample_lower_limit rows nor above sample_upper_limit)
        min_frac = min(1.0, self.sample_lower_limit / max(n, 1))
        max_frac = max(0.0, self.sample_upper_limit / max(n, 1))
        frac = max(min(self.check_sample, max_frac), min_frac)
        target = min(int(round(n * frac)), n)
        if target < n:
            rng = np.random.RandomState(self.seed)
            idx = rng.choice(n, size=target, replace=False)
            Xd, ys = Xd_all[jnp.asarray(idx)], y[idx]
        else:
            Xd, ys = Xd_all, y
        yd = jnp.asarray(ys)
        mesh = getattr(self, "mesh", None)
        row_mask = None
        if mesh is not None:
            from ...parallel.sharded import shard_rows
            Xd, row_mask, _ = shard_rows(Xd, None, mesh)
            yd, _, _ = shard_rows(yd, None, mesh)
            self._stats_input_sharding = str(Xd.sharding)
        stats = col_stats(Xd, row_mask)
        if self.correlation_type_spearman:
            corr = spearman_correlation(Xd, yd, row_mask)
        else:
            corr = pearson_correlation(Xd, yd, row_mask)
        dev: Dict[str, Any] = dict(stats._asdict())
        dev["corr"] = corr
        if getattr(self, "correlations", "label") == "full":
            # (d, d) feature-feature matrix on device (one MXU matmul);
            # Spearman mode ranks the columns first, matching the label path
            Xc = Xd
            if self.correlation_type_spearman:
                import jax as _jax
                from ...ops.stats import _rank
                Xc = _jax.vmap(_rank, in_axes=1, out_axes=1)(Xd)
            dev["feature_corr"] = pearson_correlation_matrix(Xc, row_mask)

        # categorical association stats per feature group (reference
        # :420-516): dispatch the one contingency matmul for every
        # indicator column now; the per-group association stats run on the
        # tiny (m, L) numpy tables at finish time
        groups: List[Any] = []
        if vm is not None:
            labels = np.unique(ys)
            is_binary_like = (len(labels) <= 20
                              and np.allclose(labels, labels.astype(int)))
            if is_binary_like:
                # yd is the (possibly mesh-padded) device label vector; pad
                # rows are excluded via row_mask in the contingency matmul
                label_idx = yd.astype(jnp.int32)
                num_labels = int(ys.max()) + 1
                # only indicator (0/1 pivot) groups get contingency stats
                groups = [(g, idxs) for g, idxs in vm.index_of_group().items()
                          if all(vm.columns[i].indicator_value is not None
                                 for i in idxs)]
                if groups:
                    all_idx = np.concatenate(
                        [np.asarray(idxs) for _, idxs in groups])
                    dev["counts"] = contingency_table(
                        Xd[:, jnp.asarray(all_idx)], label_idx, num_labels,
                        row_mask)
        n_sample = int(len(ys))
        sharding_note = getattr(self, "_stats_input_sharding", None)

        def finish(host: Dict[str, np.ndarray]) -> Transformer:
            return self._finish_from_host(host, d=d, vm=vm, groups=groups,
                                          n_sample=n_sample,
                                          sharding_note=sharding_note)

        return PendingFit(dev, finish)

    # -- streaming fit (OpWorkflow.train(stream=...), docs/streaming.md) -----
    def fit_streaming_prep(self, run):
        """Single-pass prep spec ``(pass_id, fold, extract, finish)`` for
        the trainer's fused layer sweep (streaming/trainer.py) — the
        sanity stats were already one composite pass, so the spec just
        exposes its pieces."""
        from ...streaming.folds import (
            ColStatsFold, CompositeFold, ContingencyFold, CorrelationFold,
        )
        if self.correlation_type_spearman:
            raise ValueError(
                "SanityChecker(correlation_type_spearman=True) cannot fit "
                "on a stream: exact ranks need the full dataset. Use "
                "Pearson, or train in-core.")
        label_f, vec_f = self.input_features
        probe = run.probe_table()
        col = probe[vec_f.name]
        vm: Optional[VectorMetadata] = col.metadata.get("vector_meta")
        d = col.width

        groups: List[Any] = []
        all_idx = np.zeros(0, np.int64)
        if vm is not None:
            groups = [(g, idxs) for g, idxs in vm.index_of_group().items()
                      if all(vm.columns[i].indicator_value is not None
                             for i in idxs)]
            if groups:
                all_idx = np.concatenate(
                    [np.asarray(idxs) for _, idxs in groups])
        folds: Dict[str, Any] = {
            "stats": ColStatsFold(d),
            "corr": CorrelationFold(
                d, full=getattr(self, "correlations", "label") == "full"),
        }
        if groups:
            folds["cont"] = ContingencyFold(len(all_idx))
        composite = CompositeFold(folds)

        def extract(table: FeatureTable):
            X = np.asarray(table[vec_f.name].values, dtype=np.float32)
            y = np.asarray(table[label_f.name].values,
                           dtype=np.float32).reshape(-1)
            parts = {"stats": (X,), "corr": (X, y)}
            if groups:
                parts["cont"] = (X[:, all_idx], y)
            return (parts,)

        def finish(state) -> Transformer:
            grps = groups
            res = composite.finalize(state)
            stats = res["stats"]
            host: Dict[str, np.ndarray] = {
                "count": stats.count, "mean": stats.mean,
                "variance": stats.variance, "min": stats.min,
                "max": stats.max, "corr": res["corr"],
            }
            if folds["corr"].full:
                host["feature_corr"] = folds["corr"].finalize_matrix(
                    state["corr"])
            n_sample = int(state["corr"]["n"])
            if grps:
                counts = res["cont"]
                if counts is None:
                    # labels were not binary-like: same branch as in-core
                    grps = []
                else:
                    host["counts"] = counts.astype(np.float64)
            return self._finish_from_host(host, d=d, vm=vm, groups=grps,
                                          n_sample=n_sample)

        return "sanity", composite, extract, finish

    def fit_streaming(self, run) -> Transformer:
        """One chunked pass of monoid folds — the out-of-core dual of the
        device stats pass: col moments, label correlations (co-moment
        merge), optional full correlation matrix, and contingency counts
        all accumulate in exact-f64 host folds and feed the SAME
        ``_finish_from_host`` decision logic the in-core fit uses. Two
        documented deviations: no sampling (the stream folds every row —
        ``check_sample``/limits describe the in-core reservoir) and no
        Spearman (exact streaming ranks need a sort over the full
        dataset)."""
        pass_id, fold, extract, finish = self.fit_streaming_prep(run)
        return finish(run.fold(pass_id, fold, extract))

    def _finish_from_host(self, host: Dict[str, np.ndarray], *, d: int,
                          vm: Optional[VectorMetadata], groups: List[Any],
                          n_sample: int,
                          sharding_note: Optional[str] = None) -> Transformer:
        """Column decisions from the materialized stat arrays — shared by
        the device fit (``fit_queued``) and the streaming fold fit
        (``fit_streaming``): both paths hand the identical host dict
        (count/mean/variance/min/max, corr, optional feature_corr, stacked
        contingency counts) to the identical removal logic."""
        stats = {k: host[k]
                 for k in ("count", "mean", "variance", "min", "max")}
        corr = host["corr"]
        feature_corr = host.get("feature_corr")
        cramers_by_col = np.full(d, np.nan)
        rule_conf_by_col = np.full(d, np.nan)
        support_by_col = np.full(d, np.nan)
        group_cramers: Dict[str, float] = {}
        group_mi: Dict[str, float] = {}
        group_pmi: Dict[str, List[List[float]]] = {}
        if groups:
            counts = host["counts"]
            off = 0
            for group, idxs in groups:
                m = len(idxs)
                cs = _contingency_stats_np(counts[off:off + m])
                off += m
                group_cramers[group] = cs["cramers_v"]
                group_mi[group] = cs["mutual_info"]
                group_pmi[group] = [
                    [round(float(x), 6) for x in r]
                    for r in cs["pointwise_mutual_info"]]
                for j, i_col in enumerate(idxs):
                    cramers_by_col[i_col] = cs["cramers_v"]
                    rule_conf_by_col[i_col] = cs["max_rule_confidence"][j]
                    support_by_col[i_col] = cs["support"][j]

        # removal reasons (reference ColumnStatistics.reasonsToRemove :783-832)
        reasons: Dict[int, List[str]] = {}

        def flag(i: int, why: str):
            reasons.setdefault(i, []).append(why)

        for i in range(d):
            if stats["variance"][i] < self.min_variance:
                flag(i, f"variance {stats['variance'][i]:.3g} below min {self.min_variance}")
            c = corr[i]
            if not np.isnan(c):
                if abs(c) > self.max_correlation:
                    flag(i, f"label correlation {c:.3f} above max {self.max_correlation} (leakage)")
                elif abs(c) < self.min_correlation:
                    flag(i, f"label correlation {c:.3f} below min {self.min_correlation}")
            if not np.isnan(cramers_by_col[i]) and cramers_by_col[i] > self.max_cramers_v:
                flag(i, f"Cramér's V {cramers_by_col[i]:.3f} above max {self.max_cramers_v}")
            if (not np.isnan(rule_conf_by_col[i])
                    and rule_conf_by_col[i] >= self.max_rule_confidence
                    and support_by_col[i] >= 0
                    and support_by_col[i] * n_sample >= self.min_required_rule_support):
                flag(i, f"association rule confidence {rule_conf_by_col[i]:.3f} "
                        f"at/above max {self.max_rule_confidence} (leakage)")

        # feature-group propagation (reference: if one indicator of a pivot
        # group leaks, the whole group goes). protect_text_shared_hash
        # exempts shared-hash text columns — a hash slot aggregates many
        # tokens, so a sibling's leak says nothing about it (reference
        # reasonsToRemove :821 + isTextSharedHash :840)
        if self.remove_feature_group and vm is not None and reasons:
            all_groups = vm.index_of_group()
            leak = {i for i, why in reasons.items()
                    if any("leakage" in w or "Cramér" in w for w in why)}
            for group, idxs in all_groups.items():
                if leak.intersection(idxs):
                    for i in idxs:
                        if i in reasons:
                            continue
                        if (self.protect_text_shared_hash
                                and _is_text_shared_hash(vm.columns[i])):
                            continue
                        flag(i, f"sibling column in group '{group}' flagged for leakage")

        to_remove = sorted(reasons) if self.remove_bad_features else []
        keep = [i for i in range(d) if i not in set(to_remove)]
        if not keep:
            raise ValueError(
                "SanityChecker would remove ALL feature columns — loosen thresholds")

        names = vm.column_names() if vm is not None else [f"c{i}" for i in range(d)]
        summary = SanityCheckerSummary(
            stats=ColumnStatistics(
                names=names,
                count=stats["count"].tolist(),
                mean=stats["mean"].tolist(),
                variance=stats["variance"].tolist(),
                min=stats["min"].tolist(),
                max=stats["max"].tolist()),
            categorical=CategoricalGroupStats(
                cramers_v={g: v for g, v in group_cramers.items()},
                mutual_info=group_mi,
                pointwise_mutual_info=group_pmi),
            correlations_with_label=[None if np.isnan(c) else float(c)
                                     for c in corr],
            correlation_type=("spearman" if self.correlation_type_spearman
                              else "pearson"),
            dropped=[names[i] for i in to_remove],
            reasons={names[i]: why for i, why in reasons.items()},
            sample_size=n_sample,
            feature_correlations=feature_corr,
        )
        model = SanityCheckerModel(keep_indices=keep, summary=summary)
        model.summary_metadata = summary.to_json()
        # diagnostic: how the stats pass was placed (asserted by the
        # multichip dryrun — 'data'-sharded under with_mesh)
        model._stats_input_sharding = sharding_note
        return self._finalize_model(model)


class SanityCheckerModel(AllowLabelAsInput, Transformer):
    """Index-keep filter (reference SanityCheckerModel.transformFn :707-717)."""

    output_type = OPVector

    def __init__(self, keep_indices: List[int], summary: Dict[str, Any], uid=None):
        super().__init__("sanityCheck", uid)
        self.keep_indices = list(keep_indices)
        self.summary = summary
        self.summary_metadata = summary

    def device_columnar(self, env):
        """Pure-jax dual for the fused serve program
        (local/scoring.compiled_score_function): index-keep slice."""
        import jax.numpy as jnp
        vals, mask = env[self.input_features[1].name]
        return vals[:, jnp.asarray(self.keep_indices)], mask

    def device_inputs(self):
        """Only the vector input is read at serve time (the label feeds the
        estimator, not the fitted filter)."""
        return [self.input_features[1].name]

    def transform_column(self, table: FeatureTable) -> Column:
        _, vec_f = self.input_features
        col = table[vec_f.name]
        keep = np.asarray(self.keep_indices)
        vm: Optional[VectorMetadata] = col.metadata.get("vector_meta")
        new_meta = {}
        if vm is not None:
            new_meta["vector_meta"] = VectorMetadata(
                self.get_output().name, vm.select(self.keep_indices).columns)
        vals = col.values
        if isinstance(vals, np.ndarray):
            out = np.ascontiguousarray(vals[:, keep])
        else:  # device array: slice on device, no host round-trip
            out = vals[:, jnp.asarray(keep)]
        return Column(OPVector, out, None, new_meta)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        _, vec_f = self.input_features
        v = row.get(vec_f.name) or []
        return [float(v[i]) for i in self.keep_indices]

    def summary_pretty(self) -> str:
        s = self.summary
        lines = [f"-- SanityChecker ({self.uid}) --",
                 f"sample size: {s['sampleSize']}",
                 f"columns kept: {len(self.keep_indices)} / {len(s['names'])}"]
        if s["dropped"]:
            lines.append("dropped:")
            for name in s["dropped"]:
                lines.append(f"  {name}: " + "; ".join(s["reasons"][name]))
        return "\n".join(lines)
