from .sanity_checker import SanityChecker, SanityCheckerModel
