"""ModelSelector factories (reference:
core/.../impl/classification/BinaryClassificationModelSelector.scala:52-179,
MultiClassificationModelSelector.scala, impl/regression/RegressionModelSelector.scala).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..tuning.splitters import DataBalancer, DataCutter, DataSplitter, Splitter
from ..tuning.validators import OpCrossValidation, OpTrainValidationSplit
from .model_selector import ModelSelector


def _build(problem: str, validator, splitter, models, evaluator):
    return ModelSelector(problem=problem, validator=validator,
                         splitter=splitter, models=models, evaluator=evaluator)


class BinaryClassificationModelSelector:
    """Defaults (reference :52-129): CV 3 folds, AuPR metric, DataBalancer."""

    @staticmethod
    def with_cross_validation(num_folds: int = 3, seed: int = 42,
                              splitter: Optional[Splitter] = None,
                              models: Optional[Sequence[Tuple[Any, Optional[List[Dict]]]]] = None,
                              evaluator=None, stratify: bool = False,
                              **validator_kw) -> ModelSelector:
        # validator_kw passes through to OpCrossValidation — e.g.
        # max_eval_rows=None, exact_sweep_fits=True for reference-exact
        # sweep semantics (docs/benchmarks.md "Sweep fidelity")
        return _build("binary",
                      OpCrossValidation(num_folds=num_folds, seed=seed, stratify=stratify,
                                        **validator_kw),
                      splitter if splitter is not None else DataBalancer(seed=seed),
                      models, evaluator)

    @staticmethod
    def with_train_validation_split(train_ratio: float = 0.75, seed: int = 42,
                                    splitter: Optional[Splitter] = None,
                                    models=None, evaluator=None,
                                    stratify: bool = False,
                                    **validator_kw) -> ModelSelector:
        return _build("binary",
                      OpTrainValidationSplit(train_ratio=train_ratio, seed=seed,
                                             stratify=stratify, **validator_kw),
                      splitter if splitter is not None else DataBalancer(seed=seed),
                      models, evaluator)


class MultiClassificationModelSelector:
    """Defaults (reference MultiClassificationModelSelector.scala): CV 3 folds,
    F1 metric, DataCutter."""

    @staticmethod
    def with_cross_validation(num_folds: int = 3, seed: int = 42,
                              splitter: Optional[Splitter] = None,
                              models=None, evaluator=None,
                              stratify: bool = False,
                              **validator_kw) -> ModelSelector:
        return _build("multiclass",
                      OpCrossValidation(num_folds=num_folds, seed=seed, stratify=stratify,
                                        **validator_kw),
                      splitter if splitter is not None else DataCutter(seed=seed),
                      models, evaluator)

    @staticmethod
    def with_train_validation_split(train_ratio: float = 0.75, seed: int = 42,
                                    splitter: Optional[Splitter] = None,
                                    models=None, evaluator=None,
                                    stratify: bool = False,
                                    **validator_kw) -> ModelSelector:
        return _build("multiclass",
                      OpTrainValidationSplit(train_ratio=train_ratio, seed=seed,
                                             stratify=stratify, **validator_kw),
                      splitter if splitter is not None else DataCutter(seed=seed),
                      models, evaluator)


class RegressionModelSelector:
    """Defaults (reference RegressionModelSelector.scala): CV 3 folds, RMSE,
    DataSplitter."""

    @staticmethod
    def with_cross_validation(num_folds: int = 3, seed: int = 42,
                              splitter: Optional[Splitter] = None,
                              models=None, evaluator=None,
                              **validator_kw) -> ModelSelector:
        return _build("regression",
                      OpCrossValidation(num_folds=num_folds, seed=seed,
                                        **validator_kw),
                      splitter if splitter is not None else DataSplitter(seed=seed),
                      models, evaluator)

    @staticmethod
    def with_train_validation_split(train_ratio: float = 0.75, seed: int = 42,
                                    splitter: Optional[Splitter] = None,
                                    models=None, evaluator=None,
                                    **validator_kw) -> ModelSelector:
        return _build("regression",
                      OpTrainValidationSplit(train_ratio=train_ratio, seed=seed,
                                             **validator_kw),
                      splitter if splitter is not None else DataSplitter(seed=seed),
                      models, evaluator)
