"""ModelSelector — automated model selection.

TPU re-design of the reference ModelSelector
(reference: core/.../impl/selector/ModelSelector.scala:135-196 fit flow,
:216-255 SelectedModel; ModelSelectorSummary.scala): splitter prepares the
train data (balance/cut), the validator sweeps families × grids × folds as
vmapped device batches, the winner refits on the full prepared train set, and
the fitted SelectedModel emits a Prediction column.
"""
from __future__ import annotations

import os

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...models.api import MODEL_REGISTRY, FittedParams, ModelFamily
from ...robustness import faults
from ...robustness.guards import (
    AllCandidatesFailedError, params_finite, quarantine_non_finite,
)
from ...robustness.policy import FaultLog, FaultReport
from ...stages.base import AllowLabelAsInput, Estimator, Transformer
from ...table import Column, FeatureTable
from ...types import OPVector, Prediction, RealNN
from ..tuning.splitters import DataSplitter, PreparedData, Splitter
from ..tuning.validators import BestEstimator, OpCrossValidation, OpValidator
from ...utils.padding import bucket_for

#: refit-fallback depth: how many ranked candidates may be tried when the
#: winner's full-data refit diverges before the train aborts aggregated
_MAX_REFIT_ATTEMPTS = 3


@dataclass
class ModelSelectorSummary:
    """(reference ModelSelectorSummary.scala:308)"""
    validation_type: str
    validation_metric: str
    problem: str
    best_model_type: str
    best_hyper: Dict[str, Any]
    best_metric_value: float
    larger_better: bool = True
    validation_results: List[Any] = field(default_factory=list)
    train_evaluation: Dict[str, Any] = field(default_factory=dict)
    holdout_evaluation: Dict[str, Any] = field(default_factory=dict)
    splitter_summary: Dict[str, Any] = field(default_factory=dict)
    #: validator's per-config validation-row cap (None = exact). Surfaced so
    #: a selection difference vs the reference's full-row scoring is
    #: explainable from the summary alone (the reference always scores every
    #: validation row, OpValidator.scala:270-312).
    validation_eval_row_cap: Optional[int] = None
    #: candidates excluded from selection (non-finite CV metrics, fits that
    #: threw, non-finite refit params), with their failure reasons — the
    #: sweep continued without them (docs/robustness.md)
    quarantined: List[Dict[str, Any]] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return {
            "validationType": self.validation_type,
            "validationMetric": self.validation_metric,
            "problem": self.problem,
            "bestModelType": self.best_model_type,
            "bestHyperparameters": self.best_hyper,
            "bestMetricValue": self.best_metric_value,
            "largerBetter": self.larger_better,
            "validationResults": [r.to_json() for r in self.validation_results],
            "trainEvaluation": self.train_evaluation,
            "holdoutEvaluation": self.holdout_evaluation,
            "splitterSummary": self.splitter_summary,
            "validationEvalRowCap": self.validation_eval_row_cap,
            "quarantinedCandidates": [dict(r) for r in self.quarantined],
        }


class ModelSelector(AllowLabelAsInput, Estimator):
    """Estimator[(RealNN label, OPVector features)] → Prediction."""

    input_types = (RealNN, OPVector)
    output_type = Prediction

    def __init__(self, problem: str,
                 validator: Optional[OpValidator] = None,
                 splitter: Optional[Splitter] = None,
                 models: Optional[Sequence[Tuple[Any, Optional[List[Dict[str, Any]]]]]] = None,
                 evaluator=None,
                 uid: Optional[str] = None):
        super().__init__("modelSelector", uid)
        if problem not in ("binary", "multiclass", "regression"):
            raise ValueError(f"unknown problem kind '{problem}'")
        self.problem = problem
        self.validator = validator or OpCrossValidation()
        self.splitter = splitter if splitter is not None else DataSplitter()
        self.evaluator = evaluator
        self.models = self._resolve_models(models)
        self.mesh = None

    def set_mesh(self, mesh) -> "ModelSelector":
        """Shard the sweep over a ('data', 'model') mesh: rows over 'data',
        the config batch over 'model' (SURVEY §2.10 P1/P2; the reference's
        8-thread Future pool becomes mesh axes). Also shards the winner
        refit, and the fitted SelectedModel keeps scoring row-sharded (the
        train/holdout evaluations ride it)."""
        self.validator.mesh = mesh
        self.mesh = mesh
        return self

    def set_sweep_checkpoint(self, ckpt) -> "ModelSelector":
        """Preemption-tolerant sweeps (wired by ``with_checkpoint_dir``):
        every evaluated candidate batch persists its fold metrics to the
        given :class:`~...impl.tuning.sweep_checkpoint.SweepCheckpoint` as
        it completes, and a resumed ``train()`` replays the persisted
        records — fingerprint-matched to the data, folds, and sweep config
        — instead of re-running them (docs/robustness.md "Resumable
        sweeps"). Train-time wiring only; never serialized with the fitted
        model."""
        self.validator._sweep_ckpt = ckpt
        return self

    def _resolve_models(self, models):
        resolved: List[Tuple[ModelFamily, List[Dict[str, Any]]]] = []
        from ...models import glm, trees  # noqa: F401 (registers families)
        if models is None:
            # reference default model types (BinaryClassificationModelSelector
            # Defaults.modelTypesToUse :59-61, MultiClassification :59-61,
            # RegressionModelSelector :59-61; NB/DT/XGB off by default)
            defaults = {
                "binary": ["OpLogisticRegression", "OpRandomForestClassifier",
                           "OpGBTClassifier", "OpLinearSVC"],
                "multiclass": ["OpLogisticRegression",
                               "OpRandomForestClassifier"],
                "regression": ["OpLinearRegression", "OpRandomForestRegressor",
                               "OpGBTRegressor",
                               "OpGeneralizedLinearRegression"],
            }[self.problem]
            models = [(MODEL_REGISTRY[name], None) for name in defaults]
        for fam, grid in models:
            if isinstance(fam, str):
                fam = MODEL_REGISTRY[fam]
            if self.problem not in fam.supports:
                raise ValueError(
                    f"{fam.name} does not support problem kind '{self.problem}'")
            if grid is None:
                grid = fam.default_grid(self.problem)
                # test-time knob: shrink DEFAULT grids so CPU CI suites stay
                # fast; explicitly-passed grids are never touched. Env (not a
                # fixture) because the CLI test's generated app runs in a
                # subprocess. Loud, so a leaked env can't silently degrade a
                # real AutoML run.
                if os.environ.get("TG_FAST_GRIDS", "").lower() in ("1", "true"):
                    import logging
                    logging.getLogger(__name__).warning(
                        "TG_FAST_GRIDS is set: default %s grid truncated "
                        "%d -> 2 configs (test mode)", fam.name, len(grid))
                    grid = grid[:2]
            resolved.append((fam, grid))
        return resolved

    @property
    def validation_metric(self) -> Tuple[str, bool]:
        if self.evaluator is not None:
            return self.evaluator.default_metric, self.evaluator.larger_better
        return {"binary": ("AuPR", True),
                "multiclass": ("F1", True),
                "regression": ("RootMeanSquaredError", False)}[self.problem]

    # -- workflow-level CV (reference findBestEstimator :112-121) ------------
    def find_best_estimator(self, table: FeatureTable,
                            during_layers: Sequence[Sequence[Tuple[Any, int]]],
                            ) -> BestEstimator:
        """Leakage-free validation: per fold, fit fresh copies of the in-CV
        DAG (label-dependent prep like SanityChecker) on the fold's train rows
        only, then sweep the model grid on the fold-specific feature matrix
        (reference OpValidator.applyDAG :228-256 + getSummary). The winner is
        recorded; the subsequent normal ``fit`` skips validation and refits it
        on the full prepared data (reference OpWorkflow.fitStages :397-442)."""
        label_f, vec_f = self.input_features
        y_all = np.asarray(table[label_f.name].values,
                           dtype=np.float32).reshape(-1)
        n = len(y_all)
        # reserve the SAME holdout the later fit() will carve out (splitter
        # split is seed-deterministic in n), so selection never sees it
        if self.splitter is not None and self.splitter.reserve_test_fraction > 0:
            train_idx, _ = self.splitter.split(n)
        else:
            train_idx = np.arange(n)
        y_train_raw = y_all[train_idx]
        prep = (self.splitter.pre_validation_prepare(y_train_raw)
                if self.splitter is not None
                else PreparedData(indices=np.arange(len(y_train_raw))))
        sel_rows = train_idx[prep.indices]
        sub = table.take(sel_rows)
        y = y_all[sel_rows]
        if prep.label_mapping:
            y = np.vectorize(
                lambda v: prep.label_mapping.get(int(v), -1))(y).astype(np.float32)
        num_classes = int(y.max()) + 1 if self.problem != "regression" else 1
        if self.problem == "binary":
            num_classes = 2
        metric_name, larger_better = self.validation_metric

        val_masks = self.validator.make_splits(y)          # (F, n)
        F = val_masks.shape[0]
        # pass 1: fit every fold's in-CV DAG copy and collect its feature
        # matrix (fold-specific SanityCheckers may keep different columns).
        # Stage-by-stage across folds: each estimator's F fold fits are
        # QUEUED via the fit_queued protocol and resolved with one fused
        # host transfer (stages/base.materialize_pending) — the fold-serial
        # host loop's F sync round-trips were the residual wall over plain
        # CV (reference fits fold DAG copies on concurrent Futures,
        # OpValidator.applyDAG :228-256). Matrices park on HOST between
        # passes — holding F device copies would multiply peak HBM by the
        # fold count at 1M×543 scale
        from ...stages.base import materialize_pending
        fold_train_rows = [np.nonzero(~val_masks[f])[0] for f in range(F)]
        fold_tbls: List[Any] = [sub] * F
        for layer in during_layers:
            for stage, _ in layer:
                if isinstance(stage, Estimator):
                    # fit on each fold's train rows only; one transform of
                    # the full table serves both train and val rows
                    pend = [stage.fit_queued(
                        fold_tbls[f].take(fold_train_rows[f]))
                        for f in range(F)]
                    stage_models = materialize_pending(pend)
                else:
                    stage_models = [stage] * F
                for f in range(F):
                    fold_tbls[f] = stage_models[f].transform(fold_tbls[f])
        fold_X: List[Optional[np.ndarray]] = []
        for f in range(F):
            if vec_f.name not in fold_tbls[f].column_names:
                raise ValueError(
                    f"in-CV DAG did not produce feature '{vec_f.name}'")
            fold_X.append(np.asarray(fold_tbls[f][vec_f.name].values,
                                     dtype=np.float32))
        del fold_tbls
        # pass 2: pad every fold's matrix to the widest fold with zero
        # columns (inert: dead-column standardization pins their linear
        # coefficients to 0, constant columns never win a tree split), so
        # all F validates share ONE compiled program per family instead of
        # paying a full compile per fold-specific width (reference
        # OpValidator.applyDAG :228-256 fits fold DAG copies concurrently;
        # here the concurrency win is amortized compilation + queued device
        # programs)
        d_max = max(x.shape[1] for x in fold_X)
        yd = jnp.asarray(y)
        # when all folds' matrices fit on device together, queue EVERY
        # fold's validate programs back-to-back and sync ONCE at the end
        # (resolve=False) — the fold-serial host loop was the residual 1.75x
        # over plain CV; at larger scales matrices park on host and each
        # fold resolves before the next uploads, bounding peak HBM to one
        # fold matrix (reference fits fold DAG copies on concurrent
        # Futures, OpValidator.applyDAG :228-256)
        defer = F * val_masks.shape[1] * d_max * 4 <= (2 << 30)
        fold_results: List[Any] = []
        for f in range(F):
            Xh = fold_X[f]
            fold_X[f] = None          # drop the host ref once uploaded
            if Xh.shape[1] != d_max:
                Xh = np.pad(Xh, ((0, 0), (0, d_max - Xh.shape[1])))
            fold_results.append(self.validator.validate(
                self.models, jnp.asarray(Xh), yd, self.problem, metric_name,
                larger_better, num_classes, val_masks=val_masks[f][None, :],
                resolve=not defer))
        fold_results = [r.resolve() if hasattr(r, "resolve") else r
                        for r in fold_results]

        # average fold winners per (family, grid point); a candidate with a
        # non-finite metric in ANY fold has a non-finite mean and is
        # quarantined from the merged selection (guards; the per-fold
        # validates already recorded the fold-level reports)
        best: Optional[BestEstimator] = None
        merged: List[Any] = []
        quarantined: List[Dict[str, Any]] = []
        for i, (family, grid) in enumerate(self.models):
            folds = np.stack([fr.results[i].fold_metrics[0]
                              for fr in fold_results])      # (F, G)
            r = fold_results[0].results[i]
            mean, masked, records = quarantine_non_finite(
                family.name, list(grid), folds, metric_name, larger_better)
            quarantined.extend(records)
            r.fold_metrics, r.mean_metrics = folds, mean
            merged.append(r)
            if not np.isfinite(mean).any():
                continue
            g_best = int(np.argmax(masked) if larger_better
                         else np.argmin(masked))
            value = float(mean[g_best])
            if best is None or ((value > best.metric_value) if larger_better
                                else (value < best.metric_value)):
                best = BestEstimator(family.name, dict(grid[g_best]), value)
        if best is None:
            raise AllCandidatesFailedError(quarantined)
        best.results = merged
        best.quarantined = quarantined
        self._preset_best = best
        return best

    # -- fit (reference ModelSelector.fit :135-196) --------------------------
    def fit(self, table: FeatureTable) -> Transformer:
        label_f, vec_f = self.input_features
        y_all = np.asarray(table[label_f.name].values, dtype=np.float32).reshape(-1)
        # the feature matrix never visits the host: row selections for the
        # holdout/balancer are index gathers on device
        Xd_all = jnp.asarray(table[vec_f.name].values, dtype=jnp.float32)
        n = len(y_all)

        # reserve holdout (reference splitter.split in workflow fitStages)
        if self.splitter is not None and self.splitter.reserve_test_fraction > 0:
            train_idx, test_idx = self.splitter.split(n)
        else:
            train_idx, test_idx = np.arange(n), np.array([], dtype=np.int64)

        y_train_raw = y_all[train_idx]
        prep = (self.splitter.pre_validation_prepare(y_train_raw)
                if self.splitter is not None
                else PreparedData(indices=np.arange(len(y_train_raw))))
        sel = train_idx[prep.indices]
        y = y_all[sel]
        if prep.label_mapping:
            y = np.vectorize(lambda v: prep.label_mapping.get(int(v), -1))(y).astype(np.float32)
        num_classes = int(y.max()) + 1 if self.problem != "regression" else 1
        if self.problem == "binary":
            num_classes = 2

        metric_name, larger_better = self.validation_metric
        Xd, yd = Xd_all[jnp.asarray(sel)], jnp.asarray(y)
        preset = getattr(self, "_preset_best", None)
        if preset is not None:
            # workflow-level CV already ran (find_best_estimator); skip the
            # in-selector sweep and refit the recorded winner. Consume it so a
            # later refit on new data validates from scratch.
            self._preset_best = None
            best = preset
        else:
            best = self.validator.validate(
                self.models, Xd, yd, self.problem, metric_name, larger_better,
                num_classes)

        # deterministic preemption point: the sweep completed (and, under a
        # checkpoint dir, persisted) but the winner never refit — a resume
        # replays the sweep from disk and goes straight to the refit
        faults.inject("preempt.refit")

        # refit winner on full prepared train (reference :158-159); rows
        # bucket-padded with zero weights for compile reuse
        n_fit = len(y)
        n_data = self.mesh.shape["data"] if self.mesh is not None else 1
        n_pad = bucket_for(n_fit, multiple_of=n_data)
        Xf, yf = Xd, yd
        if n_pad != n_fit:
            Xf = jnp.pad(Xd, ((0, n_pad - n_fit), (0, 0)))
            yf = jnp.pad(yd, (0, n_pad - n_fit))
        W = jnp.zeros((1, n_pad), jnp.float32).at[:, :n_fit].set(1.0)
        if self.mesh is not None:
            # the winner refit is a full-data fit — shard its rows over
            # 'data' like the sweep (round-3 left it unsharded: the most
            # expensive single fit of the train path ran on one chip);
            # placements retry transient link errors (robustness/policy.py)
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ...parallel.distributed import retrying_device_put
            Xf = retrying_device_put(Xf, NamedSharding(self.mesh,
                                                       P("data", None)))
            yf = retrying_device_put(yf, NamedSharding(self.mesh, P("data")))
            W = retrying_device_put(W, NamedSharding(self.mesh,
                                                     P(None, "data")))
        # refit with a non-finite guard and fallback: a winner that diverges
        # on the full prepared train (the sweep fit at a sample/cap; the
        # refit is the exact program) is quarantined and the next-ranked
        # finite candidate refits instead. With no fault the first candidate
        # IS the sweep winner, bit-identically.
        fitted = None
        best_used = (best.family_name, dict(best.hyper), best.metric_value)
        refit_quarantine: List[Dict[str, Any]] = []
        for fam_name, hyper, value in self._ranked_candidates(
                best, larger_better)[:_MAX_REFIT_ATTEMPTS]:
            family = MODEL_REGISTRY[fam_name]
            try:
                faults.inject("selector.refit", key=fam_name)
                garr = family.grid_to_arrays([hyper])
                params_b = family.fit_batch(Xf, yf, W, garr, num_classes)
                sel_params = family.select_params(params_b, 0)
                if not params_finite(sel_params,
                                     getattr(family, "inf_ok_params", ())):
                    raise ArithmeticError(
                        "refit produced non-finite fitted params")
                fitted = FittedParams(
                    family=fam_name, params=sel_params,
                    hyper=dict(hyper), num_classes=num_classes)
                best_used = (fam_name, dict(hyper), value)
                break
            except Exception as e:
                rec = {"family": fam_name, "hyper": dict(hyper),
                       "reason": f"refit failed: {type(e).__name__}: {e}"}
                refit_quarantine.append(rec)
                FaultLog.record(FaultReport(site="selector.refit",
                                            kind="quarantine", detail=rec))
        if fitted is None:
            raise AllCandidatesFailedError(
                list(best.quarantined) + refit_quarantine)

        summary = ModelSelectorSummary(
            validation_type=type(self.validator).__name__,
            validation_metric=metric_name,
            problem=self.problem,
            best_model_type=best_used[0],
            best_hyper=best_used[1],
            best_metric_value=best_used[2],
            larger_better=larger_better,
            validation_results=best.results,
            splitter_summary=dict(getattr(self.splitter, "summary", {}) or {}),
            validation_eval_row_cap=getattr(self.validator, "max_eval_rows",
                                            None),
            quarantined=list(best.quarantined) + refit_quarantine,
        )
        model = SelectedModel(fitted=fitted, summary=summary,
                              label_mapping=prep.label_mapping)
        model.mesh = self.mesh
        model = self._finalize_model(model)

        # train/holdout evaluation (reference :168-188)
        ev = self._default_evaluator()
        ev.set_label_col(label_f.name)
        ev.set_prediction_col(model.get_output().name)
        train_tbl = table.take(train_idx)
        summary.train_evaluation = _scalar_metrics(
            ev.evaluate_all(model.transform(train_tbl)))
        if len(test_idx):
            test_tbl = table.take(test_idx)
            summary.holdout_evaluation = _scalar_metrics(
                ev.evaluate_all(model.transform(test_tbl)))
        model.summary_metadata = summary.to_json()
        return model

    def _ranked_candidates(self, best, larger_better: bool):
        """Winner first, then every other finite-metric candidate ordered by
        mean validation metric — the refit fallback order used when the
        winner's full-data refit throws or yields non-finite params."""
        ranked = [(best.family_name, dict(best.hyper), best.metric_value)]
        pool = []
        for r in best.results or []:
            for g, hyper in enumerate(r.grid):
                v = float(r.mean_metrics[g])
                if not np.isfinite(v):
                    continue
                if (r.family == ranked[0][0] and dict(hyper) == ranked[0][1]):
                    continue
                pool.append((r.family, dict(hyper), v))
        pool.sort(key=(lambda t: -t[2]) if larger_better else (lambda t: t[2]))
        return ranked + pool

    def _default_evaluator(self):
        if self.evaluator is not None:
            return self.evaluator
        from ...evaluators import (
            OpBinaryClassificationEvaluator, OpMultiClassificationEvaluator,
            OpRegressionEvaluator)
        return {"binary": OpBinaryClassificationEvaluator,
                "multiclass": OpMultiClassificationEvaluator,
                "regression": OpRegressionEvaluator}[self.problem]()


def _scalar_metrics(metrics: Dict[str, Any]) -> Dict[str, float]:
    return {k: v for k, v in metrics.items() if isinstance(v, (int, float))}


class SelectedModel(AllowLabelAsInput, Transformer):
    """The fitted winner (reference SelectedModel :216-255): emits a
    Prediction column (n, k) with keys prediction / probability_i /
    rawPrediction_i."""

    output_type = Prediction

    def __init__(self, fitted: FittedParams, summary: ModelSelectorSummary,
                 label_mapping: Optional[Dict[int, int]] = None, uid=None):
        super().__init__("modelSelector", uid)
        self.fitted = fitted
        self.summary = summary
        self.label_mapping = label_mapping
        self.summary_metadata: Dict[str, Any] = {}
        #: wiring attr (never serialized): when set, columnar scoring shards
        #: its rows over the mesh 'data' axis — the selector's train/holdout
        #: evaluations and any mesh-resident serve path ride it
        self.mesh = None

    def _unmap_prediction(self, pred: np.ndarray) -> np.ndarray:
        """Map dense class indices back to the original labels dropped/remapped
        by DataCutter (reference PredictionDeIndexer semantics)."""
        if not self.label_mapping or pred.size == 0:
            return pred
        inverse = {dense: orig for orig, dense in self.label_mapping.items()}
        return np.vectorize(lambda v: inverse.get(int(v), int(v)),
                            otypes=[np.float32])(pred)

    #: the predict is reduction-bearing (gemm / matvec / softmax): its
    #: summation order is only reproducible when X arrives as a program
    #: parameter, so the transform-plan compiler traces the Prediction
    #: emission into its OWN jitted program instead of mid-segment —
    #: keeping planned output bit-identical to the eager predict_one path
    #: (plan.py; docs/plan.md "Segment partitioning")
    device_fusion_barrier = True

    @property
    def device_fusable(self) -> bool:
        """True when the winning family has a jit-traceable predict — the
        Prediction emission then compiles into its own planned segment
        (plan.py, consumed by local/scoring.compiled_score_function;
        reference analog: the one serve pass of
        FitStagesUtil.scala:96-119)."""
        from ...models.api import ModelFamily
        family = MODEL_REGISTRY[self.fitted.family]
        return type(family).predict_parts is not ModelFamily.predict_parts

    def device_inputs(self):
        """Only the feature vector is read at serve time (the label input
        feeds training, not the fitted model)."""
        return [self.input_features[-1].name]

    def device_columnar(self, env):
        """Pure-jax dual of ``transform_column``: the (n, k) Prediction
        matrix in ``prediction_column``'s key order."""
        X, _ = env[self.device_inputs()[0]]
        family = MODEL_REGISTRY[self.fitted.family]
        parts = family.predict_parts(self.fitted, X)
        pred = parts["prediction"].reshape(-1)
        if self.label_mapping:
            # DataCutter label de-index (see _unmap_prediction), as a dense
            # lookup table: unmapped dense indices pass through unchanged
            inverse = {dense: orig for orig, dense in
                       self.label_mapping.items()}
            size = max(inverse) + 2
            inv = np.arange(size, dtype=np.float32)
            for dense, orig in inverse.items():
                inv[dense] = orig
            idx = jnp.clip(pred.astype(jnp.int32), 0, size - 1)
            pred = jnp.take(jnp.asarray(inv), idx)
        cols = [pred]
        for name in (Prediction.RawPredictionName,
                     Prediction.ProbabilityName):
            if name in parts:
                arr = parts[name]
                if arr.ndim == 1:
                    arr = arr[:, None]
                cols.extend(arr[:, i] for i in range(arr.shape[1]))
        return jnp.stack(cols, axis=1), None

    def transform_column(self, table: FeatureTable) -> Column:
        _, vec_f = self.input_features
        X = jnp.asarray(table[vec_f.name].values, dtype=jnp.float32)
        n = X.shape[0]
        # getattr: models loaded from disk predate the wiring attr (mesh is
        # never serialized; the loading context re-attaches it if sharding)
        mesh = getattr(self, "mesh", None)
        n_data = mesh.shape["data"] if mesh is not None else 1
        n_pad = bucket_for(n, multiple_of=n_data)
        if n_pad != n:  # bucket rows so the predict program is reused
            X = jnp.pad(X, ((0, n_pad - n), (0, 0)))
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            X = jax.device_put(X, NamedSharding(mesh, P("data", None)))
        family = MODEL_REGISTRY[self.fitted.family]
        parts = family.predict_one(self.fitted, X)
        if n_pad != n:
            parts = {k: v[:n] for k, v in parts.items()}
        parts = dict(parts,
                     prediction=self._unmap_prediction(parts["prediction"]))
        return prediction_column(parts)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        _, vec_f = self.input_features
        v = np.asarray(row.get(vec_f.name) or [], dtype=np.float32)[None, :]
        family = MODEL_REGISTRY[self.fitted.family]
        parts = family.predict_one(self.fitted, jnp.asarray(v))
        out = {"prediction": float(self._unmap_prediction(parts["prediction"])[0])}
        for name in ("probability", "rawPrediction"):
            if name in parts:
                for i, x in enumerate(np.asarray(parts[name][0]).reshape(-1)):
                    out[f"{name}_{i}"] = float(x)
        return out

    def summary_pretty(self) -> str:
        s = self.summary
        lines = [f"-- ModelSelector ({self.uid}) --",
                 f"Evaluated {len(s.validation_results)} model type(s) with "
                 f"{s.validation_type} on metric {s.validation_metric}",
                 f"Best model: {s.best_model_type} "
                 f"{s.best_hyper} → {s.validation_metric}={s.best_metric_value:.4f}"]
        for r in s.validation_results:
            hi, lo = np.max(r.mean_metrics), np.min(r.mean_metrics)
            b, w = (hi, lo) if s.larger_better else (lo, hi)
            lines.append(f"  {r.family}: best {b:.4f} "
                         f"worst {w:.4f} over {len(r.grid)} configs")
        if s.holdout_evaluation:
            keys = ("AuPR", "AuROC", "F1", "Error", "RootMeanSquaredError", "R2")
            show = {k: round(v, 4) for k, v in s.holdout_evaluation.items() if k in keys}
            lines.append(f"Holdout: {show}")
        if s.splitter_summary:
            lines.append(f"Splitter: {s.splitter_summary}")
        return "\n".join(lines)


def prediction_column(parts: Dict[str, np.ndarray]) -> Column:
    """Pack predict_one parts into a Prediction column."""
    n = len(parts["prediction"])
    keys: List[str] = [Prediction.PredictionName]
    cols: List[np.ndarray] = [np.asarray(parts["prediction"], dtype=np.float32).reshape(-1)]
    for name in (Prediction.RawPredictionName, Prediction.ProbabilityName):
        if name in parts:
            arr = np.asarray(parts[name], dtype=np.float32)
            if arr.ndim == 1:
                arr = arr[:, None]
            for i in range(arr.shape[1]):
                keys.append(f"{name}_{i}")
                cols.append(arr[:, i])
    mat = np.stack(cols, axis=1)
    return Column(Prediction, mat, None, {"keys": tuple(keys)})
