from .model_selector import ModelSelector, SelectedModel, ModelSelectorSummary
from .factories import (
    BinaryClassificationModelSelector, MultiClassificationModelSelector,
    RegressionModelSelector,
)
