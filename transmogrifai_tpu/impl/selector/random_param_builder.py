"""Random-search hyperparameter grids.

Mirrors the reference RandomParamBuilder (reference:
core/.../impl/selector/RandomParamBuilder.scala:196): instead of exhaustive
grids, draw N random points from per-parameter distributions — the random
sweep still runs as ONE vmapped fit_batch, so on TPU a 100-point random
search costs the same wall-clock shape as a 10-point grid."""
from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np


class RandomParamBuilder:
    """Fluent random-grid builder::

        grid = (RandomParamBuilder(seed=7)
                .log_uniform("regParam", 1e-4, 1.0)
                .uniform("elasticNetParam", 0.0, 1.0)
                .build(50))
    """

    def __init__(self, seed: int = 42):
        self._rng = np.random.RandomState(seed)
        self._specs: List[Any] = []

    def uniform(self, name: str, lo: float, hi: float) -> "RandomParamBuilder":
        self._specs.append(("uniform", name, float(lo), float(hi)))
        return self

    def log_uniform(self, name: str, lo: float, hi: float) -> "RandomParamBuilder":
        if lo <= 0 or hi <= 0:
            raise ValueError("log_uniform bounds must be positive")
        self._specs.append(("log_uniform", name, float(lo), float(hi)))
        return self

    def integers(self, name: str, lo: int, hi: int) -> "RandomParamBuilder":
        self._specs.append(("integers", name, int(lo), int(hi)))
        return self

    def choice(self, name: str, values: Sequence[Any]) -> "RandomParamBuilder":
        self._specs.append(("choice", name, list(values), None))
        return self

    def build(self, n: int) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for _ in range(n):
            point: Dict[str, Any] = {}
            for kind, name, a, b in self._specs:
                if kind == "uniform":
                    point[name] = float(self._rng.uniform(a, b))
                elif kind == "log_uniform":
                    point[name] = float(np.exp(
                        self._rng.uniform(np.log(a), np.log(b))))
                elif kind == "integers":
                    point[name] = int(self._rng.randint(a, b + 1))
                else:
                    point[name] = a[int(self._rng.randint(len(a)))]
            out.append(point)
        return out
