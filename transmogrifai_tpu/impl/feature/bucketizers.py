"""Bucketizers and calibrators.

TPU re-design of the reference bucketizing stages (reference:
core/.../impl/feature/NumericBucketizer.scala:303 — explicit split points →
one-hot bucket vector; DecisionTreeNumericBucketizer.scala:300 — supervised
buckets from a single-feature decision tree with minInfoGain;
DecisionTreeNumericMapBucketizer.scala:170; PercentileCalibrator.scala:131 —
rank into 0..buckets-1 percentile scores).

The decision-tree split search is a vectorized histogram scan: candidate
thresholds come from quantiles of the native streaming-histogram sketch, label
counts per bin accumulate in one numpy pass, and the best split per node
maximizes impurity gain — the same recursion Spark's single-feature
DecisionTreeClassifier performs, without per-row JVM tasks.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...stages.base import AllowLabelAsInput, Estimator, Transformer, UnaryTransformer
from ...table import Column, FeatureTable
from ...types import OPVector, Real, RealNN
from ...utils.streaming_histogram import StreamingHistogram
from ...vector_metadata import NULL_INDICATOR, VectorColumnMetadata
from .vectorizers import TransmogrifierDefaults, _VectorModelBase


def _bucket_block(vals: np.ndarray, mask: np.ndarray, splits: Sequence[float],
                  track_nulls: bool, track_invalid: bool) -> np.ndarray:
    """One-hot bucket membership. splits = [s0, s1, ..., sk] defines k buckets
    [s0,s1), [s1,s2), ..., [s_{k-1}, sk] (reference NumericBucketizer splits
    semantics, right-inclusive last bucket)."""
    n = vals.shape[0]
    k = len(splits) - 1
    width = k + (1 if track_invalid else 0) + (1 if track_nulls else 0)
    block = np.zeros((n, width), dtype=np.float32)
    idx = np.searchsorted(np.asarray(splits, dtype=np.float64), vals,
                          side="right") - 1
    idx = np.where((vals == splits[-1]), k - 1, idx)
    in_range = (idx >= 0) & (idx < k) & mask
    rows = np.arange(n)
    block[rows[in_range], idx[in_range]] = 1.0
    if track_invalid:
        invalid = mask & ~in_range
        block[invalid, k] = 1.0
    if track_nulls:
        block[~mask, width - 1] = 1.0
    return block


class NumericBucketizer(UnaryTransformer):
    """Real → OPVector: explicit-split one-hot buckets (reference
    NumericBucketizer.scala:303)."""

    output_type = OPVector

    def __init__(self, splits: Sequence[float],
                 bucket_labels: Optional[Sequence[str]] = None,
                 track_nulls: bool = TransmogrifierDefaults.TrackNulls,
                 track_invalid: bool = False, uid=None):
        super().__init__("numericBucketizer", transform_fn=None,
                         output_type=OPVector, input_type=Real, uid=uid)
        if len(splits) < 2 or list(splits) != sorted(splits):
            raise ValueError("splits must be ascending with at least 2 points")
        self.splits = [float(s) for s in splits]
        self.bucket_labels = (list(bucket_labels) if bucket_labels is not None
                              else [f"{a}-{b}" for a, b in
                                    zip(self.splits, self.splits[1:])])
        if len(self.bucket_labels) != len(self.splits) - 1:
            raise ValueError("need one label per bucket")
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid

    def transform_column(self, table: FeatureTable) -> Column:
        f = self.input_features[0]
        col = table[f.name]
        vals = np.asarray(col.values, dtype=np.float64).reshape(-1)
        block = _bucket_block(vals, col.valid_mask(), self.splits,
                              self.track_nulls, self.track_invalid)
        meta = [VectorColumnMetadata(f.name, f.type_name, f.name, lbl)
                for lbl in self.bucket_labels]
        if self.track_invalid:
            meta.append(VectorColumnMetadata(f.name, f.type_name, f.name,
                                             "OutOfBound"))
        if self.track_nulls:
            meta.append(VectorColumnMetadata(f.name, f.type_name, f.name,
                                             NULL_INDICATOR))
        from ...vector_metadata import VectorMetadata
        vm = VectorMetadata.of(self.get_output().name, meta)
        return Column(OPVector, block, None, {"vector_meta": vm})



# ---------------------------------------------------------------------------
# Supervised (decision-tree) bucketizer
# ---------------------------------------------------------------------------

def _entropy(counts: np.ndarray) -> float:
    tot = counts.sum()
    if tot == 0:
        return 0.0
    p = counts / tot
    with np.errstate(divide="ignore", invalid="ignore"):
        return float(-(np.where(p > 0, p * np.log2(p), 0.0)).sum())


def decision_tree_splits(x: np.ndarray, y: np.ndarray, max_depth: int,
                         min_info_gain: float, num_candidates: int = 64,
                         min_leaf: int = 10) -> List[float]:
    """Split points of a depth-limited single-feature decision tree.

    Candidate thresholds are streaming-histogram quantiles; each node's best
    threshold maximizes label-entropy gain over a vectorized cumulative-count
    scan (the analog of the reference delegating to Spark's
    DecisionTreeClassifier, DecisionTreeNumericBucketizer.scala:300)."""
    classes, y_idx = np.unique(y, return_inverse=True)
    k = classes.size
    if k < 2 or x.size < 2 * min_leaf:
        return []
    sketch = StreamingHistogram(max(num_candidates * 2, 64)).update(x)
    cands = np.unique(sketch.uniform(num_candidates))
    if cands.size == 0:
        return []

    out: List[float] = []

    def recurse(sel: np.ndarray, depth: int) -> None:
        if depth >= max_depth or sel.sum() < 2 * min_leaf:
            return
        xs, ys = x[sel], y_idx[sel]
        # counts[c, j]: label-c rows at/below candidate j (one pass via digitize)
        bin_idx = np.searchsorted(cands, xs, side="right")  # 0..len(cands)
        counts = np.zeros((k, cands.size + 1), dtype=np.float64)
        np.add.at(counts, (ys, bin_idx), 1.0)
        cum = counts.cumsum(axis=1)[:, :-1]          # ≤ candidate j
        total = counts.sum(axis=1)
        n_tot = total.sum()
        left_n = cum.sum(axis=0)
        right_n = n_tot - left_n
        ok = (left_n >= min_leaf) & (right_n >= min_leaf)
        if not ok.any():
            return
        parent = _entropy(total)

        def ent(c: np.ndarray, n: np.ndarray) -> np.ndarray:
            with np.errstate(divide="ignore", invalid="ignore"):
                p = np.where(n > 0, c / np.maximum(n, 1), 0.0)
                return -(np.where(p > 0, p * np.log2(p), 0.0)).sum(axis=0)

        gain = parent - (left_n / n_tot) * ent(cum, left_n) \
                      - (right_n / n_tot) * ent(total[:, None] - cum, right_n)
        gain = np.where(ok, gain, -np.inf)
        j = int(np.argmax(gain))
        if gain[j] < min_info_gain:
            return
        thr = float(cands[j])
        out.append(thr)
        recurse(sel & (x <= thr), depth + 1)
        recurse(sel & (x > thr), depth + 1)

    recurse(np.ones_like(x, dtype=bool), 0)
    return sorted(out)


class DecisionTreeNumericBucketizer(AllowLabelAsInput, Estimator):
    """(RealNN label, Real) → OPVector supervised buckets (reference
    DecisionTreeNumericBucketizer.scala — buckets only kept if the tree finds
    splits with gain ≥ minInfoGain; otherwise the output shrinks to just the
    null-indicator column)."""

    input_types = (RealNN, Real)
    output_type = OPVector

    def __init__(self, max_depth: int = 2, min_info_gain: float = 0.01,
                 track_nulls: bool = TransmogrifierDefaults.TrackNulls,
                 track_invalid: bool = False, uid=None):
        super().__init__("dtBucketizer", uid)
        self.max_depth = max_depth
        self.min_info_gain = min_info_gain
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid

    def fit(self, table: FeatureTable) -> Transformer:
        label_f, feat_f = self.input_features
        ycol, xcol = table[label_f.name], table[feat_f.name]
        x = np.asarray(xcol.values, dtype=np.float64).reshape(-1)
        y = np.asarray(ycol.values, dtype=np.float64).reshape(-1)
        m = xcol.valid_mask() & ycol.valid_mask()
        thresholds = decision_tree_splits(
            x[m], y[m], self.max_depth, self.min_info_gain)
        splits = ([-np.inf] + thresholds + [np.inf]) if thresholds else []
        model = DecisionTreeNumericBucketizerModel(
            splits=splits, track_nulls=self.track_nulls,
            track_invalid=self.track_invalid)
        model.summary_metadata = {"splits": thresholds,
                                  "bucketed": bool(thresholds)}
        return self._finalize_model(model)


class DecisionTreeNumericBucketizerModel(_VectorModelBase):
    def __init__(self, splits: List[float], track_nulls: bool,
                 track_invalid: bool, uid=None):
        super().__init__("dtBucketizer", uid)
        self.splits = splits
        self.track_nulls = track_nulls
        self.track_invalid = track_invalid

    def transform_column(self, table: FeatureTable) -> Column:
        _, feat_f = self.input_features
        col = table[feat_f.name]
        vals = np.asarray(col.values, dtype=np.float64).reshape(-1)
        m = col.valid_mask()
        meta: List[VectorColumnMetadata] = []
        if self.splits:
            block = _bucket_block(vals, m, self.splits, self.track_nulls,
                                  self.track_invalid)
            labels = [f"{a}-{b}" for a, b in zip(self.splits, self.splits[1:])]
            meta.extend([VectorColumnMetadata(
                feat_f.name, feat_f.type_name, feat_f.name, lbl)
                for lbl in labels])
            if self.track_invalid:
                meta.append(VectorColumnMetadata(
                    feat_f.name, feat_f.type_name, feat_f.name, "OutOfBound"))
            if self.track_nulls:
                meta.append(VectorColumnMetadata(
                    feat_f.name, feat_f.type_name, feat_f.name, NULL_INDICATOR))
        else:
            block = (~m).astype(np.float32)[:, None]
            meta.append(VectorColumnMetadata(
                feat_f.name, feat_f.type_name, feat_f.name, NULL_INDICATOR))
        return self._emit(block, meta)



class DecisionTreeNumericMapBucketizer(AllowLabelAsInput, Estimator):
    """(RealNN label, RealMap) → OPVector: a supervised bucketizer per map key
    (reference DecisionTreeNumericMapBucketizer.scala:170)."""

    output_type = OPVector

    def __init__(self, max_depth: int = 2, min_info_gain: float = 0.01,
                 track_nulls: bool = TransmogrifierDefaults.TrackNulls, uid=None):
        super().__init__("dtMapBucketizer", uid)
        self.max_depth = max_depth
        self.min_info_gain = min_info_gain
        self.track_nulls = track_nulls

    def fit(self, table: FeatureTable) -> Transformer:
        label_f, map_f = self.input_features
        ycol, col = table[label_f.name], table[map_f.name]
        y = np.asarray(ycol.values, dtype=np.float64).reshape(-1)
        valid = col.valid_mask()
        n = len(col)
        keys = sorted({str(k) for i in range(n) if valid[i] and col.values[i]
                       for k in col.values[i]})
        per_key: Dict[str, List[float]] = {}
        for key in keys:
            xs, ys = [], []
            for i in range(n):
                r = col.values[i] if valid[i] else None
                v = r.get(key) if r else None
                if v is not None and not (isinstance(v, float) and np.isnan(v)):
                    xs.append(float(v))
                    ys.append(y[i])
            thr = decision_tree_splits(
                np.asarray(xs), np.asarray(ys), self.max_depth,
                self.min_info_gain) if xs else []
            per_key[key] = ([-np.inf] + thr + [np.inf]) if thr else []
        model = DecisionTreeNumericMapBucketizerModel(
            keys=keys, splits=per_key, track_nulls=self.track_nulls)
        model.summary_metadata = {
            "splits": {k: [s for s in v if np.isfinite(s)]
                       for k, v in per_key.items()}}
        return self._finalize_model(model)


class DecisionTreeNumericMapBucketizerModel(_VectorModelBase):
    def __init__(self, keys: List[str], splits: Dict[str, List[float]],
                 track_nulls: bool, uid=None):
        super().__init__("dtMapBucketizer", uid)
        self.keys = keys
        self.splits = splits
        self.track_nulls = track_nulls

    def transform_column(self, table: FeatureTable) -> Column:
        _, map_f = self.input_features
        col = table[map_f.name]
        valid = col.valid_mask()
        n = len(col)
        blocks, meta = [], []
        for key in self.keys:
            vals = np.zeros(n, dtype=np.float64)
            m = np.zeros(n, dtype=bool)
            for i in range(n):
                r = col.values[i] if valid[i] else None
                v = r.get(key) if r else None
                if v is not None and not (isinstance(v, float) and np.isnan(v)):
                    vals[i] = float(v)
                    m[i] = True
            splits = self.splits.get(key, [])
            if splits:
                blocks.append(_bucket_block(vals, m, splits,
                                            self.track_nulls, False))
                labels = [f"{a}-{b}" for a, b in zip(splits, splits[1:])]
                meta.extend([VectorColumnMetadata(
                    map_f.name, map_f.type_name, key, lbl) for lbl in labels])
                if self.track_nulls:
                    meta.append(VectorColumnMetadata(
                        map_f.name, map_f.type_name, key, NULL_INDICATOR))
            else:
                blocks.append((~m).astype(np.float32)[:, None])
                meta.append(VectorColumnMetadata(
                    map_f.name, map_f.type_name, key, NULL_INDICATOR))
        mat = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), dtype=np.float32))
        return self._emit(mat, meta)


class PercentileCalibrator(Estimator):
    """Real → RealNN percentile score in [0, buckets-1] (reference
    PercentileCalibrator.scala:131 — QuantileDiscretizer-backed; here the
    quantile boundaries come from the native streaming-histogram sketch)."""

    input_types = (Real,)
    output_type = RealNN

    def __init__(self, buckets: int = 100, uid=None):
        super().__init__("percentileCalibrator", uid)
        self.buckets = buckets

    def fit(self, table: FeatureTable) -> Transformer:
        f = self.input_features[0]
        col = table[f.name]
        vals = np.asarray(col.values, dtype=np.float64).reshape(-1)
        m = col.valid_mask()
        sketch = StreamingHistogram(max(2 * self.buckets, 64)).update(vals[m])
        bounds = np.unique(sketch.uniform(self.buckets))
        model = PercentileCalibratorModel(
            boundaries=bounds.tolist(), buckets=self.buckets)
        model.summary_metadata = {"boundaries": bounds.tolist()}
        return self._finalize_model(model)


class PercentileCalibratorModel(Transformer):
    output_type = RealNN

    def __init__(self, boundaries: List[float], buckets: int, uid=None):
        super().__init__("percentileCalibrator", uid)
        self.boundaries = boundaries
        self.buckets = buckets

    def transform_column(self, table: FeatureTable) -> Column:
        f = self.input_features[0]
        col = table[f.name]
        vals = np.asarray(col.values, dtype=np.float64).reshape(-1)
        m = col.valid_mask()
        scaled = self._scale(vals)
        scaled[~m] = 0.0
        return Column(RealNN, scaled.astype(np.float32), None)

    def _scale(self, vals: np.ndarray) -> np.ndarray:
        if not self.boundaries:
            return np.zeros_like(vals)
        idx = np.searchsorted(np.asarray(self.boundaries), vals, side="right")
        # map bucket index onto 0..buckets-1 even when boundaries collapsed
        k = len(self.boundaries) + 1
        return np.floor(idx * (self.buckets - 1) / max(k - 1, 1)).astype(np.float64)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        v = row.get(self.input_features[0].name)
        if v is None:
            return 0.0
        return float(self._scale(np.array([float(v)]))[0])
