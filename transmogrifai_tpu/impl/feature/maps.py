"""Map-typed feature vectorizers: per-key expansion with provenance.

TPU re-design of the reference map vectorizer family (reference:
core/.../impl/feature/OPMapVectorizer.scala:468 — typed map → mean/mode-filled
reals + null indicators per key; TextMapPivotVectorizer.scala:145;
MultiPickListMapVectorizer.scala:122; SmartTextMapVectorizer.scala:296).
Map columns are host-side dict arrays; fit discovers the key space (optionally
white/black-listed), and transform emits one dense float32 block whose slots
carry ``grouping=key`` metadata so SanityChecker/ModelInsights can attribute
them back (reference OpVectorColumnMetadata.grouping).
"""
from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...features import Feature
from ...stages.base import Estimator, Transformer
from ...table import Column, FeatureTable
from ...types import OPVector
from ...vector_metadata import (
    NULL_INDICATOR, OTHER_INDICATOR, VectorColumnMetadata, VectorMetadata,
)
from .vectorizers import TransmogrifierDefaults, _VectorModelBase, tokenize_text


def _map_rows(col: Column) -> List[Optional[Dict[str, Any]]]:
    valid = col.valid_mask()
    return [col.values[i] if valid[i] and col.values[i] is not None else None
            for i in range(len(col))]


def _discover_keys(rows: Sequence[Optional[Dict[str, Any]]],
                   white: Sequence[str], black: Sequence[str]) -> List[str]:
    keys: set = set()
    for r in rows:
        if r:
            keys.update(str(k) for k in r)
    if white:
        keys &= set(white)
    keys -= set(black)
    return sorted(keys)


class MapVectorizer(Estimator):
    """Seq[RealMap/IntegralMap/BinaryMap/CurrencyMap/…] → OPVector.

    Numeric map values per key: mean-fill (or constant) + null indicator per
    key (reference OPMapVectorizer.scala — each typed subclass fills with
    mean/mode and tracks nulls per key)."""

    output_type = OPVector

    def __init__(self, fill_with_mean: bool = TransmogrifierDefaults.FillWithMean,
                 fill_value: float = TransmogrifierDefaults.FillValue,
                 track_nulls: bool = TransmogrifierDefaults.TrackNulls,
                 white_list_keys: Sequence[str] = (),
                 black_list_keys: Sequence[str] = (), uid=None):
        super().__init__("vecMap", uid)
        self.fill_with_mean = fill_with_mean
        self.fill_value = fill_value
        self.track_nulls = track_nulls
        self.white_list_keys = tuple(white_list_keys)
        self.black_list_keys = tuple(black_list_keys)

    def fit(self, table: FeatureTable) -> Transformer:
        all_keys: List[List[str]] = []
        fills: List[List[float]] = []
        for f in self.input_features:
            rows = _map_rows(table[f.name])
            keys = _discover_keys(rows, self.white_list_keys, self.black_list_keys)
            kf: List[float] = []
            for k in keys:
                if self.fill_with_mean:
                    vals = [float(r[k]) for r in rows
                            if r and k in r and r[k] is not None
                            and not (isinstance(r[k], float) and np.isnan(r[k]))]
                    kf.append(float(np.mean(vals)) if vals else self.fill_value)
                else:
                    kf.append(self.fill_value)
            all_keys.append(keys)
            fills.append(kf)
        model = MapVectorizerModel(keys=all_keys, fills=fills,
                                   track_nulls=self.track_nulls)
        return self._finalize_model(model)


class MapVectorizerModel(_VectorModelBase):
    def __init__(self, keys: List[List[str]], fills: List[List[float]],
                 track_nulls: bool, uid=None):
        super().__init__("vecMap", uid)
        self.keys = keys
        self.fills = fills
        self.track_nulls = track_nulls

    def transform_column(self, table: FeatureTable) -> Column:
        n = table.num_rows
        blocks: List[np.ndarray] = []
        meta: List[VectorColumnMetadata] = []
        for f, keys, fills in zip(self.input_features, self.keys, self.fills):
            rows = _map_rows(table[f.name])
            k = len(keys)
            width = k * (2 if self.track_nulls else 1)
            block = np.zeros((n, width), dtype=np.float32)
            for j, (key, fill) in enumerate(zip(keys, fills)):
                vcol = j * (2 if self.track_nulls else 1)
                for i, r in enumerate(rows):
                    v = r.get(key) if r else None
                    missing = v is None or (isinstance(v, float) and np.isnan(v))
                    if missing:
                        block[i, vcol] = fill
                        if self.track_nulls:
                            block[i, vcol + 1] = 1.0
                    else:
                        block[i, vcol] = float(v)
                meta.append(VectorColumnMetadata(f.name, f.type_name, key, None))
                if self.track_nulls:
                    meta.append(VectorColumnMetadata(
                        f.name, f.type_name, key, NULL_INDICATOR))
            blocks.append(block)
        mat = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), dtype=np.float32))
        return self._emit(mat, meta)


class TextMapPivotVectorizer(Estimator):
    """Seq[TextMap] → OPVector: per-key top-K one-hot pivot with OTHER + null
    (reference TextMapPivotVectorizer.scala:145)."""

    output_type = OPVector

    def __init__(self, top_k: int = TransmogrifierDefaults.TopK,
                 min_support: int = TransmogrifierDefaults.MinSupport,
                 track_nulls: bool = TransmogrifierDefaults.TrackNulls,
                 white_list_keys: Sequence[str] = (),
                 black_list_keys: Sequence[str] = (), uid=None):
        super().__init__("pivotTextMap", uid)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls
        self.white_list_keys = tuple(white_list_keys)
        self.black_list_keys = tuple(black_list_keys)

    def fit(self, table: FeatureTable) -> Transformer:
        vocabs: List[Dict[str, List[str]]] = []
        for f in self.input_features:
            rows = _map_rows(table[f.name])
            keys = _discover_keys(rows, self.white_list_keys, self.black_list_keys)
            per_key: Dict[str, List[str]] = {}
            for k in keys:
                cnt = Counter()
                for r in rows:
                    if r and k in r and r[k] is not None:
                        if isinstance(r[k], (list, tuple, set)):
                            cnt.update(str(v) for v in r[k])
                        else:
                            cnt[str(r[k])] += 1
                top = [v for v, c in cnt.most_common() if c >= self.min_support]
                per_key[k] = sorted(top, key=lambda v: (-cnt[v], v))[: self.top_k]
            vocabs.append(per_key)
        model = TextMapPivotVectorizerModel(vocabs=vocabs,
                                            track_nulls=self.track_nulls)
        return self._finalize_model(model)


class TextMapPivotVectorizerModel(_VectorModelBase):
    def __init__(self, vocabs: List[Dict[str, List[str]]], track_nulls: bool,
                 uid=None):
        super().__init__("pivotTextMap", uid)
        self.vocabs = vocabs
        self.track_nulls = track_nulls

    def transform_column(self, table: FeatureTable) -> Column:
        n = table.num_rows
        blocks: List[np.ndarray] = []
        meta: List[VectorColumnMetadata] = []
        for f, per_key in zip(self.input_features, self.vocabs):
            rows = _map_rows(table[f.name])
            for key in sorted(per_key):
                vocab = per_key[key]
                k = len(vocab)
                width = k + 1 + (1 if self.track_nulls else 0)
                block = np.zeros((n, width), dtype=np.float32)
                index = {v: i for i, v in enumerate(vocab)}
                for i, r in enumerate(rows):
                    v = r.get(key) if r else None
                    if v is None:
                        if self.track_nulls:
                            block[i, k + 1] = 1.0
                        continue
                    items = v if isinstance(v, (list, tuple, set)) else [v]
                    for item in items:
                        j = index.get(str(item))
                        if j is None:
                            block[i, k] = 1.0
                        else:
                            block[i, j] = 1.0
                blocks.append(block)
                meta.extend([VectorColumnMetadata(f.name, f.type_name, key, v)
                             for v in vocab])
                meta.append(VectorColumnMetadata(
                    f.name, f.type_name, key, OTHER_INDICATOR))
                if self.track_nulls:
                    meta.append(VectorColumnMetadata(
                        f.name, f.type_name, key, NULL_INDICATOR))
        mat = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), dtype=np.float32))
        return self._emit(mat, meta)


#: MultiPickListMap pivots identically — set-valued entries hit the
#: isinstance(list/tuple/set) path above (reference MultiPickListMapVectorizer)
MultiPickListMapVectorizer = TextMapPivotVectorizer


class SmartTextMapVectorizer(Estimator):
    """Seq[TextMap] → OPVector: per-key cardinality decides pivot vs hashing
    (reference SmartTextMapVectorizer.scala:296)."""

    output_type = OPVector

    def __init__(self, max_cardinality: int = TransmogrifierDefaults.MaxCardinality,
                 top_k: int = TransmogrifierDefaults.TopK,
                 min_support: int = TransmogrifierDefaults.MinSupport,
                 num_hashes: int = TransmogrifierDefaults.NumHashes,
                 track_nulls: bool = TransmogrifierDefaults.TrackNulls, uid=None):
        super().__init__("smartTxtMapVec", uid)
        self.max_cardinality = max_cardinality
        self.top_k = top_k
        self.min_support = min_support
        self.num_hashes = num_hashes
        self.track_nulls = track_nulls

    def fit(self, table: FeatureTable) -> Transformer:
        plans: List[Dict[str, Dict[str, Any]]] = []
        for f in self.input_features:
            rows = _map_rows(table[f.name])
            keys = _discover_keys(rows, (), ())
            plan: Dict[str, Dict[str, Any]] = {}
            for k in keys:
                cnt = Counter(str(r[k]) for r in rows
                              if r and k in r and r[k] is not None)
                if len(cnt) <= self.max_cardinality:
                    top = [v for v, c in cnt.most_common() if c >= self.min_support]
                    top = sorted(top, key=lambda v: (-cnt[v], v))[: self.top_k]
                    plan[k] = {"kind": "pivot", "vocab": top}
                else:
                    plan[k] = {"kind": "hash"}
            plans.append(plan)
        model = SmartTextMapVectorizerModel(
            plans=plans, num_hashes=self.num_hashes, track_nulls=self.track_nulls)
        return self._finalize_model(model)


class SmartTextMapVectorizerModel(_VectorModelBase):
    def __init__(self, plans: List[Dict[str, Dict[str, Any]]], num_hashes: int,
                 track_nulls: bool, uid=None):
        super().__init__("smartTxtMapVec", uid)
        self.plans = plans
        self.num_hashes = num_hashes
        self.track_nulls = track_nulls

    def transform_column(self, table: FeatureTable) -> Column:
        n = table.num_rows
        blocks: List[np.ndarray] = []
        meta: List[VectorColumnMetadata] = []
        for f, plan in zip(self.input_features, self.plans):
            rows = _map_rows(table[f.name])
            for key in sorted(plan):
                spec = plan[key]
                vals = [r.get(key) if r else None for r in rows]
                if spec["kind"] == "pivot":
                    vocab = spec["vocab"]
                    k = len(vocab)
                    block = np.zeros((n, k + 1), dtype=np.float32)
                    index = {v: i for i, v in enumerate(vocab)}
                    for i, v in enumerate(vals):
                        if v is None:
                            continue
                        j = index.get(str(v), -1)
                        block[i, j if j >= 0 else k] = 1.0
                    blocks.append(block)
                    meta.extend([VectorColumnMetadata(f.name, f.type_name, key, v)
                                 for v in vocab])
                    meta.append(VectorColumnMetadata(
                        f.name, f.type_name, key, OTHER_INDICATOR))
                else:
                    from .vectorizers import tokenize_hash_texts
                    blocks.append(tokenize_hash_texts(
                        [str(v) if v is not None else None for v in vals],
                        self.num_hashes))
                    meta.extend([VectorColumnMetadata(
                        f.name, f.type_name, key, None,
                        descriptor_value=f"hash_{j}")
                        for j in range(self.num_hashes)])
                if self.track_nulls:
                    nul = np.array([1.0 if v is None else 0.0 for v in vals],
                                   dtype=np.float32)
                    blocks.append(nul[:, None])
                    meta.append(VectorColumnMetadata(
                        f.name, f.type_name, key, NULL_INDICATOR))
        mat = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), dtype=np.float32))
        return self._emit(mat, meta)


class TextMapNullEstimator(Estimator):
    """Seq[TextMap] → OPVector of per-key null indicators (reference
    TextMapNullEstimator.scala:108). An estimator because the key space must
    be discovered from the training data; the fitted model emits one
    null-indicator slot per (feature, key)."""

    output_type = OPVector

    def __init__(self, white_list_keys: Sequence[str] = (),
                 black_list_keys: Sequence[str] = (), uid=None):
        super().__init__("textMapNull", uid)
        self.white_list_keys = tuple(white_list_keys)
        self.black_list_keys = tuple(black_list_keys)

    def fit(self, table: FeatureTable) -> Transformer:
        keys = [
            _discover_keys(_map_rows(table[f.name]),
                           self.white_list_keys, self.black_list_keys)
            for f in self.input_features
        ]
        return self._finalize_model(TextMapNullModel(keys=keys))


class TextMapNullModel(_VectorModelBase):
    def __init__(self, keys: List[List[str]], uid=None):
        super().__init__("textMapNull", uid)
        self.keys = keys

    def transform_column(self, table: FeatureTable) -> Column:
        n = table.num_rows
        blocks: List[np.ndarray] = []
        meta: List[VectorColumnMetadata] = []
        for f, keys in zip(self.input_features, self.keys):
            rows = _map_rows(table[f.name])
            block = np.zeros((n, len(keys)), dtype=np.float32)
            for j, key in enumerate(keys):
                for i, r in enumerate(rows):
                    v = r.get(key) if r else None
                    if v is None or str(v) == "":
                        block[i, j] = 1.0
                meta.append(VectorColumnMetadata(
                    f.name, f.type_name, key, NULL_INDICATOR))
            blocks.append(block)
        mat = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), dtype=np.float32))
        return self._emit(mat, meta)
