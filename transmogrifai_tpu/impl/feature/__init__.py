from .vectorizers import (
    TransmogrifierDefaults, RealVectorizer, IntegralVectorizer,
    BinaryVectorizer, RealNNVectorizer, OneHotVectorizer, TextTokenizer,
    HashingVectorizer, SmartTextVectorizer, VectorsCombiner,
)
from .transmogrifier import transmogrify
