"""Scaling stages with metadata for descaling predictions.

TPU re-design of the reference scaling family (reference:
core/.../impl/feature/ScalerTransformer.scala:186 — linear/log scaling whose
args are stored in column metadata; DescalerTransformer.scala:112 — reads that
metadata off another feature to invert; OpScalarStandardScaler.scala:109 —
z-score fit; FillMissingWithMean.scala:76).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...stages.base import (
    BinaryTransformer, Estimator, Transformer, UnaryTransformer,
)
from ...table import Column, FeatureTable
from ...types import Real, RealNN

#: metadata key carrying the scaling args (reference ScalingType + args)
SCALER_META = "scaler"


class ScalerTransformer(UnaryTransformer):
    """Real → Real scaled; scaling args ride column metadata so a
    DescalerTransformer can invert them (reference ScalerTransformer.scala)."""

    def __init__(self, scaling_type: str = "linear", slope: float = 1.0,
                 intercept: float = 0.0, uid=None):
        if scaling_type not in ("linear", "log"):
            raise ValueError("scaling_type must be 'linear' or 'log'")
        super().__init__(f"scale_{scaling_type}", transform_fn=None,
                         output_type=Real, input_type=Real, uid=uid)
        self.scaling_type = scaling_type
        self.slope = slope
        self.intercept = intercept

    def _apply(self, vals: np.ndarray) -> np.ndarray:
        if self.scaling_type == "linear":
            return self.slope * vals + self.intercept
        return np.log(vals)

    def transform_column(self, table: FeatureTable) -> Column:
        col = table[self.input_features[0].name]
        vals = np.asarray(col.values, dtype=np.float64).reshape(-1)
        with np.errstate(divide="ignore", invalid="ignore"):
            out = self._apply(vals)
        meta = {SCALER_META: {"type": self.scaling_type, "slope": self.slope,
                              "intercept": self.intercept}}
        return Column(Real, out.astype(np.float32),
                      None if col.mask is None else np.asarray(col.mask), meta)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        v = row.get(self.input_features[0].name)
        if v is None:
            return None
        return float(self._apply(np.array([float(v)]))[0])


class DescalerTransformer(BinaryTransformer):
    """(scaled value, scaler-carrying feature) → descaled value (reference
    DescalerTransformer.scala — reads scaling metadata from input 2)."""

    def __init__(self, uid=None):
        super().__init__("descale", transform_fn=None, output_type=Real,
                         input_types=(Real, Real), uid=uid)
        self._scaler_args: Optional[Dict[str, Any]] = None

    def _invert(self, vals: np.ndarray, args: Dict[str, Any]) -> np.ndarray:
        if args["type"] == "linear":
            slope = args["slope"]
            if slope == 0:
                raise ValueError("cannot descale: slope is 0")
            return (vals - args["intercept"]) / slope
        return np.exp(vals)

    def transform_column(self, table: FeatureTable) -> Column:
        val_f, scaled_f = self.input_features
        col = table[val_f.name]
        args = table[scaled_f.name].metadata.get(SCALER_META)
        if args is None:
            raise ValueError(
                f"feature '{scaled_f.name}' carries no scaler metadata")
        self._scaler_args = dict(args)
        vals = np.asarray(col.values, dtype=np.float64).reshape(-1)
        out = self._invert(vals, args)
        return Column(Real, out.astype(np.float32),
                      None if col.mask is None else np.asarray(col.mask))

    def transform_row(self, row: Dict[str, Any]) -> Any:
        v = row.get(self.input_features[0].name)
        if v is None or self._scaler_args is None:
            return None
        return float(self._invert(np.array([float(v)]), self._scaler_args)[0])


class OpScalarStandardScaler(Estimator):
    """RealNN → RealNN z-score (reference OpScalarStandardScaler.scala)."""

    input_types = (RealNN,)
    output_type = RealNN

    def __init__(self, with_mean: bool = True, with_std: bool = True, uid=None):
        super().__init__("stdScaler", uid)
        self.with_mean = with_mean
        self.with_std = with_std

    def fit(self, table: FeatureTable) -> Transformer:
        col = table[self.input_features[0].name]
        vals = np.asarray(col.values, dtype=np.float64).reshape(-1)
        mean = float(vals.mean()) if self.with_mean else 0.0
        std = float(vals.std()) if self.with_std else 1.0
        model = OpScalarStandardScalerModel(
            mean=mean, std=std if std > 0 else 1.0)
        model.summary_metadata = {"mean": mean, "std": std}
        return self._finalize_model(model)


class OpScalarStandardScalerModel(Transformer):
    output_type = RealNN

    def __init__(self, mean: float, std: float, uid=None):
        super().__init__("stdScaler", uid)
        self.mean = mean
        self.std = std

    def transform_column(self, table: FeatureTable) -> Column:
        col = table[self.input_features[0].name]
        vals = np.asarray(col.values, dtype=np.float32).reshape(-1)
        out = (vals - np.float32(self.mean)) / np.float32(self.std)
        return Column(RealNN, out, None)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        v = row.get(self.input_features[0].name)
        return (float(v) - self.mean) / self.std if v is not None else None


class FillMissingWithMean(Estimator):
    """Real → RealNN mean-filled (reference FillMissingWithMean.scala)."""

    input_types = (Real,)
    output_type = RealNN

    def __init__(self, default_value: float = 0.0, uid=None):
        super().__init__("fillWithMean", uid)
        self.default_value = default_value

    def fit(self, table: FeatureTable) -> Transformer:
        col = table[self.input_features[0].name]
        vals = np.asarray(col.values, dtype=np.float64).reshape(-1)
        m = col.valid_mask()
        mean = float(vals[m].mean()) if m.any() else self.default_value
        model = FillMissingWithMeanModel(mean=mean)
        return self._finalize_model(model)


class FillMissingWithMeanModel(Transformer):
    output_type = RealNN

    def __init__(self, mean: float, uid=None):
        super().__init__("fillWithMean", uid)
        self.mean = mean

    def transform_column(self, table: FeatureTable) -> Column:
        col = table[self.input_features[0].name]
        vals = np.asarray(col.values, dtype=np.float32).reshape(-1)
        out = np.where(col.valid_mask(), vals, np.float32(self.mean))
        return Column(RealNN, out, None)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        v = row.get(self.input_features[0].name)
        return float(v) if v is not None else self.mean
