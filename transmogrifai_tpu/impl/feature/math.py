"""Row-level math and miscellaneous transformers.

TPU re-design of the reference math/misc stages (reference:
core/.../impl/feature/MathTransformers (unary+binary arithmetic, 393 LoC),
AliasTransformer.scala:63, SubstringTransformer.scala:75,
ToOccurTransformer.scala:67, FilterMap.scala:55, TextLenTransformer.scala:69,
TextListNullTransformer.scala:69, DropIndicesByTransformer.scala:79,
JaccardSimilarity.scala:46, NGramSimilarity.scala:100). Numeric transformers
run columnar over device-eligible arrays; string/map stages stay host-side.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Type

import numpy as np

from ...stages.base import (
    BinaryTransformer, SequenceTransformer, Transformer, UnaryTransformer,
)
from ...table import Column, FeatureTable
from ...types import (
    Binary, FeatureType, Integral, MultiPickList, OPMap, OPVector, Real,
    RealNN, Text, TextList,
)

# ---------------------------------------------------------------------------
# Numeric math (columnar over masked float arrays)
# ---------------------------------------------------------------------------


class _NumericUnary(UnaryTransformer):
    """Real → Real elementwise with validity-mask propagation."""

    def __init__(self, name: str, np_fn: Callable[[np.ndarray], np.ndarray],
                 uid=None):
        super().__init__(name, transform_fn=None, output_type=Real,
                         input_type=Real, uid=uid)
        self.np_fn = np_fn

    def transform_column(self, table: FeatureTable) -> Column:
        col = table[self.input_features[0].name]
        vals = np.asarray(col.values, dtype=np.float64).reshape(-1)
        with np.errstate(all="ignore"):
            out = self.np_fn(vals).astype(np.float32)
        mask = col.valid_mask() & np.isfinite(out)
        out = np.where(mask, out, 0.0).astype(np.float32)
        return Column(Real, out, mask)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        v = row.get(self.input_features[0].name)
        if v is None:
            return None
        with np.errstate(all="ignore"):
            out = float(self.np_fn(np.array([float(v)]))[0])
        return out if np.isfinite(out) else None


def AbsoluteValue(uid=None):   # reference RichNumericFeature.abs
    return _NumericUnary("abs", np.abs, uid=uid)


def Ceil(uid=None):
    return _NumericUnary("ceil", np.ceil, uid=uid)


def Floor(uid=None):
    return _NumericUnary("floor", np.floor, uid=uid)


def RoundTransformer(uid=None):
    return _NumericUnary("round", np.round, uid=uid)


def Exp(uid=None):
    return _NumericUnary("exp", np.exp, uid=uid)


def Sqrt(uid=None):
    return _NumericUnary("sqrt", np.sqrt, uid=uid)


def Log(base: float = np.e, uid=None):
    return _NumericUnary("log", lambda v: np.log(v) / np.log(base), uid=uid)


def Power(p: float, uid=None):
    return _NumericUnary("power", lambda v: np.power(v, p), uid=uid)


def SquareRoot(uid=None):
    return Sqrt(uid=uid)


class ScalarOp(UnaryTransformer):
    """Real (op) scalar → Real (reference RichNumericFeature +,-,*,/ scalar)."""

    _OPS = {"+": np.add, "-": np.subtract, "*": np.multiply, "/": np.divide}

    def __init__(self, op: str, scalar: float, uid=None):
        super().__init__(f"scalar{op}", transform_fn=None, output_type=Real,
                         input_type=Real, uid=uid)
        self.op = op
        self.scalar = float(scalar)

    def transform_column(self, table: FeatureTable) -> Column:
        col = table[self.input_features[0].name]
        vals = np.asarray(col.values, dtype=np.float64).reshape(-1)
        with np.errstate(all="ignore"):
            out = self._OPS[self.op](vals, self.scalar).astype(np.float32)
        mask = col.valid_mask() & np.isfinite(out)
        return Column(Real, np.where(mask, out, 0.0).astype(np.float32), mask)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        v = row.get(self.input_features[0].name)
        if v is None:
            return None
        with np.errstate(all="ignore"):
            out = float(self._OPS[self.op](float(v), self.scalar))
        return out if np.isfinite(out) else None


class BinaryMathOp(BinaryTransformer):
    """(Real, Real) → Real elementwise; missing propagates, div-by-0 → missing
    (reference MathTransformers binary ops semantics)."""

    _OPS = {"+": np.add, "-": np.subtract, "*": np.multiply, "/": np.divide}

    def __init__(self, op: str, uid=None):
        if op not in self._OPS:
            raise ValueError(f"unknown op {op}")
        super().__init__(f"binop{op}", transform_fn=None, output_type=Real,
                         input_types=(Real, Real), uid=uid)
        self.op = op

    def transform_column(self, table: FeatureTable) -> Column:
        a = table[self.input_features[0].name]
        b = table[self.input_features[1].name]
        va = np.asarray(a.values, dtype=np.float64).reshape(-1)
        vb = np.asarray(b.values, dtype=np.float64).reshape(-1)
        with np.errstate(all="ignore"):
            out = self._OPS[self.op](va, vb).astype(np.float32)
        mask = a.valid_mask() & b.valid_mask() & np.isfinite(out)
        return Column(Real, np.where(mask, out, 0.0).astype(np.float32), mask)

    def transform_row(self, row: Dict[str, Any]) -> Any:
        a = row.get(self.input_features[0].name)
        b = row.get(self.input_features[1].name)
        if a is None or b is None:
            return None
        with np.errstate(all="ignore"):
            out = float(self._OPS[self.op](float(a), float(b)))
        return out if np.isfinite(out) else None


# ---------------------------------------------------------------------------
# Misc transformers
# ---------------------------------------------------------------------------

class AliasTransformer(UnaryTransformer):
    """Identity with a new name (reference AliasTransformer.scala)."""

    def __init__(self, name: str, uid=None):
        super().__init__("alias", transform_fn=lambda v: v,
                         output_type=Real, uid=uid)
        self.alias = name

    def set_input(self, *features):
        out = super().set_input(*features)
        self.output_type = features[0].feature_type
        return out

    def output_name(self) -> str:
        return self.alias

    def transform_column(self, table: FeatureTable) -> Column:
        return table[self.input_features[0].name]


class SubstringTransformer(BinaryTransformer):
    """(Text, Text) → Binary: is input2 a substring of input1 (reference
    SubstringTransformer.scala)."""

    def __init__(self, uid=None):
        super().__init__(
            "substring",
            transform_fn=lambda a, b: (None if a is None or b is None
                                       else str(b).lower() in str(a).lower()),
            output_type=Binary, input_types=(Text, Text), uid=uid)


class ToOccurTransformer(UnaryTransformer):
    """Any → RealNN 1.0/0.0 occurrence flag (reference ToOccurTransformer.scala
    — default: non-empty numeric>0 / non-empty text / true → 1.0)."""

    def __init__(self, matches: Optional[Callable[[Any], bool]] = None, uid=None):
        def default_match(v: Any) -> bool:
            if v is None:
                return False
            if isinstance(v, bool):
                return v
            if isinstance(v, (int, float)):
                return float(v) > 0
            return bool(v)
        fn = matches or default_match
        super().__init__("toOccur",
                         transform_fn=lambda v: 1.0 if fn(v) else 0.0,
                         output_type=RealNN, uid=uid)


class FilterMap(UnaryTransformer):
    """OPMap → OPMap white/black-list filter (reference FilterMap.scala)."""

    def __init__(self, white_list_keys: Sequence[str] = (),
                 black_list_keys: Sequence[str] = (), uid=None):
        white = set(white_list_keys)
        black = set(black_list_keys)

        def fn(v):
            if v is None:
                return None
            out = {k: x for k, x in v.items()
                   if (not white or str(k) in white) and str(k) not in black}
            return out or None

        super().__init__("filterMap", transform_fn=fn, output_type=OPMap, uid=uid)
        self.white_list_keys = tuple(white_list_keys)
        self.black_list_keys = tuple(black_list_keys)

    def set_input(self, *features):
        out = super().set_input(*features)
        self.output_type = features[0].feature_type
        return out


class TextLenTransformer(UnaryTransformer):
    """Text → Integral length in characters (reference TextLenTransformer)."""

    def __init__(self, uid=None):
        super().__init__("textLen",
                         transform_fn=lambda v: 0 if v is None else len(str(v)),
                         output_type=Integral, input_type=Text, uid=uid)


class TextListNullTransformer(SequenceTransformer):
    """Seq[TextList] → OPVector of null indicators (reference
    TextListNullTransformer.scala)."""

    output_type = OPVector

    def __init__(self, uid=None):
        super().__init__("textListNull", transform_fn=None,
                         output_type=OPVector, uid=uid)

    def transform_column(self, table: FeatureTable) -> Column:
        from ...vector_metadata import (
            NULL_INDICATOR, VectorColumnMetadata, VectorMetadata,
        )
        blocks, meta = [], []
        for f in self.input_features:
            col = table[f.name]
            m = col.valid_mask()
            blocks.append((~m).astype(np.float32))
            meta.append(VectorColumnMetadata(f.name, f.type_name, f.name,
                                             NULL_INDICATOR))
        vm = VectorMetadata.of(self.get_output().name, meta)
        return Column(OPVector, np.stack(blocks, axis=1), None,
                      {"vector_meta": vm})

    def transform_row(self, row: Dict[str, Any]) -> Any:
        return [0.0 if row.get(f.name) else 1.0 for f in self.input_features]


class DropIndicesByTransformer(UnaryTransformer):
    """OPVector → OPVector dropping slots whose metadata matches a predicate
    (reference DropIndicesByTransformer.scala — e.g. drop null indicators)."""

    def __init__(self, predicate: Callable[[Any], bool], uid=None):
        super().__init__("dropIndicesBy", transform_fn=None,
                         output_type=OPVector, input_type=OPVector, uid=uid)
        self.predicate = predicate

    def transform_column(self, table: FeatureTable) -> Column:
        col = table[self.input_features[0].name]
        vm = col.metadata.get("vector_meta")
        if vm is None:
            raise ValueError("input vector carries no metadata")
        keep = [i for i, c in enumerate(vm.columns) if not self.predicate(c)]
        mat = np.asarray(col.values, dtype=np.float32)[:, keep]
        new_vm = vm.select(keep)
        return Column(OPVector, mat, None, {"vector_meta": new_vm})

    def transform_row(self, row: Dict[str, Any]) -> Any:
        raise ValueError(
            "DropIndicesByTransformer needs the vector metadata attached to "
            "columnar inputs; score via the batch/micro-batch path")


def jaccard_similarity(a: Optional[Sequence[str]], b: Optional[Sequence[str]]
                       ) -> Optional[float]:
    """|A∩B| / |A∪B|; both empty → 1.0 (reference JaccardSim.scala)."""
    sa = set(a or ())
    sb = set(b or ())
    if not sa and not sb:
        return 1.0
    union = sa | sb
    return len(sa & sb) / len(union)


class JaccardSimilarity(BinaryTransformer):
    """(MultiPickList, MultiPickList) → RealNN (reference
    JaccardSimilarity.scala)."""

    def __init__(self, uid=None):
        super().__init__("jaccardSim", transform_fn=jaccard_similarity,
                         output_type=RealNN, uid=uid)


def _ngrams(s: str, n: int) -> set:
    s = f" {s.lower()} "
    return {s[i:i + n] for i in range(max(len(s) - n + 1, 1))}


class NGramSimilarity(BinaryTransformer):
    """(Text, Text) → RealNN character n-gram Jaccard similarity (reference
    NGramSimilarity.scala — Lucene NGramDistance approximated by n-gram
    Jaccard; empty/missing pairs → 0)."""

    def __init__(self, n: int = 3, uid=None):
        def fn(a, b):
            if not a or not b:
                return 0.0
            ga, gb = _ngrams(str(a), n), _ngrams(str(b), n)
            if not ga or not gb:
                return 0.0
            return len(ga & gb) / len(ga | gb)
        super().__init__("ngramSim", transform_fn=fn, output_type=RealNN,
                         input_types=(None, None), uid=uid)
        self.n = n


# ---------------------------------------------------------------------------
# Collection-lifted transformers (reference OPCollectionTransformer.scala:209)
# ---------------------------------------------------------------------------


class OPCollectionTransformer(UnaryTransformer):
    """Lift a scalar value function over the elements of a collection feature
    (reference OPCollectionTransformer.scala — OPList/OPSet/OPMapTransformer
    wrap a unary stage so it applies per element). ``element_fn`` runs on each
    list element / set member / map value; empty or null collections pass
    through as empty."""

    def __init__(self, element_fn: Callable[[Any], Any],
                 output_type: Type[FeatureType],
                 input_type: Optional[Type[FeatureType]] = None,
                 operation_name: str = "collectionApply", uid=None):
        super().__init__(operation_name, transform_fn=self._apply,
                         output_type=output_type, input_type=input_type,
                         uid=uid)
        self.element_fn = element_fn

    def _apply(self, v):
        if v is None:
            return None
        if isinstance(v, dict):
            return {k: self.element_fn(x) for k, x in v.items()}
        if isinstance(v, (set, frozenset)):
            return {self.element_fn(x) for x in v}
        if isinstance(v, (list, tuple, np.ndarray)):
            return [self.element_fn(x) for x in v]
        return self.element_fn(v)


class OPListTransformer(OPCollectionTransformer):
    """TextList/DateList element-wise map (reference OPListTransformer)."""

    def __init__(self, element_fn, output_type=TextList, input_type=TextList,
                 uid=None):
        super().__init__(element_fn, output_type, input_type,
                         operation_name="listApply", uid=uid)


class OPSetTransformer(OPCollectionTransformer):
    """MultiPickList element-wise map (reference OPSetTransformer)."""

    def __init__(self, element_fn, output_type=MultiPickList,
                 input_type=MultiPickList, uid=None):
        super().__init__(element_fn, output_type, input_type,
                         operation_name="setApply", uid=uid)


class OPMapTransformer(OPCollectionTransformer):
    """Map value-wise map, keys preserved (reference OPMapTransformer)."""

    def __init__(self, element_fn, output_type, input_type=None, uid=None):
        super().__init__(element_fn, output_type, input_type,
                         operation_name="mapApply", uid=uid)
