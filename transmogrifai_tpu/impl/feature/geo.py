"""Geolocation vectorizers.

TPU re-design of the reference geolocation stages (reference:
core/.../impl/feature/GeolocationVectorizer.scala:156,
GeolocationMapVectorizer.scala:129): a Geolocation value is a
(lat, lon, accuracy) triple (features/.../types/Geolocation.scala:206); fit
computes the **geographic midpoint** (3-D unit-vector average) of non-missing
rows as the fill value; transform emits the triple + null indicator.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...stages.base import Estimator, Transformer
from ...table import Column, FeatureTable
from ...types import OPVector
from ...vector_metadata import NULL_INDICATOR, VectorColumnMetadata
from .vectorizers import TransmogrifierDefaults, _VectorModelBase

_GEO_NAMES = ("lat", "lon", "accuracy")


def geographic_midpoint(latlon: np.ndarray) -> Tuple[float, float]:
    """Mean point on the sphere: average 3-D unit vectors then re-project
    (reference Geolocation.scala GeolocationExtensions midpoint logic)."""
    lat = np.radians(latlon[:, 0])
    lon = np.radians(latlon[:, 1])
    x = np.cos(lat) * np.cos(lon)
    y = np.cos(lat) * np.sin(lon)
    z = np.sin(lat)
    xm, ym, zm = x.mean(), y.mean(), z.mean()
    hyp = np.hypot(xm, ym)
    if hyp < 1e-12 and abs(zm) < 1e-12:
        return 0.0, 0.0
    return float(np.degrees(np.arctan2(zm, hyp))), float(np.degrees(np.arctan2(ym, xm)))


def _geo_rows(col: Column) -> List[Optional[Sequence[float]]]:
    valid = col.valid_mask()
    out: List[Optional[Sequence[float]]] = []
    for i in range(len(col)):
        v = col.values[i] if valid[i] else None
        out.append(list(v) if v is not None and len(v) >= 2 else None)
    return out


class GeolocationVectorizer(Estimator):
    """Seq[Geolocation] → OPVector: midpoint-fill + null indicator."""

    output_type = OPVector

    def __init__(self, fill_with_mean: bool = True,
                 track_nulls: bool = TransmogrifierDefaults.TrackNulls, uid=None):
        super().__init__("vecGeo", uid)
        self.fill_with_mean = fill_with_mean
        self.track_nulls = track_nulls

    def fit(self, table: FeatureTable) -> Transformer:
        fills: List[List[float]] = []
        for f in self.input_features:
            rows = [r for r in _geo_rows(table[f.name]) if r is not None]
            if self.fill_with_mean and rows:
                pts = np.array([[r[0], r[1]] for r in rows], dtype=np.float64)
                lat, lon = geographic_midpoint(pts)
                acc = float(np.mean([r[2] if len(r) > 2 else 0.0 for r in rows]))
                fills.append([lat, lon, acc])
            else:
                fills.append([0.0, 0.0, 0.0])
        model = GeolocationVectorizerModel(fills=fills,
                                           track_nulls=self.track_nulls)
        return self._finalize_model(model)


class GeolocationVectorizerModel(_VectorModelBase):
    def __init__(self, fills: List[List[float]], track_nulls: bool, uid=None):
        super().__init__("vecGeo", uid)
        self.fills = fills
        self.track_nulls = track_nulls

    def transform_column(self, table: FeatureTable) -> Column:
        n = table.num_rows
        blocks, meta = [], []
        for f, fill in zip(self.input_features, self.fills):
            rows = _geo_rows(table[f.name])
            width = 3 + (1 if self.track_nulls else 0)
            block = np.zeros((n, width), dtype=np.float32)
            for i, r in enumerate(rows):
                if r is None:
                    block[i, :3] = fill
                    if self.track_nulls:
                        block[i, 3] = 1.0
                else:
                    block[i, 0], block[i, 1] = float(r[0]), float(r[1])
                    block[i, 2] = float(r[2]) if len(r) > 2 else 0.0
            blocks.append(block)
            meta.extend([VectorColumnMetadata(
                f.name, f.type_name, f.name, None, descriptor_value=g)
                for g in _GEO_NAMES])
            if self.track_nulls:
                meta.append(VectorColumnMetadata(
                    f.name, f.type_name, f.name, NULL_INDICATOR))
        return self._emit(np.concatenate(blocks, axis=1), meta)


class GeolocationMapVectorizer(Estimator):
    """Seq[GeolocationMap] → OPVector: per-key midpoint-fill + null indicator
    (reference GeolocationMapVectorizer.scala)."""

    output_type = OPVector

    def __init__(self, track_nulls: bool = TransmogrifierDefaults.TrackNulls,
                 uid=None):
        super().__init__("vecGeoMap", uid)
        self.track_nulls = track_nulls

    def fit(self, table: FeatureTable) -> Transformer:
        all_keys: List[List[str]] = []
        fills: List[Dict[str, List[float]]] = []
        for f in self.input_features:
            col = table[f.name]
            valid = col.valid_mask()
            keys: set = set()
            per_key: Dict[str, List[List[float]]] = {}
            for i in range(len(col)):
                r = col.values[i] if valid[i] else None
                if not r:
                    continue
                for k, v in r.items():
                    if v is not None and len(v) >= 2:
                        keys.add(str(k))
                        per_key.setdefault(str(k), []).append(list(v))
            kf: Dict[str, List[float]] = {}
            for k in sorted(keys):
                pts = np.array([[v[0], v[1]] for v in per_key[k]], dtype=np.float64)
                lat, lon = geographic_midpoint(pts)
                acc = float(np.mean([v[2] if len(v) > 2 else 0.0
                                     for v in per_key[k]]))
                kf[k] = [lat, lon, acc]
            all_keys.append(sorted(keys))
            fills.append(kf)
        model = GeolocationMapVectorizerModel(
            keys=all_keys, fills=fills, track_nulls=self.track_nulls)
        return self._finalize_model(model)


class GeolocationMapVectorizerModel(_VectorModelBase):
    def __init__(self, keys: List[List[str]], fills: List[Dict[str, List[float]]],
                 track_nulls: bool, uid=None):
        super().__init__("vecGeoMap", uid)
        self.keys = keys
        self.fills = fills
        self.track_nulls = track_nulls

    def transform_column(self, table: FeatureTable) -> Column:
        n = table.num_rows
        blocks, meta = [], []
        for f, keys, kf in zip(self.input_features, self.keys, self.fills):
            col = table[f.name]
            valid = col.valid_mask()
            for key in keys:
                width = 3 + (1 if self.track_nulls else 0)
                block = np.zeros((n, width), dtype=np.float32)
                fill = kf.get(key, [0.0, 0.0, 0.0])
                for i in range(n):
                    r = col.values[i] if valid[i] else None
                    v = r.get(key) if r else None
                    if v is None or len(v) < 2:
                        block[i, :3] = fill
                        if self.track_nulls:
                            block[i, 3] = 1.0
                    else:
                        block[i, 0], block[i, 1] = float(v[0]), float(v[1])
                        block[i, 2] = float(v[2]) if len(v) > 2 else 0.0
                blocks.append(block)
                meta.extend([VectorColumnMetadata(
                    f.name, f.type_name, key, None, descriptor_value=g)
                    for g in _GEO_NAMES])
                if self.track_nulls:
                    meta.append(VectorColumnMetadata(
                        f.name, f.type_name, key, NULL_INDICATOR))
        mat = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), dtype=np.float32))
        return self._emit(mat, meta)
