"""Transmogrifier — automated feature engineering dispatch.

Mirrors reference Transmogrifier.transmogrify
(core/.../impl/feature/Transmogrifier.scala:102-348): group features by type,
apply each group's default vectorizer, combine everything into a single
OPVector feature.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Type

from ...features import Feature
from ...types import (
    Base64, Binary, BinaryMap, City, ComboBox, Country, Currency, Date,
    DateList, DateMap, DateTime, DateTimeMap, Email, FeatureType, Geolocation,
    GeolocationMap, ID, Integral, MultiPickList, MultiPickListMap, OPMap,
    OPVector, Percent, PickList, PostalCode, Prediction, Real, RealNN, State,
    Street, Text, TextArea, TextAreaMap, TextList, TextMap, URL, Phone,
)
from .dates import (
    DEFAULT_CIRCULAR_PERIODS, DateListVectorizer, DateMapToUnitCircleVectorizer,
    DateToUnitCircleTransformer,
)
from .geo import GeolocationMapVectorizer, GeolocationVectorizer
from .maps import MapVectorizer, SmartTextMapVectorizer, TextMapPivotVectorizer
from .vectorizers import (
    BinaryVectorizer, HashingVectorizer, IntegralVectorizer, OneHotVectorizer,
    RealNNVectorizer, RealVectorizer, SmartTextVectorizer, VectorsCombiner,
)

#: type groups → vectorizer builder (reference Transmogrifier case match :102-348)
_CATEGORICAL_TYPES = (PickList, ComboBox, ID, Country, State, City, PostalCode,
                      Street, Phone)
_FREE_TEXT_TYPES = (TextArea, Base64, URL, Email)
_FREE_TEXT_MAP_TYPES = (TextMap, TextAreaMap)


def transmogrify(features: Sequence[Feature]) -> Feature:
    """Auto-vectorize a heterogeneous feature set into one OPVector feature
    (the ``.transmogrify()`` / ``.vectorize()`` entry of the reference DSL,
    RichFeaturesCollection.scala:69)."""
    if not features:
        raise ValueError("transmogrify needs at least one feature")
    groups: Dict[str, List[Feature]] = {}
    for f in features:
        groups.setdefault(_group_of(f), []).append(f)
    vectorized: List[Feature] = []
    for group in sorted(groups):
        feats = sorted(groups[group], key=lambda f: f.name)
        stage = _vectorizer_for(group)
        stage.set_input(*feats)
        vectorized.append(stage.get_output())
    if len(vectorized) == 1:
        return vectorized[0]
    combiner = VectorsCombiner()
    combiner.set_input(*vectorized)
    return combiner.get_output()


def _group_of(f: Feature) -> str:
    ft = f.feature_type
    if issubclass(ft, Prediction):
        return "vector"
    if issubclass(ft, GeolocationMap):
        return "geomap"
    if issubclass(ft, (DateMap, DateTimeMap)):
        return "datemap"
    if issubclass(ft, MultiPickListMap):
        return "multipicklistmap"
    if issubclass(ft, _FREE_TEXT_MAP_TYPES):
        return "textmap"
    if issubclass(ft, OPMap):
        elem = getattr(ft, "element_type", None)
        if elem is not None and issubclass(elem, (Real, Integral, Binary)):
            return "numericmap"
        return "categoricalmap"
    if issubclass(ft, RealNN):
        return "realnn"
    if issubclass(ft, (Real, Currency, Percent)):
        return "real"
    if issubclass(ft, Binary):
        return "binary"
    if issubclass(ft, (Date, DateTime)):
        return "date"
    if issubclass(ft, Integral):
        return "integral"
    if issubclass(ft, MultiPickList):
        return "multipicklist"
    if issubclass(ft, _CATEGORICAL_TYPES):
        return "categorical"
    if issubclass(ft, _FREE_TEXT_TYPES) or ft is Text:
        return "text"
    if issubclass(ft, DateList):
        return "datelist"
    if issubclass(ft, Geolocation):
        return "geolocation"
    if issubclass(ft, TextList):
        return "textlist"
    if issubclass(ft, OPVector):
        return "vector"
    raise NotImplementedError(
        f"transmogrify has no default vectorizer for {ft.__name__} "
        f"(feature '{f.name}') yet")


def _vectorizer_for(group: str):
    if group == "realnn":
        return RealNNVectorizer()
    if group == "real":
        return RealVectorizer()
    if group == "integral":
        return IntegralVectorizer()
    if group == "date":
        # reference default: circular date representations (Transmogrifier
        # case Date/DateTime with CircularDateRepresentations)
        return DateToUnitCircleTransformer(periods=DEFAULT_CIRCULAR_PERIODS)
    if group == "datelist":
        return DateListVectorizer(pivot="SinceLast")
    if group == "binary":
        return BinaryVectorizer()
    if group in ("categorical", "multipicklist"):
        return OneHotVectorizer()
    if group == "text":
        return SmartTextVectorizer()
    if group == "textlist":
        return HashingVectorizer()
    if group == "geolocation":
        return GeolocationVectorizer()
    if group == "numericmap":
        return MapVectorizer()
    if group == "categoricalmap":
        return TextMapPivotVectorizer()
    if group == "multipicklistmap":
        return TextMapPivotVectorizer()
    if group == "textmap":
        return SmartTextMapVectorizer()
    if group == "datemap":
        return DateMapToUnitCircleVectorizer()
    if group == "geomap":
        return GeolocationMapVectorizer()
    if group == "vector":
        return VectorsCombiner()
    raise AssertionError(group)
