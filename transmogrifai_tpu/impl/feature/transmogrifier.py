"""Transmogrifier — automated feature engineering dispatch.

Mirrors reference Transmogrifier.transmogrify
(core/.../impl/feature/Transmogrifier.scala:102-348): group features by type,
apply each group's default vectorizer, combine everything into a single
OPVector feature.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Type

from ...features import Feature
from ...types import (
    Binary, City, ComboBox, Country, Currency, Date, DateTime, FeatureType, ID,
    Integral, MultiPickList, OPVector, Percent, PickList, PostalCode, Real,
    RealNN, State, Street, Text, TextArea, TextList, Email, URL, Base64, Phone,
)
from .vectorizers import (
    BinaryVectorizer, HashingVectorizer, IntegralVectorizer, OneHotVectorizer,
    RealNNVectorizer, RealVectorizer, SmartTextVectorizer, VectorsCombiner,
)

#: type groups → vectorizer builder (reference Transmogrifier case match :102-348)
_CATEGORICAL_TYPES = (PickList, ComboBox, ID, Country, State, City, PostalCode,
                      Street, Phone)
_FREE_TEXT_TYPES = (TextArea, Base64, URL, Email)


def transmogrify(features: Sequence[Feature]) -> Feature:
    """Auto-vectorize a heterogeneous feature set into one OPVector feature
    (the ``.transmogrify()`` / ``.vectorize()`` entry of the reference DSL,
    RichFeaturesCollection.scala:69)."""
    if not features:
        raise ValueError("transmogrify needs at least one feature")
    groups: Dict[str, List[Feature]] = {}
    for f in features:
        groups.setdefault(_group_of(f), []).append(f)
    vectorized: List[Feature] = []
    for group in sorted(groups):
        feats = sorted(groups[group], key=lambda f: f.name)
        stage = _vectorizer_for(group)
        stage.set_input(*feats)
        vectorized.append(stage.get_output())
    if len(vectorized) == 1:
        return vectorized[0]
    combiner = VectorsCombiner()
    combiner.set_input(*vectorized)
    return combiner.get_output()


def _group_of(f: Feature) -> str:
    ft = f.feature_type
    if issubclass(ft, RealNN):
        return "realnn"
    if issubclass(ft, (Real, Currency, Percent)):
        return "real"
    if issubclass(ft, Binary):
        return "binary"
    if issubclass(ft, (Date, DateTime)):
        return "date"
    if issubclass(ft, Integral):
        return "integral"
    if issubclass(ft, MultiPickList):
        return "multipicklist"
    if issubclass(ft, _CATEGORICAL_TYPES):
        return "categorical"
    if issubclass(ft, _FREE_TEXT_TYPES) or ft is Text:
        return "text"
    if issubclass(ft, TextList):
        return "textlist"
    if issubclass(ft, OPVector):
        return "vector"
    raise NotImplementedError(
        f"transmogrify has no default vectorizer for {ft.__name__} "
        f"(feature '{f.name}') yet")


def _vectorizer_for(group: str):
    if group == "realnn":
        return RealNNVectorizer()
    if group == "real":
        return RealVectorizer()
    if group in ("integral", "date"):
        # dates as integral until the unit-circle date vectorizer lands
        return IntegralVectorizer()
    if group == "binary":
        return BinaryVectorizer()
    if group in ("categorical", "multipicklist"):
        return OneHotVectorizer()
    if group == "text":
        return SmartTextVectorizer()
    if group == "textlist":
        return HashingVectorizer()
    if group == "vector":
        return VectorsCombiner()
    raise AssertionError(group)
