"""Text / NLP stages.

TPU re-design of the reference text zoo (reference:
core/.../impl/feature/OpCountVectorizer.scala:127, OpWord2Vec.scala:128,
OpLDA.scala:199, OpNGram.scala:64, OpStopWordsRemover.scala:70,
LangDetector.scala:68, NameEntityRecognizer.scala:101, MimeTypeDetector.scala:134,
PhoneNumberParser.scala:566, ValidEmailTransformer.scala:47,
OpStringIndexer.scala / OpIndexToString.scala).

Execution split: vocabulary building, tokenizing and parsing are host string
work; the *learning* stages (Word2Vec skip-gram with negative sampling, LDA
variational EM) train as jitted JAX programs on the device — batched matmuls
on the MXU instead of Spark's mllib implementations. Where the reference
leans on JVM libraries (Optimaize langdetect, OpenNLP NER, Tika MIME,
libphonenumber), the equivalents here are self-contained: stopword-profile
language scoring, rule-based NER, magic-byte MIME sniffing, and a
digit-pattern phone validator.
"""
from __future__ import annotations

import base64 as _b64
import re
import unicodedata
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...stages.base import (BinaryTransformer, Estimator, Transformer,
                            UnaryTransformer)
from ...table import Column, FeatureTable
from ...types import (
    Base64, Binary, Email, Integral, MultiPickListMap, OPVector, Phone,
    PickList, Real, RealMap, RealNN, Text, TextList, URL,
)
from ...vector_metadata import VectorColumnMetadata, VectorMetadata
from .vectorizers import TransmogrifierDefaults, _VectorModelBase, tokenize_text


# ---------------------------------------------------------------------------
# CountVectorizer / NGram / StopWords / StringIndexer
# ---------------------------------------------------------------------------

class OpCountVectorizer(Estimator):
    """TextList → OPVector of vocabulary counts (reference
    OpCountVectorizer.scala — vocabSize / minDF / binary)."""

    input_types = (TextList,)
    output_type = OPVector

    def __init__(self, vocab_size: int = 512, min_df: int = 1,
                 binary: bool = False, uid=None):
        super().__init__("countVec", uid)
        self.vocab_size = vocab_size
        self.min_df = min_df
        self.binary = binary

    def fit(self, table: FeatureTable) -> Transformer:
        f = self.input_features[0]
        col = table[f.name]
        valid = col.valid_mask()
        df_counts: Counter = Counter()
        for i in range(len(col)):
            if valid[i] and col.values[i]:
                df_counts.update(set(col.values[i]))
        vocab = [t for t, c in df_counts.most_common() if c >= self.min_df]
        vocab = sorted(vocab, key=lambda t: (-df_counts[t], t))[: self.vocab_size]
        model = OpCountVectorizerModel(vocab=vocab, binary=self.binary)
        return self._finalize_model(model)


class OpCountVectorizerModel(_VectorModelBase):
    def __init__(self, vocab: List[str], binary: bool, uid=None):
        super().__init__("countVec", uid)
        self.vocab = vocab
        self.binary = binary

    def transform_column(self, table: FeatureTable) -> Column:
        f = self.input_features[0]
        col = table[f.name]
        valid = col.valid_mask()
        index = {t: j for j, t in enumerate(self.vocab)}
        mat = np.zeros((len(col), len(self.vocab)), dtype=np.float32)
        for i in range(len(col)):
            if not valid[i] or not col.values[i]:
                continue
            for t in col.values[i]:
                j = index.get(t)
                if j is not None:
                    mat[i, j] += 1.0
        if self.binary:
            np.minimum(mat, 1.0, out=mat)
        meta = [VectorColumnMetadata(f.name, f.type_name, f.name, t)
                for t in self.vocab]
        return self._emit(mat, meta)


class OpNGram(UnaryTransformer):
    """TextList → TextList of word n-grams (reference OpNGram.scala)."""

    def __init__(self, n: int = 2, uid=None):
        def fn(toks):
            if not toks:
                return []
            return [" ".join(toks[i:i + n])
                    for i in range(max(len(toks) - n + 1, 0))]
        super().__init__("ngram", transform_fn=fn, output_type=TextList,
                         input_type=TextList, uid=uid)
        self.n = n


#: English stopwords (reference uses Spark's StopWordsRemover defaults)
ENGLISH_STOP_WORDS = frozenset("""
a about above after again against all am an and any are aren't as at be
because been before being below between both but by can't cannot could
couldn't did didn't do does doesn't doing don't down during each few for from
further had hadn't has hasn't have haven't having he her here hers herself him
himself his how i i'm if in into is isn't it its itself let's me more most my
myself no nor not of off on once only or other ought our ours ourselves out
over own same she should shouldn't so some such than that the their theirs
them themselves then there these they this those through to too under until up
very was wasn't we were weren't what when where which while who whom why with
won't would wouldn't you your yours yourself yourselves
""".split())

#: language → high-frequency function words. The profiles are DATA, not
#: code (reference LangDetector.scala wraps Optimaize's ~70 n-gram
#: profiles; ~20 languages here, each pinned by tests/test_nlp_accuracy.py
#: fixtures). Accented/diacritic forms included where the tokenizer keeps
#: them (it lowercases but preserves letters).
#: combining-mark ranges whose marks the word-regex tokenizer SPLITS words
#: on: Hebrew niqqud/pointing, Arabic harakat, Brahmic vowel signs
#: (Devanagari…Sinhala). Latin combining marks are NOT stripped — é/ř/ä
#: recompose under NFC and carry the close-pair cues (_CUE_TOKENS: gl
#: 'máis' vs pt 'mais', cs 'při' vs sk 'pri' must stay distinct).
_SPLIT_MARK_RANGES = ((0x0591, 0x05C7), (0x064B, 0x0670),
                      (0x0900, 0x0DFF))


def _strip_marks(text: str) -> str:
    """Remove the combining marks (Mn/Mc) of the scripts in
    _SPLIT_MARK_RANGES after NFD decomposition, then re-compose (NFC) so
    Latin diacritics return to their precomposed forms. Without this the
    tokenizer splits pointed words ('דאָס' → 'דא', 'ס'; 'हामी' → 'ह',
    'म') and profile hits can never match."""
    out = []
    for c in unicodedata.normalize("NFD", text):
        if unicodedata.category(c) in ("Mn", "Mc"):
            cp = ord(c)
            if any(lo <= cp <= hi for lo, hi in _SPLIT_MARK_RANGES):
                continue
        out.append(c)
    return unicodedata.normalize("NFC", "".join(out))


_STOPWORD_PROFILES: Dict[str, frozenset] = {
    "en": ENGLISH_STOP_WORDS,
    "fr": frozenset("""le la les un une des et est dans pour que qui sur avec
 ne pas ce cette son ses il elle nous vous ils elles au aux du de mais ou
 donc""".split()),
    "es": frozenset("""el la los las un una unos y es en para que por con no
 se su sus este esta esto pero mas como o si del al lo ya""".split()),
    "de": frozenset("""der die das ein eine und ist in fur mit nicht sich auf
 als auch es an werden aus er sie nach bei um am sind noch wie einem
 uber""".split()),
    "it": frozenset("""il la le lo gli un una e di che in per con non si su
 questo questa sono ma come anche piu o se del alla nel""".split()),
    "pt": frozenset("""o a os as um uma de do da dos das e é em no na nos nas
 para que não com por se mais mas como ou ao aos pelo pela isso está
 são""".split()),
    "nl": frozenset("""de het een en van in is dat op te met voor niet zijn
 er aan ook als bij nog naar dan uit deze om maar hij wij jullie ze
 wordt""".split()),
    "sv": frozenset("""och att det som en på är av för med den till i inte
 har de ett om men var sig så här vi han hon efter vid kan ska""".split()),
    "no": frozenset("""og i det som en er på til av for med at ikke den har
 de et om men var seg så her vi han hun etter ved kan skal fra""".split()),
    "da": frozenset("""og i det som en er på til af for med at ikke den har
 de et om men var sig så her vi han hun efter ved kan skal fra
 ogsa""".split()),
    "fi": frozenset("""ja on ei se että hän oli mutta joka ovat kun niin
 myös kuin sen tämä ole mitä nyt vain siinä jo hänen kanssa""".split()),
    "pl": frozenset("""i w na z że się nie jest to do jak po co tak ale o za
 od przez dla przy był być są ten tym jego jej ich może""".split()),
    "ru": frozenset("""и в не на я что он с как это по но они мы она из у за
 то же вы так его её к был для при о а или если когда""".split()),
    "uk": frozenset("""і в не на я що він з як це по але вони ми вона із у
 за те ж ви так його її до був для при про а або якщо коли""".split()),
    "tr": frozenset("""ve bir bu da de için ile olarak daha çok gibi ama en
 kadar sonra olan var yok ben sen o biz siz onlar ne mi değil""".split()),
    "ro": frozenset("""și în nu a cu de la pe este un o care mai să se din
 dar ce el ea noi voi ei pentru sunt fost după până fără""".split()),
    "cs": frozenset("""a v na je se že to s z do i o k ale jako po za by byl
 jsou ten tato jeho její my vy oni když pro při nebo""".split()),
    "hu": frozenset("""a az és hogy nem is egy ez de van volt mint csak meg
 már el még mi ti ők ha lesz vagy azt aki ami ő mert""".split()),
    "id": frozenset("""yang dan di dengan untuk dari pada ini itu adalah
 tidak akan ke dalam juga bisa ada saya kamu dia kami mereka atau
 sudah""".split()),
    "vi": frozenset("""và của là có không được trong cho một người này các
 với những để tôi bạn anh chị em chúng họ hoặc đã sẽ đang""".split()),
    # -- round-4 tranche: toward Optimaize's ~70 (next 24) ------------------
    "ca": frozenset("""el la els les un una i és en per que amb no es seu
 seva aquest aquesta però més com del dels al als ho ja també""".split()),
    "hr": frozenset("""i u na je se da za s od su ne to kao ali o po iz koji
 biti bio ona mi vi oni kada ako ili sa što ovo ova taj""".split()),
    "sr": frozenset("""и у на је се да за с од су не то као али о по из
 који бити био она ми ви они када ако или са што ово ова тај""".split()),
    "bg": frozenset("""и в на е се да за с от са не то като но о по из
 който съм бил тя ние вие те кога ако или със що това този""".split()),
    "sk": frozenset("""a v na je sa že to s z do aj o k ale ako po za by bol
 sú ten táto jeho jej my vy oni keď pre pri alebo""".split()),
    "sl": frozenset("""in v na je se da za s z od so ne to kot ali o po iz
 ki biti bil ona mi vi oni ko če s čim to ta tudi""".split()),
    "lt": frozenset("""ir į yra kad su iš bet tai kaip o po už nuo per dėl
 prie buvo būti jis ji mes jūs jie kai jei arba šis ši""".split()),
    "lv": frozenset("""un ir ka ar no bet tas kā o pēc uz par pie bija būt
 viņš viņa mēs jūs viņi kad ja vai šis šī arī tikai""".split()),
    "et": frozenset("""ja on et ei see ta oli aga mis kui nii ka nagu oma
 selle olema tema meie teie nad siis või ning veel juba""".split()),
    "ms": frozenset("""yang dan di dengan untuk dari pada ini itu ialah
 tidak akan ke dalam juga boleh ada saya awak dia kami mereka atau
 telah""".split()),
    "tl": frozenset("""ang ng sa na at ay mga ito hindi para kung siya ako
 ikaw kami sila may ba rin lang naman pero o dahil""".split()),
    "sw": frozenset("""na ya wa kwa ni za katika la hii hiyo si kama lakini
 au yake wake mimi wewe yeye sisi nyinyi wao kuwa sana""".split()),
    "af": frozenset("""die en van in is dat op te met vir nie sy wees er aan
 ook as by nog na dan uit hierdie om maar hy ons julle hulle""".split()),
    "el": frozenset("""και το η ο να του της με που είναι για από δεν στο
 στη τον την τα οι ένα μια αυτό αλλά ή αν θα""".split()),
    "fa": frozenset("""و در به از که این را با است برای آن یک خود تا بر او
 ما شما آنها اگر یا هم نیز باید بود""".split()),
    "ar": frozenset("""في من على أن إلى عن مع هذا هذه التي الذي كان لا ما هو
 هي نحن أنتم هم إذا أو لم قد كل بعد""".split()),
    "he": frozenset("""של את על אל עם זה זאת אשר היה לא מה הוא היא אנחנו
 אתם הם אם או גם כל אחרי אבל יש כי""".split()),
    "hi": frozenset("""और का की के में है कि यह वह से पर को नहीं एक हम तुम
 वे अगर या भी सब बाद था थी""".split()),
    "bn": frozenset("""এবং ও এর যে মধ্যে হয় এই সে থেকে উপর কে না এক আমরা
 তুমি তারা যদি বা আরও সব পরে ছিল""".split()),
    "th": frozenset("""และ ของ ที่ ใน เป็น ไม่ ได้ ให้ มี ว่า จะ กับ แต่
 หรือ เขา เรา คุณ พวก ถ้า ก็ ทุก หลัง""".split()),
    "ja": frozenset("""の に は を た が で て と し れ さ ある いる も
 する から な こと として""".split()),
    "ko": frozenset("""이 그 저 것 수 들 및 에서 의 를 을 은 는 가 와 과
 하다 있다 없다 그리고 하지만""".split()),
    "zh": frozenset("""的 一 是 在 不 了 有 和 人 这 中 大 为 上 个 国 我
 以 要 他 时 来 用 们""".split()),
    "ta": frozenset("""மற்றும் இந்த அந்த என்று ஒரு இல்லை உள்ள அது இது நான்
 நீ அவர் நாம் அவர்கள் என அல்லது எல்லா பின்""".split()),
    # -- round-5 tranche: toward/past Optimaize's ~70 (see _SCRIPT_LANGS
    # for the 12 script-exact additions) -----------------------------------
    "is": frozenset("""og að er ekki það sem hann hún við þið þeir en um
 frá til með fyrir var ég þú hvað eða líka núna alltaf""".split()),
    "ga": frozenset("""agus an na is tá ní sé sí mé tú muid sibh siad ar
 le do ag go bhí seo sin ach nó gach nuair mar""".split()),
    "cy": frozenset("""y yr mae yn a ac i o gan am ar ei eu ni chi nhw
 oedd bod hwn hon ond neu gyda wedi fel dim""".split()),
    "eu": frozenset("""eta da ez du bat hau hori zen dira nik zuk guk
 zuek haiek edo ere baina izan dute dago egin behar""".split()),
    "gl": frozenset("""e o a os as un unha de do da en non que para con
 se máis pero como ou ao polo pola é son ten""".split()),
    "sq": frozenset("""dhe në një është nuk të për me nga se si por ose
 ai ajo ne ju ata kjo ky ishte janë kur çdo""".split()),
    "mk": frozenset("""и во на е се да за со од не тоа како но по кој
 беше таа ние вие тие ако или што ова овој сите""".split()),
    "be": frozenset("""і ў не на я што ён з як гэта па але яны мы яна у
 за тое ж вы так яго яе да быў для пры пра або калі""".split()),
    "ur": frozenset("""اور کا کی کے میں ہے کہ یہ وہ سے پر کو نہیں ایک ہم
 تم اگر یا بھی سب بعد تھا تھی""".split()),
    # -- round-5b: past Optimaize's ~70 -------------------------------------
    "mt": frozenset("""il u ta li ma hija huwa dan din għal minn fuq biex
 kien mhux ukoll jew meta kif dawn qed se iktar""".split()),
    "so": frozenset("""iyo ka ku oo waa in uu ay la ma aan ayaa waxaa kale
 badan sidoo markii halkan aad buu soo noqon""".split()),
    "ht": frozenset("""nan ak pou li yo ki sa se te gen moun tout pa mwen
 ou nou yon sou men anpil kounye apre""".split()),
    "br": frozenset("""hag ar an en e da eus ez oa bet ul ur med pe gant
 evit war a-raok goude brezhoneg kement""".split()),
    "yi": frozenset("""דער די דאָס איז און אין פֿון מיט אויף ער זי מיר איר
 זיי אַ אַן נישט וואָס ווען אויך נאָך""".split()),
    "mr": frozenset("""आणि आहे या तो ती ते मी तू आम्ही तुम्ही हा ही हे पण
 किंवा मध्ये वर साठी होता होती आहेत""".split()),
    "ne": frozenset("""र छ यो त्यो म तिमी हामी उनीहरू यी ती पनि वा मा लागि
 थियो थिए गर्न भने छन् हुन्छ""".split()),
}

#: decisive token/character CUES for closely-related language pairs where
#: shared stopwords drown the signal on short text (the reference's
#: Optimaize n-gram profiles are robust here; these weighted cues are the
#: hand-built analog). Token cues count 3x a stopword hit; each decisive
#: character counts 2x (capped) — sv/no/da, cs/sk, ms/id, pt/gl, fi/et.
_CUE_TOKENS: Dict[str, frozenset] = {
    "sv": frozenset("och är inte jag vad ingen mycket".split()),
    "no": frozenset("etter av hva noen ut".split()),
    "da": frozenset("af efter hvad nogen gennem".split()),
    "cs": frozenset("že když byl nebo při".split()),
    "sk": frozenset("keď bol alebo pri sú".split()),
    "ms": frozenset("boleh awak ialah kerana".split()),
    "id": frozenset("bisa kamu adalah karena sudah".split()),
    "pt": frozenset("uma não mais pelo pela são está".split()),
    "gl": frozenset("unha non máis polo pola ten".split()),
    "hr": frozenset("što tko uvijek lijepo tjedan".split()),
    "sl": frozenset("če tudi kot kdo vedno".split()),
}

_CUE_CHARS: Dict[str, str] = {
    "sv": "äö", "no": "æø", "da": "æø", "de": "ß",
    "cs": "řěů", "sk": "ľĺŕô", "is": "þð", "ro": "țș",
    "pt": "ãõ", "hu": "őű", "et": "õ", "tr": "ğı",
}

# mark-strip every profile/cue word once at import: the detector compares
# mark-stripped tokens (see _strip_marks — without this, pointed Yiddish /
# matra-bearing Devanagari words could never match)
_STOPWORD_PROFILES = {lang: frozenset(_strip_marks(w) for w in words)
                      for lang, words in _STOPWORD_PROFILES.items()}
_CUE_TOKENS = {lang: frozenset(_strip_marks(w) for w in words)
               for lang, words in _CUE_TOKENS.items()}

#: decisive Unicode script ranges: when ≥50% of a text's letters fall in
#: one of these blocks, the language set narrows to the block's candidates
#: (the Optimaize n-gram analog for languages without whitespace or with
#: unique scripts); within multi-language scripts the stopword profiles
#: disambiguate
_SCRIPT_LANGS = [
    ((0x3040, 0x30FF), ("ja",)),            # Hiragana + Katakana
    ((0xAC00, 0xD7AF), ("ko",)),            # Hangul syllables
    ((0x0E00, 0x0E7F), ("th",)),            # Thai
    ((0x0590, 0x05FF), ("he", "yi")),       # Hebrew script: he vs yi
    ((0x0900, 0x097F), ("hi", "mr", "ne")),  # Devanagari: hi/mr/ne
    ((0x0980, 0x09FF), ("bn",)),            # Bengali
    ((0x0B80, 0x0BFF), ("ta",)),            # Tamil
    ((0x0370, 0x03FF), ("el",)),            # Greek
    ((0x0600, 0x06FF), ("ar", "fa", "ur")),  # Arabic script: ar/fa/ur
    ((0x4E00, 0x9FFF), ("zh", "ja")),       # CJK ideographs: zh vs ja
    ((0x0400, 0x04FF), ("ru", "uk", "bg", "sr", "mk", "be")),  # Cyrillic
    # -- round-5: script-exact languages (Optimaize covers these via
    # profiles; a unique block is strictly stronger evidence) -------------
    ((0x0530, 0x058F), ("hy",)),            # Armenian
    ((0x10A0, 0x10FF), ("ka",)),            # Georgian
    ((0x0D00, 0x0D7F), ("ml",)),            # Malayalam
    ((0x0C00, 0x0C7F), ("te",)),            # Telugu
    ((0x0C80, 0x0CFF), ("kn",)),            # Kannada
    ((0x0A80, 0x0AFF), ("gu",)),            # Gujarati
    ((0x0A00, 0x0A7F), ("pa",)),            # Gurmukhi (Punjabi)
    ((0x0D80, 0x0DFF), ("si",)),            # Sinhala
    ((0x1000, 0x109F), ("my",)),            # Myanmar (Burmese)
    ((0x1780, 0x17FF), ("km",)),            # Khmer
    ((0x0E80, 0x0EFF), ("lo",)),            # Lao
    ((0x1200, 0x137F), ("am",)),            # Ethiopic (Amharic)
]

#: Urdu-specific letters absent from Arabic and Persian (ٹ ڈ ڑ ں ے ھ)
_UR_CHARS = frozenset("ٹڈڑںےھ")

#: Persian-specific letters absent from Arabic (پ چ ژ گ ک ی)
_FA_CHARS = frozenset("پچژگکی")


class OpStopWordsRemover(UnaryTransformer):
    """TextList → TextList minus stopwords (reference OpStopWordsRemover)."""

    def __init__(self, stop_words: Optional[Sequence[str]] = None,
                 case_sensitive: bool = False, uid=None):
        words = frozenset(stop_words) if stop_words is not None \
            else ENGLISH_STOP_WORDS
        if not case_sensitive:
            words = frozenset(w.lower() for w in words)

        def fn(toks):
            if not toks:
                return []
            if case_sensitive:
                return [t for t in toks if t not in words]
            return [t for t in toks if t.lower() not in words]

        super().__init__("stopWords", transform_fn=fn, output_type=TextList,
                         input_type=TextList, uid=uid)
        self.case_sensitive = case_sensitive


class OpStringIndexer(Estimator):
    """Text → RealNN label index ordered by frequency (reference
    OpStringIndexer.scala; handle_invalid: 'error' | 'skip' | 'keep' matches
    StringIndexer semantics — 'keep' maps unseen to vocab size)."""

    input_types = (Text,)
    output_type = RealNN

    def __init__(self, handle_invalid: str = "keep", uid=None):
        super().__init__("strIdx", uid)
        if handle_invalid not in ("error", "skip", "keep"):
            raise ValueError("handle_invalid must be error|skip|keep")
        self.handle_invalid = handle_invalid

    #: NoFilter overrides: count invalid rows as a trainable None label
    count_nulls = False

    def fit(self, table: FeatureTable) -> Transformer:
        f = self.input_features[0]
        col = table[f.name]
        valid = col.valid_mask()
        if self.count_nulls:
            cnt = Counter(str(col.values[i]) if valid[i] else None
                          for i in range(len(col)))
        else:
            cnt = Counter(str(col.values[i])
                          for i in range(len(col)) if valid[i])
        # rank by frequency; ties: null sorts with "" deterministically first
        labels = sorted(cnt, key=lambda t: (-cnt[t], t is not None, t or ""))
        model = OpStringIndexerModel(labels=labels,
                                     handle_invalid=self.handle_invalid)
        model.summary_metadata = {"labels": labels}
        return self._finalize_model(model)


class OpStringIndexerModel(Transformer):
    output_type = RealNN

    def __init__(self, labels: List[str], handle_invalid: str, uid=None):
        super().__init__("strIdx", uid)
        self.labels = labels
        self.handle_invalid = handle_invalid
        #: NoFilter variant: a null UNSEEN in training goes to the unseen
        #: bucket instead of conflating with "" (a null seen in training is
        #: its own label via the None entry in `labels` — see _index)
        self.null_to_unseen = False
        self._label_index = {t: i for i, t in enumerate(labels)}

    def _index(self, v: Optional[str]) -> Optional[float]:
        index = self._label_index
        if v is None:
            # NoFilter trains null as its own frequency-ranked label
            # (reference OpStringIndexerNoFilter.scala countByValue over
            # Option); only a null unseen in training goes to UnseenLabel
            if None in index:
                return float(index[None])
            if self.null_to_unseen:
                return float(len(self.labels))
            v = ""
        j = index.get(str(v))
        if j is not None:
            return float(j)
        if self.handle_invalid == "keep":
            return float(len(self.labels))
        if self.handle_invalid == "skip":
            return None
        raise ValueError(f"unseen label {v!r}")

    def rendered_labels(self) -> List[str]:
        """Labels with the trained-null entry rendered as 'null' (the
        metadata/text representation; indices match self.labels)."""
        return ["null" if t is None else t for t in self.labels]

    def transform_column(self, table: FeatureTable) -> Column:
        col = table[self.input_features[0].name]
        valid = col.valid_mask()
        vals = [self._index(col.values[i] if valid[i] else None)
                for i in range(len(col))]
        # label/index mapping rides the column (the reference attaches it to
        # the column schema metadata; PredictionDeIndexer reads it there)
        return Column.of_values(RealNN, vals).with_metadata(
            labels=self.rendered_labels())

    def transform_fn(self, v):
        return self._index(v)


#: label used by the NoFilter indexer variants for out-of-vocabulary values
UNSEEN_LABEL = "UnseenLabel"


class OpStringIndexerNoFilter(OpStringIndexer):
    """Text → RealNN index that never drops rows (reference
    OpStringIndexerNoFilter.scala). Matching the reference's ``countByValue``
    over Option: a null seen in training is itself a frequency-ranked label
    (a frequent null can take index 0) rendered as ``'null'`` in metadata;
    only values/nulls genuinely unseen in training map to the reserved
    ``UnseenLabel`` index (= vocab size) so the full label set round-trips
    through OpIndexToStringNoFilter.

    Caveat (shared with the reference's metadata rendering): a LITERAL
    ``"null"`` string in the training data renders identically to the
    trained-null label, so metadata label names are not injective in that
    corner — indices remain distinct and decoding is still total."""

    count_nulls = True

    def __init__(self, unseen_name: str = UNSEEN_LABEL, uid=None):
        super().__init__(handle_invalid="keep", uid=uid)
        self.unseen_name = unseen_name

    def fit(self, table: FeatureTable) -> Transformer:
        model = super().fit(table)
        model.null_to_unseen = True
        model.summary_metadata = {
            "labels": ["null" if t is None else t for t in model.labels]
            + [self.unseen_name],
            "unseenName": self.unseen_name,
        }
        return model


class OpIndexToString(Transformer):
    """RealNN index → Text label (reference OpIndexToString.scala)."""

    input_types = (RealNN,)
    output_type = Text

    def __init__(self, labels: Sequence[str], uid=None):
        super().__init__("idxToStr", uid)
        # a None label (NoFilter's trained-null) renders as 'null', matching
        # the reference metadata — text output can't carry a distinct None
        self.labels = ["null" if t is None else t for t in labels]

    def transform_column(self, table: FeatureTable) -> Column:
        col = table[self.input_features[0].name]
        vals = np.asarray(col.values).astype(np.int64).reshape(-1)
        out = [self.labels[v] if 0 <= v < len(self.labels) else None
               for v in vals]
        return Column.of_values(Text, out)

    def transform_fn(self, v):
        i = int(v) if v is not None else -1
        return self.labels[i] if 0 <= i < len(self.labels) else None


class OpIndexToStringNoFilter(OpIndexToString):
    """RealNN index → Text label, with out-of-range indices mapped to the
    reserved ``unseen_name`` instead of null (reference
    OpIndexToStringNoFilter.scala — the inverse of OpStringIndexerNoFilter,
    so label round-trips are total)."""

    def __init__(self, labels: Sequence[str], unseen_name: str = UNSEEN_LABEL,
                 uid=None):
        super().__init__(labels, uid=uid)
        self.unseen_name = unseen_name

    def transform_column(self, table: FeatureTable) -> Column:
        col = table[self.input_features[0].name]
        valid = col.valid_mask()
        raw = np.asarray(col.values, dtype=np.float64).reshape(-1)
        out = []
        for i in range(len(raw)):
            if not valid[i] or np.isnan(raw[i]):
                out.append(self.unseen_name)
                continue
            j = int(raw[i])
            out.append(self.labels[j] if 0 <= j < len(self.labels)
                       else self.unseen_name)
        return Column.of_values(Text, out)

    def transform_fn(self, v):
        if v is None or (isinstance(v, float) and np.isnan(v)):
            return self.unseen_name
        i = int(v)
        return self.labels[i] if 0 <= i < len(self.labels) else self.unseen_name


# ---------------------------------------------------------------------------
# Word2Vec (skip-gram negative sampling, jitted JAX training)
# ---------------------------------------------------------------------------

class OpWord2Vec(Estimator):
    """TextList → OPVector: average of learned word embeddings (reference
    OpWord2Vec.scala wraps Spark's Word2Vec). Training is a jitted SGNS loop:
    all (center, context, negatives) triples are materialized host-side once,
    then minibatch SGD runs as one lax.fori_loop of MXU-friendly gathers."""

    input_types = (TextList,)
    output_type = OPVector

    def __init__(self, vector_size: int = 32, window: int = 5,
                 min_count: int = 2, num_negatives: int = 4,
                 steps: int = 400, learning_rate: float = 0.5,
                 max_vocab: int = 4096, max_pairs: int = 2_000_000,
                 seed: int = 42, uid=None):
        super().__init__("word2vec", uid)
        self.vector_size = vector_size
        self.window = window
        self.min_count = min_count
        self.num_negatives = num_negatives
        self.steps = steps
        self.learning_rate = learning_rate
        self.max_pairs = max_pairs
        self.max_vocab = max_vocab
        self.seed = seed

    def fit(self, table: FeatureTable) -> Transformer:
        import jax
        import jax.numpy as jnp

        f = self.input_features[0]
        col = table[f.name]
        valid = col.valid_mask()
        docs = [col.values[i] for i in range(len(col))
                if valid[i] and col.values[i]]
        cnt = Counter(t for d in docs for t in d)
        vocab = [t for t, c in cnt.most_common(self.max_vocab)
                 if c >= self.min_count]
        index = {t: i for i, t in enumerate(vocab)}
        v = len(vocab)
        if v < 2:
            model = OpWord2VecModel(vocab=vocab,
                                    vectors=np.zeros((max(v, 1), self.vector_size),
                                                     dtype=np.float32))
            return self._finalize_model(model)

        # (center, context) pairs, host-side, reservoir-capped: an unbounded
        # O(corpus x window) materialization would exhaust host memory on a
        # real corpus — SGD samples minibatches anyway, so a uniform
        # reservoir of max_pairs pairs trains the same objective
        rng_res = np.random.RandomState(self.seed)
        cap = self.max_pairs
        centers: List[int] = []
        contexts: List[int] = []
        seen = 0
        for d in docs:
            ids = [index[t] for t in d if t in index]
            for i, c in enumerate(ids):
                lo, hi = max(0, i - self.window), min(len(ids), i + self.window + 1)
                for j in range(lo, hi):
                    if j == i:
                        continue
                    seen += 1
                    if len(centers) < cap:
                        centers.append(c)
                        contexts.append(ids[j])
                    else:  # reservoir sampling keeps a uniform subset
                        r = rng_res.randint(0, seen)
                        if r < cap:
                            centers[r] = c
                            contexts[r] = ids[j]
        if not centers:
            model = OpWord2VecModel(vocab=vocab,
                                    vectors=np.zeros((v, self.vector_size),
                                                     dtype=np.float32))
            return self._finalize_model(model)

        rng = np.random.RandomState(self.seed)
        centers_a = jnp.asarray(np.asarray(centers, dtype=np.int32))
        contexts_a = jnp.asarray(np.asarray(contexts, dtype=np.int32))
        n_pairs = centers_a.shape[0]
        batch = min(4096, n_pairs)
        key = jax.random.PRNGKey(self.seed)
        W0 = jnp.asarray(rng.randn(v, self.vector_size).astype(np.float32) * 0.1)
        C0 = jnp.zeros((v, self.vector_size), dtype=jnp.float32)
        # mean-gradient step: the scatter-adds below accumulate every pair in
        # the minibatch, so scale by 1/batch to keep updates bounded
        lr = self.learning_rate / batch
        negk = self.num_negatives

        def step(carry, _):
            W, C, key = carry
            key, k1, k2 = jax.random.split(key, 3)
            sel = jax.random.randint(k1, (batch,), 0, n_pairs)
            c_idx = centers_a[sel]
            o_idx = contexts_a[sel]
            neg = jax.random.randint(k2, (batch, negk), 0, v)
            wc = W[c_idx]                               # (b, k)
            co = C[o_idx]                               # (b, k)
            cn = C[neg]                                 # (b, neg, k)
            pos_logit = (wc * co).sum(-1)
            neg_logit = jnp.einsum("bk,bnk->bn", wc, cn)
            # SGNS gradients
            gp = jax.nn.sigmoid(pos_logit) - 1.0        # (b,)
            gn = jax.nn.sigmoid(neg_logit)              # (b, neg)
            g_wc = gp[:, None] * co + jnp.einsum("bn,bnk->bk", gn, cn)
            g_co = gp[:, None] * wc
            g_cn = gn[..., None] * wc[:, None, :]
            W = W.at[c_idx].add(-lr * g_wc)
            C = C.at[o_idx].add(-lr * g_co)
            C = C.at[neg.reshape(-1)].add(-lr * g_cn.reshape(-1, self.vector_size))
            return (W, C, key), None

        (W, _, _), _ = jax.lax.scan(step, (W0, C0, key), None, length=self.steps)
        model = OpWord2VecModel(vocab=vocab, vectors=np.asarray(W))
        return self._finalize_model(model)


class OpWord2VecModel(_VectorModelBase):
    def __init__(self, vocab: List[str], vectors: np.ndarray, uid=None):
        super().__init__("word2vec", uid)
        self.vocab = vocab
        self.vectors = vectors

    def transform_column(self, table: FeatureTable) -> Column:
        f = self.input_features[0]
        col = table[f.name]
        valid = col.valid_mask()
        index = {t: i for i, t in enumerate(self.vocab)}
        k = self.vectors.shape[1]
        mat = np.zeros((len(col), k), dtype=np.float32)
        for i in range(len(col)):
            if not valid[i] or not col.values[i]:
                continue
            ids = [index[t] for t in col.values[i] if t in index]
            if ids:
                mat[i] = self.vectors[ids].mean(axis=0)
        meta = [VectorColumnMetadata(f.name, f.type_name, f.name, None,
                                     descriptor_value=f"w2v_{j}")
                for j in range(k)]
        return self._emit(mat, meta)


# ---------------------------------------------------------------------------
# LDA (variational EM, jitted)
# ---------------------------------------------------------------------------

class OpLDA(Estimator):
    """OPVector (term counts) → OPVector topic mixture (reference
    OpLDA.scala wraps Spark's LDA). Variational EM with the E-step's
    per-document fixed-point iterations vmapped across the corpus — every
    EM sweep is one jitted device program."""

    input_types = (OPVector,)
    output_type = OPVector

    def __init__(self, k: int = 10, max_iter: int = 30, alpha: float = 0.1,
                 beta: float = 0.01, seed: int = 42, uid=None):
        super().__init__("lda", uid)
        self.k = k
        self.max_iter = max_iter
        self.alpha = alpha
        self.beta = beta
        self.seed = seed

    def fit(self, table: FeatureTable) -> Transformer:
        import jax
        import jax.numpy as jnp

        f = self.input_features[0]
        X = np.asarray(table[f.name].values, dtype=np.float32)  # (n, V) counts
        n, vsz = X.shape
        rng = np.random.RandomState(self.seed)
        topics0 = jnp.asarray(
            rng.dirichlet(np.ones(vsz), size=self.k).astype(np.float32))
        Xd = jnp.asarray(X)
        alpha, beta, K = self.alpha, self.beta, self.k

        @jax.jit
        def em(topics):
            def e_doc(x):
                gamma = jnp.ones((K,), jnp.float32)
                def one(carry, _):
                    g, _ = carry
                    # phi ∝ topics * exp(digamma(gamma))  (simplified VB)
                    weights = jnp.exp(jax.scipy.special.digamma(g))[:, None]
                    phi = weights * topics                   # (K, V)
                    phi = phi / jnp.maximum(phi.sum(0), 1e-12)[None, :]
                    g_new = alpha + (phi * x[None, :]).sum(1)
                    return (g_new, phi), None
                (g, phi), _ = jax.lax.scan(one, (gamma, topics), None, length=20)
                return g, phi * x[None, :]
            gammas, stats = jax.vmap(e_doc)(Xd)              # (n,K), (n,K,V)
            new_topics = stats.sum(0) + beta
            new_topics = new_topics / new_topics.sum(1, keepdims=True)
            return new_topics, gammas

        topics = topics0
        for _ in range(self.max_iter):
            topics, gammas = em(topics)
        model = OpLDAModel(topics=np.asarray(topics), alpha=self.alpha)
        return self._finalize_model(model)


class OpLDAModel(_VectorModelBase):
    def __init__(self, topics: np.ndarray, alpha: float, uid=None):
        super().__init__("lda", uid)
        self.topics = topics
        self.alpha = alpha

    def transform_column(self, table: FeatureTable) -> Column:
        import jax
        import jax.numpy as jnp
        f = self.input_features[0]
        X = jnp.asarray(np.asarray(table[f.name].values, dtype=np.float32))
        topics = jnp.asarray(self.topics)
        K = topics.shape[0]
        alpha = self.alpha

        @jax.jit
        def infer(Xb):
            def e_doc(x):
                gamma = jnp.ones((K,), jnp.float32)
                def one(g, _):
                    weights = jnp.exp(jax.scipy.special.digamma(g))[:, None]
                    phi = weights * topics
                    phi = phi / jnp.maximum(phi.sum(0), 1e-12)[None, :]
                    return alpha + (phi * x[None, :]).sum(1), None
                g, _ = jax.lax.scan(one, gamma, None, length=20)
                return g / g.sum()
            return jax.vmap(e_doc)(Xb)

        mat = np.asarray(infer(X))
        meta = [VectorColumnMetadata(f.name, f.type_name, f.name, None,
                                     descriptor_value=f"topic_{j}")
                for j in range(K)]
        return self._emit(mat, meta)


# ---------------------------------------------------------------------------
# Language detection / NER / MIME / phone / email / URL
# ---------------------------------------------------------------------------

class LangDetector(UnaryTransformer):
    """Text → RealMap of language scores (reference LangDetector.scala wraps
    Optimaize, ~70 languages; here: Unicode-script narrowing + weighted
    stopword/cue-profile hit rates over a **72-language** table — see
    _STOPWORD_PROFILES / _CUE_TOKENS / _SCRIPT_LANGS,
    tests/test_nlp_accuracy.py for per-language floors).

    Script-unique languages (ja/ko/th/he/hi/bn/ta/el and Arabic-script
    ar/fa) are decided by character blocks — the whitespace tokenizer
    cannot segment them; multi-language scripts (Cyrillic, Latin) fall
    through to per-language stopword profiles restricted to that script."""

    def __init__(self, uid=None):
        def fn(v):
            if not v:
                return None
            s = str(v)
            letters = [c for c in s if c.isalpha()]
            if letters:
                n_l = len(letters)
                in_range = {}
                for (lo, hi), langs in _SCRIPT_LANGS:
                    c = sum(1 for ch in letters if lo <= ord(ch) <= hi)
                    if c:
                        in_range[(lo, hi)] = (c, langs)
                kana = in_range.get((0x3040, 0x30FF), (0, ()))[0]
                cjk = in_range.get((0x4E00, 0x9FFF), (0, ()))[0]
                if kana and (kana + cjk) >= 0.5 * n_l:
                    return {"ja": 1.0}
                if cjk >= 0.5 * n_l:
                    return {"zh": 1.0}
                for (lo, hi), (c, langs) in in_range.items():
                    if c < 0.5 * n_l or (lo, hi) in (
                            (0x3040, 0x30FF), (0x4E00, 0x9FFF)):
                        continue
                    if langs == ("ar", "fa", "ur"):
                        if any(ch in _UR_CHARS for ch in s):
                            return {"ur": 1.0}
                        return {"fa" if any(ch in _FA_CHARS for ch in s)
                                else "ar": 1.0}
                    if len(langs) == 1:
                        return {langs[0]: 1.0}
                    # multi-language script: restrict profiles to the
                    # block. Only the he/yi and hi/mr/ne splits fall back
                    # to the block's dominant language on zero profile
                    # evidence — the Cyrillic block must keep returning
                    # None for unprofiled languages (docs/nlp.md: an
                    # unsupported language scores 0 everywhere, it does
                    # not pretend to be Russian)
                    scores = self._profile_scores(s, langs)
                    if scores:
                        return scores
                    if langs in (("he", "yi"), ("hi", "mr", "ne")):
                        return {langs[0]: 1.0}
                    return None
            return self._profile_scores(s, None)
        super().__init__("langDetect", transform_fn=fn, output_type=RealMap,
                         input_type=Text, uid=uid)

    @staticmethod
    def _profile_scores(s, restrict):
        toks = tokenize_text(_strip_marks(s))
        if not toks:
            return None
        scores = {}
        for lang, words in _STOPWORD_PROFILES.items():
            if restrict is not None and lang not in restrict:
                continue
            hits = sum(1 for t in toks if t in words)
            # weighted cues split closely-related pairs (see _CUE_TOKENS);
            # gated on >=1 base stopword hit so letters SHARED across
            # languages (sv/fi/et/de all write ä/ö) cannot rank a language
            # with zero profile evidence above the true one
            if hits:
                cues = _CUE_TOKENS.get(lang)
                if cues:
                    hits += 3 * sum(1 for t in toks if t in cues)
                cue_ch = _CUE_CHARS.get(lang)
                if cue_ch:
                    hits += 2 * min(sum(s.count(c) for c in cue_ch), 3)
                scores[lang] = hits / len(toks)
        total = sum(scores.values())
        if not total:
            return None
        return {k: v_ / total for k, v_ in scores.items()}


_NER_TITLES = frozenset({"mr", "mrs", "ms", "dr", "prof", "sir"})

#: Title-case run ENDING in one of these → Organization (reference OpenNLP
#: ships an organization model; suffix cues are the rule-based analog)
_NER_ORG_SUFFIXES = frozenset(
    """inc corp ltd llc plc gmbh ag co company corporation university
    institute college bank group holdings labs laboratories foundation
    association ministry department agency council committee""".split())

#: strongly-locative preposition before a single Title-case token →
#: Location even when the gazetteer misses it ("lives in Springfield");
#: 'from'/'to'/'of' are excluded — they introduce persons and orgs too
_NER_LOC_PREPS = frozenset({"in", "at", "near"})

#: gazetteer of countries/major cities (lowercase, ';'-separated so
#: multiword names stay whole); a Title-case run whose full text matches →
#: Location regardless of context (reference OpenNLP location model;
#: gazetteers are data, not code)
_NER_LOC_LOOKUP = frozenset(e.strip() for e in """
united states;united kingdom;france;germany;italy;spain;portugal;canada;
mexico;brazil;argentina;china;japan;india;australia;russia;netherlands;
belgium;sweden;norway;denmark;finland;poland;austria;switzerland;ireland;
greece;turkey;egypt;nigeria;kenya;south africa;new zealand;singapore;
london;paris;berlin;madrid;rome;lisbon;tokyo;beijing;shanghai;mumbai;
delhi;sydney;melbourne;moscow;amsterdam;brussels;stockholm;oslo;
copenhagen;helsinki;warsaw;vienna;zurich;dublin;athens;istanbul;cairo;
lagos;nairobi;toronto;vancouver;montreal;chicago;boston;seattle;
san francisco;new york;los angeles;washington;houston;atlanta;miami
""".replace("\n", "").split(";") if e.strip())


#: given-name lexicon (case-insensitive) for the two regimes where
#: capitalization carries no signal — lowercase prose and ALL-CAPS
#: headlines (the reference's OpenNLP model learns case features;
#: VERDICT r4 missing #4 lists exactly these losses). ~200 common
#: given names across cultures; data, not code.
_NER_FIRST_NAMES = frozenset("""
james john robert michael william david richard joseph thomas charles
christopher daniel matthew anthony mark donald steven paul andrew joshua
kenneth kevin brian george edward ronald timothy jason jeffrey ryan jacob
gary nicholas eric jonathan stephen larry justin scott brandon benjamin
samuel gregory frank alexander raymond patrick jack dennis jerry tyler
aaron jose adam henry nathan douglas zachary peter kyle walter ethan
jeremy harold keith christian roger noah gerald carl terry sean austin
arthur lawrence jesse dylan bryan joe jordan billy bruce albert willie
gabriel logan alan juan wayne roy ralph randy eugene vincent russell
elijah louis bobby philip johnny mary patricia jennifer linda elizabeth
barbara susan jessica sarah karen nancy lisa betty margaret sandra
ashley kimberly emily donna michelle dorothy carol amanda melissa
deborah stephanie rebecca sharon laura cynthia kathleen amy shirley
angela helen anna brenda pamela nicole emma samantha katherine christine
debra rachel catherine carolyn janet ruth maria heather diane virginia
julie joyce victoria olivia kelly christina lauren joan evelyn judith
megan cheryl andrea hannah martha jacqueline frances gloria ann teresa
kathryn sara janice jean alice madison doris abigail julia judy grace
denise amber marilyn beverly danielle theresa sophia marie diana
mohammed ahmed ali hassan ibrahim omar yusuf fatima aisha wei ming li
chen hiroshi yuki kenji sakura raj amit priya sanjay anil sunita ivan
dmitri olga natasha sergei pierre jean-claude marie-claire hans klaus
greta sven lars ingrid carlos miguel sofia diego pablo lucia paulo joao
""".split())

#: verbs/common words that collide with given names in lowercase prose —
#: a lowercase "mark said" must not become a Person
_NER_COMMON_AFTER = frozenset("""said says went goes saw sees met meets
told tells asked asks made makes got gets was is are were has had can
will would may might must shall the and with here there then now today
""".split())

#: given names that are also ordinary English words — excluded from the
#: no-case-signal recovery paths ("grace period", "mark twenty",
#: "amber alert", "jack hammer" must not become Persons; precision over
#: recall where case evidence is absent)
_NER_AMBIGUOUS_NAMES = frozenset("""mark grace amber frank jack will rose
dawn ruby jade bill bob art grant miles penny holly ivy joy hope june
april may summer carol crystal daisy hazel iris pearl violet olive gary
jean bruce wayne norman dean victor
""".split())


class NameEntityRecognizer(UnaryTransformer):
    """Text → MultiPickListMap of entities by tag (reference
    NameEntityRecognizer.scala wraps OpenNLP's name finder; here a
    rule-based recognizer over Title-case token runs: Organization by
    corporate/institutional suffix, Location by gazetteer or preposition
    cue, Person after a title or for multi-token runs, else Name).
    Round 5 adds the two no-case-signal regimes: lowercase given-name +
    surname pairs and ALL-CAPS text (lexicon/gazetteer-driven — OpenNLP's
    statistical model still wins on novel names in those regimes)."""

    def __init__(self, uid=None):
        def fn(v):
            if not v:
                return None
            tokens = re.findall(r"[A-Za-z][\w'.-]*", str(v))
            out: Dict[str, set] = {}
            alpha = [t for t in tokens if t.isalpha()]
            caps = sum(1 for t in alpha if t.isupper() and len(t) > 1)
            if alpha and len(alpha) >= 3 and caps >= 0.8 * len(alpha):
                return _ner_no_case(tokens, out)
            _ner_lowercase_pairs(tokens, out)
            i = 0
            while i < len(tokens):
                t = tokens[i]
                # titles introduce a Person but are not part of the name
                if t.lower().rstrip(".") in _NER_TITLES:
                    i += 1
                    continue
                if t[0].isupper() and i > 0:   # skip sentence-initial token
                    run = [t]
                    j = i + 1
                    while j < len(tokens) and tokens[j][0].isupper():
                        run.append(tokens[j])
                        j += 1
                    prev = tokens[i - 1].lower().rstrip(".")
                    joined = " ".join(run)
                    last = run[-1].lower().rstrip(".")
                    if last in _NER_ORG_SUFFIXES and len(run) > 1:
                        tag = "Organization"
                    elif joined.lower() in _NER_LOC_LOOKUP:
                        tag = "Location"
                    elif prev in _NER_LOC_PREPS and len(run) == 1:
                        tag = "Location"
                    elif prev in _NER_TITLES or len(run) > 1:
                        tag = "Person"
                    else:
                        tag = "Name"
                    out.setdefault(tag, set()).add(joined)
                    i = j
                else:
                    i += 1
            return {k: sorted(v_) for k, v_ in out.items()} or None
        super().__init__("ner", transform_fn=fn, output_type=MultiPickListMap,
                         input_type=Text, uid=uid)


def _ner_lowercase_pairs(tokens, out) -> None:
    """Recover lowercase 'firstname surname' Persons by lexicon — ONLY
    when the text carries no case signal at all (no Title-case token past
    position 0): in normally-cased prose, a lowercase 'grace period' is
    case EVIDENCE AGAINST a name, not a missed one. Ambiguous
    name-or-word given names are excluded."""
    if any(t[0].isupper() for t in tokens[1:]):
        return
    for i in range(len(tokens) - 1):
        a, b = tokens[i], tokens[i + 1]
        if (a.islower() and b.islower() and a in _NER_FIRST_NAMES
                and a not in _NER_AMBIGUOUS_NAMES
                and b.isalpha() and len(b) >= 3
                and b not in _NER_COMMON_AFTER
                and b not in _NER_FIRST_NAMES):
            out.setdefault("Person", set()).add(f"{a} {b}")


def _ner_no_case(tokens, out):
    """ALL-CAPS text: capitalization is uninformative, so entities come
    from the lexicons only — given-name pairs, the location gazetteer
    (1-2 token windows) and organization suffixes."""
    low = [t.lower().rstrip(".") for t in tokens]
    n = len(tokens)
    i = 0
    while i < n:
        two = " ".join(low[i:i + 2]) if i + 1 < n else None
        if two and two in _NER_LOC_LOOKUP:
            out.setdefault("Location", set()).add(
                " ".join(tokens[i:i + 2]))
            i += 2
            continue
        if low[i] in _NER_LOC_LOOKUP:
            out.setdefault("Location", set()).add(tokens[i])
            i += 1
            continue
        if (low[i] in _NER_FIRST_NAMES
                and low[i] not in _NER_AMBIGUOUS_NAMES and i + 1 < n
                and tokens[i + 1].isalpha()
                and low[i + 1] not in _NER_COMMON_AFTER):
            j = i + 2
            if j < n and low[j] in _NER_ORG_SUFFIXES:
                out.setdefault("Organization", set()).add(
                    " ".join(tokens[i:j + 1]))
                i = j + 1
                continue
            out.setdefault("Person", set()).add(
                " ".join(tokens[i:i + 2]))
            i += 2
            continue
        if low[i] in _NER_ORG_SUFFIXES and i > 0 \
                and tokens[i - 1].isalpha():
            out.setdefault("Organization", set()).add(
                f"{tokens[i - 1]} {tokens[i]}")
        i += 1
    return {k: sorted(v_) for k, v_ in out.items()} or None


#: (magic bytes, offset, MIME). Reference Tika inspects hundreds of
#: formats incl. containers; this table covers the common ones whose magic
#: fits in the first 16 decoded bytes (offset 8 handles RIFF/ftyp family)
_MAGIC = [
    (b"%PDF", 0, "application/pdf"),
    (b"\x89PNG", 0, "image/png"),
    (b"\xff\xd8\xff", 0, "image/jpeg"),
    (b"GIF8", 0, "image/gif"),
    (b"PK\x03\x04", 0, "application/zip"),
    (b"\x1f\x8b", 0, "application/gzip"),
    (b"BM", 0, "image/bmp"),
    (b"WEBP", 8, "image/webp"),
    (b"WAVE", 8, "audio/x-wav"),
    (b"AVI ", 8, "video/x-msvideo"),
    (b"ftyp", 4, "video/mp4"),
    (b"II*\x00", 0, "image/tiff"),
    (b"MM\x00*", 0, "image/tiff"),
    (b"\x00\x00\x01\x00", 0, "image/vnd.microsoft.icon"),
    (b"ID3", 0, "audio/mpeg"),
    (b"\xff\xfb", 0, "audio/mpeg"),
    (b"OggS", 0, "audio/ogg"),
    (b"fLaC", 0, "audio/x-flac"),
    (b"7z\xbc\xaf\x27\x1c", 0, "application/x-7z-compressed"),
    (b"Rar!\x1a\x07", 0, "application/x-rar-compressed"),
    (b"BZh", 0, "application/x-bzip2"),
    (b"\xfd7zXZ\x00", 0, "application/x-xz"),
    (b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1", 0, "application/x-tika-msoffice"),
    (b"{\\rtf", 0, "application/rtf"),
    (b"%!PS", 0, "application/postscript"),
    (b"SQLite format 3", 0, "application/x-sqlite3"),
    (b"\x7fELF", 0, "application/x-executable"),
    (b"\xca\xfe\xba\xbe", 0, "application/java-vm"),
    (b"wOFF", 0, "font/woff"),
    (b"wOF2", 0, "font/woff2"),
    (b"{", 0, "application/json"),
    (b"<?xml", 0, "application/xml"),
    (b"<html", 0, "text/html"),
]


#: zip entry-name cues → container-specific MIME (reference Tika opens the
#: zip and reads [Content_Types].xml / the ODF mimetype entry; a docx IS a
#: zip — round 3 sniffed it as application/zip). The first local-file
#: header's name sits at byte 30, and OOXML/ODF/epub/jar writers put the
#: identifying entry first (ODF and epub REQUIRE it first).
_ZIP_CONTAINERS = [
    (b"word/", "application/vnd.openxmlformats-officedocument"
               ".wordprocessingml.document"),
    (b"xl/", "application/vnd.openxmlformats-officedocument"
             ".spreadsheetml.sheet"),
    (b"ppt/", "application/vnd.openxmlformats-officedocument"
              ".presentationml.presentation"),
    (b"mimetypeapplication/vnd.oasis.opendocument.text",
     "application/vnd.oasis.opendocument.text"),
    (b"mimetypeapplication/vnd.oasis.opendocument.spreadsheet",
     "application/vnd.oasis.opendocument.spreadsheet"),
    (b"mimetypeapplication/vnd.oasis.opendocument.presentation",
     "application/vnd.oasis.opendocument.presentation"),
    (b"mimetypeapplication/epub+zip", "application/epub+zip"),
    (b"META-INF/MANIFEST.MF", "application/java-archive"),
]

#: how much base64 we decode for container inspection: 4096 chars → 3072
#: bytes, enough for the tar ustar magic at offset 257 and the zip central
#: cues ([Content_Types].xml appears within the first entries for OOXML)
_MIME_PEEK_B64 = 4096


def _zip_entry_names(buf: bytes, limit: int = 16):
    """Entry names from zip local-file headers within the peek window.
    Anchored parsing (not substring search over compressed bytes — deflate
    data or an unrelated path like 'crossword/x.txt' must not look like an
    OOXML part)."""
    names = []
    off = 0
    while len(names) < limit and off + 30 <= len(buf):
        if buf[off:off + 4] != b"PK\x03\x04":
            break
        n_len = int.from_bytes(buf[off + 26:off + 28], "little")
        e_len = int.from_bytes(buf[off + 28:off + 30], "little")
        c_size = int.from_bytes(buf[off + 18:off + 22], "little")
        names.append(buf[off + 30:off + 30 + n_len])
        nxt = off + 30 + n_len + e_len + c_size
        if nxt <= off:
            break
        off = nxt
    return names


def _zip_stored_content(buf: bytes, target: bytes, limit: int = 16) -> bytes:
    """Content bytes of a STORED (method 0) local-file entry named
    ``target`` within the peek window; b"" when absent, compressed, or
    truncated. Anchored header walk like :func:`_zip_entry_names`."""
    off = 0
    seen = 0
    while seen < limit and off + 30 <= len(buf):
        if buf[off:off + 4] != b"PK\x03\x04":
            break
        method = int.from_bytes(buf[off + 8:off + 10], "little")
        n_len = int.from_bytes(buf[off + 26:off + 28], "little")
        e_len = int.from_bytes(buf[off + 28:off + 30], "little")
        c_size = int.from_bytes(buf[off + 18:off + 22], "little")
        name = buf[off + 30:off + 30 + n_len]
        data_off = off + 30 + n_len + e_len
        if name == target:
            if method != 0:
                return b""
            return buf[data_off:data_off + c_size]
        nxt = data_off + c_size
        if nxt <= off:
            break
        off = nxt
        seen += 1
    return b""


def _sniff_zip(buf: bytes) -> str:
    """Inside-zip container detection (Tika's container recursion analog):
    decisions key on parsed ENTRY NAMES (and the ODF/epub mimetype entry's
    stored content, which immediately follows its header)."""
    names = _zip_entry_names(buf)
    if names and names[0] == b"mimetype":
        # ODF/epub require the uncompressed mimetype entry first; its
        # content starts right after the 30-byte header + name
        for cue, mime in _ZIP_CONTAINERS:
            if cue.startswith(b"mimetype") and cue[8:] in buf[:300]:
                return mime
    for nm in names:
        if nm.startswith(b"word/"):
            return _ZIP_CONTAINERS[0][1]
        if nm.startswith(b"xl/"):
            return _ZIP_CONTAINERS[1][1]
        if nm.startswith(b"ppt/"):
            return _ZIP_CONTAINERS[2][1]
        if nm == b"META-INF/MANIFEST.MF":
            return _ZIP_CONTAINERS[7][1]
    if any(nm == b"[Content_Types].xml" for nm in names):
        # OOXML whose word/-xl/-ppt/ parts fall outside the peek window
        # (nonstandard entry order, or a large [Content_Types].xml pushing
        # them past 3 KB): the flavor lives in [Content_Types].xml's
        # MAIN-part declaration, so when that entry is STORED parse its
        # content (never the surrounding deflate bytes — the
        # _zip_entry_names invariant); else report Tika's generic OOXML
        # type rather than degrading to application/zip
        ct = _zip_stored_content(buf, b"[Content_Types].xml")
        for cue, mime in (
                (b"wordprocessingml.document.main+xml",
                 _ZIP_CONTAINERS[0][1]),
                (b"spreadsheetml.sheet.main+xml", _ZIP_CONTAINERS[1][1]),
                (b"presentationml.presentation.main+xml",
                 _ZIP_CONTAINERS[2][1])):
            if cue in ct:
                return mime
        return "application/x-tika-ooxml"
    return "application/zip"


#: OLE2 main-stream names → concrete legacy-Office MIME type (Tika's POIFS
#: container detection analog; names live in the compound-file directory)
_OLE2_STREAMS = (
    ("WordDocument", "application/msword"),
    ("Workbook", "application/vnd.ms-excel"),
    ("Book", "application/vnd.ms-excel"),
    ("PowerPoint Document", "application/vnd.ms-powerpoint"),
    ("VisioDocument", "application/vnd.visio"),
)

#: how much extra base64 we are willing to decode to reach the OLE2
#: directory sector (the header points at it; legacy Office files keep it
#: in the first few sectors, but it is rarely inside the 3 KB peek)
_OLE2_MAX_BYTES = 256 << 10


def _sniff_ole2(full_b64: str, head: bytes) -> str:
    """Legacy doc/xls/ppt via the compound-file (CFBF/OLE2) directory:
    parse the header's sector size + first-directory-sector pointer, decode
    just enough of the base64 payload to reach that sector, and classify by
    the well-known main-stream names (reference MimeTypeDetector.scala:134
    delegates to Tika's POIFS inspection). Unknown or out-of-reach
    directories keep Tika's x-tika-msoffice catch-all."""
    try:
        if len(head) < 80:
            return "application/x-tika-msoffice"
        sect_shift = int.from_bytes(head[30:32], "little")
        if not 7 <= sect_shift <= 12:
            return "application/x-tika-msoffice"
        ssz = 1 << sect_shift
        dir_sect = int.from_bytes(head[48:52], "little", signed=True)
        if dir_sect < 0:
            return "application/x-tika-msoffice"
        # sector n starts at (n + 1) << sect_shift (header = sector -1)
        dir_off = (dir_sect + 1) << sect_shift
        want = dir_off + ssz
        if want > _OLE2_MAX_BYTES:
            return "application/x-tika-msoffice"
        n_chars = -(-want // 3) * 4 + 4
        buf = _b64.b64decode(full_b64[:n_chars] + "==", validate=False)
        if len(buf) < dir_off + 128:
            return "application/x-tika-msoffice"
        names = []
        for off in range(dir_off, min(dir_off + ssz, len(buf) - 127), 128):
            n_len = int.from_bytes(buf[off + 64:off + 66], "little")
            if not 2 <= n_len <= 64:
                continue
            try:
                names.append(buf[off:off + n_len - 2].decode("utf-16-le"))
            except Exception:
                continue
        for stream, mime in _OLE2_STREAMS:
            if stream in names:
                return mime
    except Exception:
        pass
    return "application/x-tika-msoffice"


def _sniff_gzip(buf: bytes) -> str:
    """Peek inside gzip (Tika reports the compressed stream's type for
    .tar.gz); failures fall back to plain gzip."""
    try:
        import zlib
        inner = zlib.decompressobj(47).decompress(buf, 1024)
        if len(inner) > 262 and inner[257:262] == b"ustar":
            return "application/x-gtar"
    except Exception:
        pass
    return "application/gzip"


class MimeTypeDetector(UnaryTransformer):
    """Base64 → Text MIME type by magic bytes, with container inspection:
    zip-based formats (docx/xlsx/pptx/odt/ods/odp/epub/jar) resolve to
    their specific type via entry-name cues, legacy OLE2 (doc/xls/ppt/vsd)
    via the compound-file directory's main-stream names, gzip peeks for an
    inner tar, and plain tar is detected by the ustar magic at offset 257
    (reference MimeTypeDetector.scala wraps Apache Tika, which recurses
    containers)."""

    def __init__(self, uid=None):
        def fn(v):
            if not v:
                return None
            try:
                buf = _b64.b64decode(str(v)[:_MIME_PEEK_B64] + "==",
                                     validate=False)
            except Exception:
                return None
            head = buf[:24]
            if len(buf) > 262 and buf[257:262] == b"ustar":
                return "application/x-tar"
            for magic, off, mime in _MAGIC:
                if head[off:off + len(magic)] == magic:
                    if mime == "application/zip":
                        return _sniff_zip(buf)
                    if mime == "application/gzip":
                        return _sniff_gzip(buf)
                    if mime == "application/x-tika-msoffice":
                        return _sniff_ole2(str(v), buf[:512])
                    return mime
            if all(32 <= b < 127 or b in (9, 10, 13) for b in head[:16]):
                return "text/plain"
            return "application/octet-stream"
        super().__init__("mimeDetect", transform_fn=fn, output_type=Text,
                         input_type=Base64, uid=uid)


#: minimal per-region phone length table (reference uses libphonenumber; this
#: validates country code + national-number length for common regions)
#: region -> (country code, national significant lengths, trunk prefix):
#: national formats in trunk-prefix countries are written with a leading
#: '0' ('020 7946 0958') that E.164 drops (+44 20 7946 0958)
_PHONE_REGIONS = {
    "US": ("1", 10, ""), "CA": ("1", 10, ""), "GB": ("44", (9, 10), "0"),
    "FR": ("33", 9, "0"), "DE": ("49", (10, 11), "0"),
    "IN": ("91", 10, "0"), "AU": ("61", 9, "0"),
    "JP": ("81", (9, 10), "0"), "BR": ("55", (10, 11), "0"),
    "MX": ("52", 10, ""),
    "IT": ("39", (9, 10), ""), "ES": ("34", 9, ""),
    "NL": ("31", 9, "0"), "SE": ("46", (7, 8, 9), "0"),
    "CH": ("41", 9, "0"), "CN": ("86", (10, 11), "0"),
    "KR": ("82", (8, 9, 10), "0"), "RU": ("7", 10, "8"),
    "ZA": ("27", 9, "0"), "AR": ("54", 10, "0"),
    "SG": ("65", 8, ""), "NZ": ("64", (8, 9), "0"),
    # -- round-4 tranche (libphonenumber national-significant-number
    # lengths; trunk prefix where the national dialing format carries one)
    "AT": ("43", (8, 9, 10, 11, 12, 13), "0"),
    "BE": ("32", (8, 9), "0"), "PT": ("351", 9, ""),
    "DK": ("45", 8, ""), "NO": ("47", 8, ""),
    "FI": ("358", (6, 7, 8, 9, 10, 11), "0"),
    "PL": ("48", 9, ""), "CZ": ("420", 9, ""),
    "SK": ("421", 9, "0"), "HU": ("36", (8, 9), "06"),
    "RO": ("40", 9, "0"), "BG": ("359", (8, 9), "0"),
    "GR": ("30", 10, ""), "IE": ("353", (7, 8, 9), "0"),
    "IL": ("972", (8, 9), "0"), "AE": ("971", (8, 9), "0"),
    "SA": ("966", (8, 9), "0"), "TH": ("66", (8, 9), "0"),
    "MY": ("60", (8, 9, 10), "0"), "PH": ("63", 10, "0"),
    "VN": ("84", (9, 10), "0"), "ID": ("62", (9, 10, 11, 12), "0"),
    "PK": ("92", 10, "0"), "EG": ("20", (8, 9, 10), "0"),
    "NG": ("234", (7, 8, 10), "0"), "KE": ("254", 9, "0"),
    "CL": ("56", 9, ""), "CO": ("57", 10, ""),
    "PE": ("51", (8, 9), "0"), "UA": ("380", 9, "0"),
    "HK": ("852", 8, ""), "TW": ("886", (8, 9), "0"),
    # -- round-5 tranche: toward libphonenumber's ~240 regions.
    # NANP territories (cc 1, 10-digit national numbers, no trunk) — the
    # reference's DefaultCountryCodes is NANP-heavy
    # (PhoneNumberParser.scala:325+)
    "DO": ("1", 10, ""), "PR": ("1", 10, ""), "BS": ("1", 10, ""),
    "BB": ("1", 10, ""), "JM": ("1", 10, ""), "TT": ("1", 10, ""),
    "AI": ("1", 10, ""), "AG": ("1", 10, ""), "VG": ("1", 10, ""),
    "VI": ("1", 10, ""), "KY": ("1", 10, ""), "BM": ("1", 10, ""),
    "GD": ("1", 10, ""), "TC": ("1", 10, ""), "MS": ("1", 10, ""),
    "LC": ("1", 10, ""), "DM": ("1", 10, ""), "VC": ("1", 10, ""),
    "KN": ("1", 10, ""), "GU": ("1", 10, ""),
    # Europe
    "IS": ("354", 7, ""), "LU": ("352", (6, 8, 9), ""),
    "MT": ("356", 8, ""), "CY": ("357", 8, ""), "EE": ("372", (7, 8), ""),
    "HR": ("385", (8, 9), "0"), "SI": ("386", 8, "0"),
    "RS": ("381", (8, 9), "0"), "BA": ("387", 8, "0"),
    "MK": ("389", 8, "0"), "AL": ("355", 9, "0"),
    "LT": ("370", 8, "8"), "LV": ("371", 8, ""),
    "MD": ("373", 8, "0"), "BY": ("375", 9, "8"),
    "ME": ("382", 8, "0"), "MC": ("377", (8, 9), ""),
    "LI": ("423", 7, ""), "AD": ("376", 6, ""),
    # Caucasus / Central Asia
    "GE": ("995", 9, "0"), "AM": ("374", 8, "0"),
    "AZ": ("994", 9, "0"), "KZ": ("7", 10, "8"),
    "UZ": ("998", 9, ""), "KG": ("996", 9, "0"), "TJ": ("992", 9, ""),
    "TM": ("993", 8, "8"), "MN": ("976", 8, ""),
    # South / Southeast Asia
    "BD": ("880", (8, 9, 10), "0"), "LK": ("94", 9, "0"),
    "NP": ("977", (8, 9, 10), "0"), "MM": ("95", (7, 8, 9, 10), "0"),
    "KH": ("855", (8, 9), "0"), "LA": ("856", (8, 9, 10), "0"),
    "BN": ("673", 7, ""), "MO": ("853", 8, ""),
    # Middle East / North Africa
    "JO": ("962", (8, 9), "0"), "LB": ("961", (7, 8), "0"),
    "KW": ("965", 8, ""), "QA": ("974", 8, ""), "BH": ("973", 8, ""),
    "OM": ("968", 8, ""), "IQ": ("964", 10, "0"),
    "IR": ("98", 10, "0"), "SY": ("963", (8, 9), "0"),
    "YE": ("967", (7, 8, 9), "0"),
    "MA": ("212", 9, "0"), "DZ": ("213", (8, 9), "0"),
    "TN": ("216", 8, ""), "LY": ("218", (8, 9), "0"),
    # Sub-Saharan Africa
    "GH": ("233", 9, "0"), "TZ": ("255", 9, "0"), "UG": ("256", 9, "0"),
    "ZM": ("260", 9, "0"), "ZW": ("263", 9, "0"),
    "ET": ("251", 9, "0"), "SN": ("221", 9, ""), "CI": ("225", 10, ""),
    "CM": ("237", 9, ""), "RW": ("250", 9, "0"), "MW": ("265", (7, 9), "0"),
    "MZ": ("258", (8, 9), ""), "BW": ("267", (7, 8), ""),
    "NA": ("264", (8, 9), "0"), "MU": ("230", (7, 8), ""),
    # Latin America
    "EC": ("593", (8, 9), "0"), "UY": ("598", 8, "0"),
    "PY": ("595", (8, 9), "0"), "BO": ("591", 8, "0"),
    "VE": ("58", 10, "0"), "CR": ("506", 8, ""), "PA": ("507", (7, 8), ""),
    "GT": ("502", 8, ""), "HN": ("504", 8, ""), "SV": ("503", 8, ""),
    "NI": ("505", 8, ""), "CU": ("53", 8, "0"),
    # Pacific
    "FJ": ("679", 7, ""), "PG": ("675", (7, 8), ""),
}


#: per-region national-significant-number PATTERNS (libphonenumber
#: isValidNumber analog for the top-traffic regions; the length table above
#: is the isPossibleNumber analog for all 54). Each entry: leading-digit /
#: area-code regexes for fixed-line and mobile numbers, anchored over the
#: NSN after trunk stripping. NANP regions share one fixed-or-mobile plan.
#: Reference: PhoneNumberParser.scala delegates both tiers to
#: libphonenumber's per-region metadata (:259-314).
_NANP = r"[2-9]\d{2}[2-9]\d{6}"
_PHONE_PATTERNS: Dict[str, Dict[str, str]] = {
    # every NANP region shares one numbering plan (area code [2-9]XX +
    # exchange [2-9]XX) — without these entries a strict "+1" lookup would
    # fall through to a pattern-less territory and accept any 10 digits
    **{rg: {"fixed_line_or_mobile": _NANP}
       for rg in ("US", "CA", "DO", "PR", "BS", "BB", "JM", "TT", "AI",
                  "AG", "VG", "VI", "KY", "BM", "GD", "TC", "MS", "LC",
                  "DM", "VC", "KN", "GU")},
    "GB": {"mobile": r"7[1-57-9]\d{8}", "fixed_line": r"[12]\d{8,9}|3\d{9}"},
    "FR": {"mobile": r"[67]\d{8}", "fixed_line": r"[1-59]\d{8}"},
    "DE": {"mobile": r"1[5-7]\d{8,9}", "fixed_line": r"[2-9]\d{7,10}"},
    "IN": {"mobile": r"[6-9]\d{9}", "fixed_line": r"[2-5]\d{9}"},
    "AU": {"mobile": r"4\d{8}", "fixed_line": r"[2378]\d{8}"},
    "JP": {"mobile": r"[789]0\d{8}", "fixed_line": r"[1-9]\d{7,8}"},
    "BR": {"mobile": r"\d{2}9\d{8}", "fixed_line": r"\d{2}[2-5]\d{7}"},
    "MX": {"fixed_line_or_mobile": r"[2-9]\d{9}"},
    "IT": {"mobile": r"3\d{8,9}", "fixed_line": r"0\d{8,9}"},
    "ES": {"mobile": r"[67]\d{8}", "fixed_line": r"[89]\d{8}"},
    "NL": {"mobile": r"6\d{8}", "fixed_line": r"[1-578]\d{8}"},
    "SE": {"mobile": r"7[02369]\d{7}", "fixed_line": r"[1-68]\d{6,8}"},
    "CH": {"mobile": r"7[5-9]\d{7}", "fixed_line": r"[2-6]\d{8}"},
    "CN": {"mobile": r"1[3-9]\d{9}", "fixed_line": r"[2-9]\d{8,9}"},
    "KR": {"mobile": r"1[0-9]\d{7,8}",
           "fixed_line": r"2\d{7,8}|[3-6]\d{8}"},
    "RU": {"mobile": r"9\d{9}", "fixed_line": r"[348]\d{9}"},
    "ZA": {"mobile": r"[67]\d{8}|8[1-4]\d{7}", "fixed_line": r"[1-5]\d{8}"},
    "SG": {"mobile": r"[89]\d{7}", "fixed_line": r"[36]\d{7}"},
    "HK": {"mobile": r"[569]\d{7}", "fixed_line": r"[23]\d{7}"},
    "PL": {"mobile": r"(?:4[5-9]|5[0137]|6[069]|7[2389]|88)\d{7}",
           "fixed_line": r"[1-3]\d{8}"},
}


def _match_pattern(region: str, nsn: str) -> Optional[str]:
    """NSN → number type ('mobile' / 'fixed_line' /
    'fixed_line_or_mobile') per the region's pattern table; None when the
    region has no table or nothing matches."""
    pats = _PHONE_PATTERNS.get(region)
    if not pats:
        return None
    for typ, pat in pats.items():
        if re.fullmatch(pat, nsn):
            return typ
    return None


def _split_nsn(digits: str, region: str,
               spec: Optional[Tuple] = None) -> Optional[str]:
    """Digits (national or cc-prefixed) → the national significant number
    for ``region``, or None when the shape matches neither. ``spec``
    overrides the region lookup (parse_phone passes its already-resolved
    spec so unknown regions keep the documented US-rules fallback)."""
    spec = spec if spec is not None else _PHONE_REGIONS.get(region)
    if spec is None:
        return None
    cc, ln, trunk = spec
    lens = (ln,) if isinstance(ln, int) else tuple(ln)
    if trunk and digits.startswith(trunk) \
            and len(digits) - len(trunk) in lens:
        return digits[len(trunk):]
    if len(digits) in lens:
        return digits
    if digits.startswith(cc) and len(digits) - len(cc) in lens:
        return digits[len(cc):]
    return None


def parse_phone(v: Optional[str], default_region: str = "US",
                strict: bool = False) -> Optional[Tuple[str, bool]]:
    """→ (E.164-ish normalized, is_valid) (reference PhoneNumberParser).

    Two validation tiers mirroring libphonenumber: the default checks
    country code + national-number LENGTH (isPossibleNumber analog, every
    region in _PHONE_REGIONS — 153); ``strict=True`` additionally requires
    the leading-digit /
    area-code pattern of the region's numbering plan when the region is in
    ``_PHONE_PATTERNS`` (isValidNumber analog, 22 regions — regions without
    a pattern table keep length semantics)."""
    if not v:
        return None
    digits = re.sub(r"[^\d+]", "", str(v))
    explicit_cc = digits.startswith("+")
    digits = digits.lstrip("+")
    if not digits:
        return None
    region = default_region.upper()
    cc, ln, trunk = _PHONE_REGIONS.get(region, ("1", 10, ""))
    lens = (ln,) if isinstance(ln, int) else tuple(ln)
    if explicit_cc:
        for rg, (rcc, rln, _tr) in _PHONE_REGIONS.items():
            rlens = (rln,) if isinstance(rln, int) else tuple(rln)
            if digits.startswith(rcc) and len(digits) - len(rcc) in rlens:
                if strict and _PHONE_PATTERNS.get(rg) is not None \
                        and _match_pattern(rg, digits[len(rcc):]) is None:
                    continue
                return ("+" + digits, True)
        return ("+" + digits, False)
    # national format with the region's trunk prefix: strip it for E.164
    nsn = _split_nsn(digits, region, spec=(cc, ln, trunk))
    if nsn is not None:
        ok = (not strict or _PHONE_PATTERNS.get(region) is None
              or _match_pattern(region, nsn) is not None)
        if ok:
            return ("+" + cc + nsn, True)
    return ("+" + digits, False)


def phone_number_type(v: Optional[str], default_region: str = "US"
                      ) -> Optional[str]:
    """Phone → 'mobile' | 'fixed_line' | 'fixed_line_or_mobile' | None
    (libphonenumber PhoneNumberUtil.getNumberType analog for the regions
    with pattern metadata; None = invalid, unknown type, or no table)."""
    if not v:
        return None
    digits = re.sub(r"[^\d+]", "", str(v))
    explicit_cc = digits.startswith("+")
    digits = digits.lstrip("+")
    if not digits:
        return None
    if explicit_cc:
        for rg, (rcc, rln, _tr) in _PHONE_REGIONS.items():
            rlens = (rln,) if isinstance(rln, int) else tuple(rln)
            if digits.startswith(rcc) and len(digits) - len(rcc) in rlens:
                t = _match_pattern(rg, digits[len(rcc):])
                if t is not None:
                    return t
        return None
    region = default_region.upper()
    nsn = _split_nsn(digits, region)
    return _match_pattern(region, nsn) if nsn is not None else None


class PhoneNumberParser(UnaryTransformer):
    """Phone → Phone normalized, invalid → missing (reference
    PhoneNumberParser.scala). ``strict`` requires the region's numbering-
    plan pattern (libphonenumber isValidNumber tier) on top of the length
    check (isPossibleNumber tier)."""

    def __init__(self, default_region: str = "US", strict: bool = False,
                 uid=None):
        def fn(v):
            r = parse_phone(v, default_region, strict=strict)
            return r[0] if r is not None and r[1] else None
        super().__init__("parsePhone", transform_fn=fn, output_type=Phone,
                         input_type=Phone, uid=uid)
        self.default_region = default_region
        self.strict = strict


class IsValidPhoneDefaultCountry(UnaryTransformer):
    """Phone → Binary validity (reference isValidPhoneDefaultCountry)."""

    def __init__(self, default_region: str = "US", strict: bool = False,
                 uid=None):
        def fn(v):
            if v is None:
                return None
            r = parse_phone(v, default_region, strict=strict)
            return bool(r is not None and r[1])
        super().__init__("isValidPhone", transform_fn=fn, output_type=Binary,
                         input_type=Phone, uid=uid)
        self.default_region = default_region
        self.strict = strict


def _bigrams(s: str) -> set:
    s = s.strip().upper()
    return {s[i:i + 2] for i in range(len(s) - 1)} if len(s) > 1 else {s}


def _name_bigrams(table: Dict[str, str]):
    return [(code.upper(), [_bigrams(n) for n in str(names).split(",")])
            for code, names in table.items()]


#: minimum Jaccard similarity for a free-text country-name match — below
#: it, unrelated text shares only incidental bigrams ('Europe' vs 'PERU')
#: and must fall back to the default region
_REGION_SIM_FLOOR = 0.34


def _resolve_region(region_text: Optional[str], default_region: str,
                    name_bigrams=None) -> str:
    """Free-text region → region code (reference
    PhoneNumberParser.validCountryCode :285-305): exact region-code match
    first, then Jaccard bigram similarity against country NAMES (so
    'United States' or 'USA,United States of America' both resolve to US).
    Unlike the reference's unconditional maxBy, matches below
    ``_REGION_SIM_FLOOR`` fall back to the default region — arbitrary text
    must not resolve to whichever country shares one bigram."""
    if not region_text:
        return default_region
    rc = str(region_text).strip().upper()
    if rc in _PHONE_REGIONS:
        return rc
    rc_bi = _bigrams(rc)
    best, best_sim = None, 0.0
    for code, name_sets in (name_bigrams
                            if name_bigrams is not None
                            else _DEFAULT_NAME_BIGRAMS):
        for nb in name_sets:
            inter = len(rc_bi & nb)
            union = len(rc_bi | nb)
            sim = inter / union if union else 0.0
            if sim > best_sim:
                best, best_sim = code, sim
    return best if best is not None and best_sim >= _REGION_SIM_FLOOR \
        else default_region


#: country-name table for free-text region resolution (reference
#: DefaultCountryCodes, PhoneNumberParser.scala:325+ — NANP-heavy there;
#: here one name per supported region)
_DEFAULT_COUNTRY_NAMES: Dict[str, str] = {
    "US": "USA, UNITED STATES OF AMERICA", "CA": "CANADA",
    "GB": "UNITED KINGDOM, GREAT BRITAIN", "FR": "FRANCE",
    "DE": "GERMANY, DEUTSCHLAND", "IN": "INDIA", "AU": "AUSTRALIA",
    "JP": "JAPAN", "BR": "BRAZIL, BRASIL", "MX": "MEXICO", "IT": "ITALY",
    "ES": "SPAIN, ESPANA", "NL": "NETHERLANDS, HOLLAND", "SE": "SWEDEN",
    "CH": "SWITZERLAND", "CN": "CHINA", "KR": "SOUTH KOREA, KOREA",
    "RU": "RUSSIA, RUSSIAN FEDERATION", "ZA": "SOUTH AFRICA",
    "AR": "ARGENTINA", "SG": "SINGAPORE", "NZ": "NEW ZEALAND",
    "AT": "AUSTRIA", "BE": "BELGIUM", "PT": "PORTUGAL", "DK": "DENMARK",
    "NO": "NORWAY", "FI": "FINLAND", "PL": "POLAND",
    "CZ": "CZECH REPUBLIC, CZECHIA", "SK": "SLOVAKIA", "HU": "HUNGARY",
    "RO": "ROMANIA", "BG": "BULGARIA", "GR": "GREECE", "IE": "IRELAND",
    "IL": "ISRAEL", "AE": "UNITED ARAB EMIRATES, UAE",
    "SA": "SAUDI ARABIA", "TH": "THAILAND", "MY": "MALAYSIA",
    "PH": "PHILIPPINES", "VN": "VIETNAM", "ID": "INDONESIA",
    "PK": "PAKISTAN", "EG": "EGYPT", "NG": "NIGERIA", "KE": "KENYA",
    "CL": "CHILE", "CO": "COLOMBIA", "PE": "PERU", "UA": "UKRAINE",
    "HK": "HONG KONG", "TW": "TAIWAN",
}

_DEFAULT_NAME_BIGRAMS = _name_bigrams(_DEFAULT_COUNTRY_NAMES)


class ParsePhoneNumber(BinaryTransformer):
    """(Phone, Text region) → Phone normalized (reference
    ParsePhoneNumber.scala:143): the second input names the region per row
    — a region code or a free-text country name resolved by Jaccard bigram
    similarity. International (+-prefixed) numbers ignore the region."""

    def __init__(self, default_region: str = "US", strict: bool = False,
                 codes_and_countries: Optional[Dict[str, str]] = None,
                 uid=None):
        name_bi = (_name_bigrams(codes_and_countries)
                   if codes_and_countries else None)

        def fn(v, region_text):
            rc = _resolve_region(region_text, default_region, name_bi)
            r = parse_phone(v, rc, strict=strict)
            return r[0] if r is not None and r[1] else None
        super().__init__("parsePhoneCC", transform_fn=fn, output_type=Phone,
                         input_types=(Phone, Text), uid=uid)
        self.default_region = default_region
        self.strict = strict


class IsValidPhoneNumber(BinaryTransformer):
    """(Phone, Text region) → Binary validity (reference
    IsValidPhoneNumber.scala:198)."""

    def __init__(self, default_region: str = "US", strict: bool = False,
                 codes_and_countries: Optional[Dict[str, str]] = None,
                 uid=None):
        name_bi = (_name_bigrams(codes_and_countries)
                   if codes_and_countries else None)

        def fn(v, region_text):
            if v is None:
                return None
            rc = _resolve_region(region_text, default_region, name_bi)
            r = parse_phone(v, rc, strict=strict)
            return bool(r is not None and r[1])
        super().__init__("isValidPhoneCC", transform_fn=fn,
                         output_type=Binary, input_types=(Phone, Text),
                         uid=uid)
        self.default_region = default_region
        self.strict = strict


_EMAIL_RE = re.compile(
    r"^[A-Za-z0-9.!#$%&'*+/=?^_`{|}~-]+@[A-Za-z0-9](?:[A-Za-z0-9-]{0,61}"
    r"[A-Za-z0-9])?(?:\.[A-Za-z0-9](?:[A-Za-z0-9-]{0,61}[A-Za-z0-9])?)+$")


class ValidEmailTransformer(UnaryTransformer):
    """Email → Binary validity (reference ValidEmailTransformer.scala)."""

    def __init__(self, uid=None):
        def fn(v):
            if v is None:
                return None
            return bool(_EMAIL_RE.match(str(v)))
        super().__init__("isValidEmail", transform_fn=fn, output_type=Binary,
                         input_type=Email, uid=uid)


class EmailToPickList(UnaryTransformer):
    """Email → PickList of the domain (reference RichTextFeature
    toEmailDomain)."""

    def __init__(self, uid=None):
        def fn(v):
            if v is None or not _EMAIL_RE.match(str(v)):
                return None
            return str(v).rsplit("@", 1)[1].lower()
        super().__init__("emailDomain", transform_fn=fn, output_type=PickList,
                         input_type=Email, uid=uid)


_URL_RE = re.compile(r"^(https?|ftp)://([^/\s:?#]+)", re.IGNORECASE)


class UrlToDomain(UnaryTransformer):
    """URL → PickList host (reference RichTextFeature toDomain / isValidUrl)."""

    def __init__(self, uid=None):
        def fn(v):
            if v is None:
                return None
            m = _URL_RE.match(str(v))
            return m.group(2).lower() if m else None
        super().__init__("urlDomain", transform_fn=fn, output_type=PickList,
                         input_type=URL, uid=uid)


class IsValidUrl(UnaryTransformer):
    def __init__(self, uid=None):
        def fn(v):
            if v is None:
                return None
            return bool(_URL_RE.match(str(v)))
        super().__init__("isValidUrl", transform_fn=fn, output_type=Binary,
                         input_type=URL, uid=uid)


class EmailToPrefix(UnaryTransformer):
    """Email → Text local part (reference RichTextFeature toEmailPrefix)."""

    def __init__(self, uid=None):
        def fn(v):
            if v is None or not _EMAIL_RE.match(str(v)):
                return None
            return str(v).rsplit("@", 1)[0]
        super().__init__("emailPrefix", transform_fn=fn, output_type=Text,
                         input_type=Email, uid=uid)


class UrlToProtocol(UnaryTransformer):
    """URL → Text protocol (reference RichTextFeature toProtocol)."""

    def __init__(self, uid=None):
        def fn(v):
            if v is None:
                return None
            m = _URL_RE.match(str(v))
            return m.group(1).lower() if m else None
        super().__init__("urlProtocol", transform_fn=fn, output_type=Text,
                         input_type=URL, uid=uid)


class TextToMultiPickList(UnaryTransformer):
    """Text → MultiPickList singleton set (reference RichTextFeature
    toMultiPickList — the text value as a one-element categorical set)."""

    def __init__(self, uid=None):
        from ...types import MultiPickList
        def fn(v):
            return None if v is None else [str(v)]
        super().__init__("toMultiPickList", transform_fn=fn,
                         output_type=MultiPickList, input_type=Text, uid=uid)


class RegexTokenizer(UnaryTransformer):
    """Text → TextList by a regex token pattern (reference RichTextFeature
    tokenizeRegex — Lucene pattern analyzer replaced by re.findall)."""

    def __init__(self, pattern: str = r"\w+", to_lowercase: bool = True,
                 min_token_length: int = 1, uid=None):
        rex = re.compile(pattern)

        def fn(v):
            if v is None:
                return None
            s = str(v).lower() if to_lowercase else str(v)
            # finditer + group(0): full matches even when the user pattern
            # contains capture groups (findall would return group contents)
            return [m.group(0) for m in rex.finditer(s)
                    if len(m.group(0)) >= min_token_length]

        super().__init__("tokenizeRegex", transform_fn=fn,
                         output_type=TextList, input_type=Text, uid=uid)
        self.pattern = pattern
        self.to_lowercase = to_lowercase
        self.min_token_length = min_token_length


class IsValidPhoneMap(UnaryTransformer):
    """PhoneMap → BinaryMap per-key validity (reference
    RichMapFeature.isValidPhoneDefaultCountryMap)."""

    def __init__(self, default_region: str = "US", uid=None):
        from ...types import BinaryMap

        def fn(v):
            if v is None:
                return None
            out = {}
            for k, s in v.items():
                r = parse_phone(s, default_region)
                out[k] = bool(r is not None and r[1])
            return out

        from ...types import PhoneMap
        super().__init__("isValidPhoneMap", transform_fn=fn,
                         output_type=BinaryMap, input_type=PhoneMap, uid=uid)
        self.default_region = default_region


class OpIDF(Estimator):
    """Seq[OPVector term counts] → OPVector tf-idf weights (reference
    RichListFeature.tfidf wraps Spark ml.feature.IDF: idf(t) =
    log((N + 1) / (df_t + 1)), applied multiplicatively)."""

    input_types = (OPVector,)
    output_type = OPVector

    def __init__(self, min_doc_freq: int = 0, uid=None):
        super().__init__("idf", uid)
        self.min_doc_freq = min_doc_freq

    def fit(self, table: FeatureTable) -> Transformer:
        col = table[self.input_features[0].name]
        tf = np.asarray(col.values, np.float64)
        n_docs = tf.shape[0]
        df = (tf > 0).sum(axis=0)
        idf = np.log((n_docs + 1.0) / (df + 1.0))
        idf[df < self.min_doc_freq] = 0.0
        model = OpIDFModel(idf=idf.astype(np.float32))
        model.summary_metadata = {"numDocs": int(n_docs)}
        return self._finalize_model(model)


class OpIDFModel(_VectorModelBase):
    def __init__(self, idf: np.ndarray, uid=None):
        super().__init__("idf", uid)
        self.idf = np.asarray(idf, np.float32)

    def transform_column(self, table: FeatureTable) -> Column:
        col = table[self.input_features[0].name]
        mat = np.asarray(col.values, np.float32) * self.idf[None, :]
        return Column(OPVector, mat, None, dict(col.metadata))

    def transform_fn(self, v):
        if v is None:
            return None
        return (np.asarray(v, np.float32) * self.idf).tolist()
