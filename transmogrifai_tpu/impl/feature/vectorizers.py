"""Feature vectorizers: typed columns → OPVector columns with provenance.

TPU re-design of the reference vectorizer zoo (reference:
core/.../impl/feature/RealVectorizer.scala, IntegralVectorizer.scala,
BinaryVectorizer.scala, OpOneHotVectorizer.scala, SmartTextVectorizer.scala,
OPCollectionHashingVectorizer.scala, TextTokenizer.scala,
VectorsCombiner.scala, TransmogrifierDefaults Transmogrifier.scala:52-90).

Execution split: statistics and string handling (vocab counts, tokenizing,
hashing) run host-side in vectorized numpy — they are string work the TPU
cannot express — and emit dense float32 blocks; everything downstream (models,
stats, scoring) consumes the resulting device arrays. Null semantics match the
reference: mean/mode fill + a tracked null-indicator column per feature.
"""
from __future__ import annotations

import zlib
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...features import Feature
from ...stages.base import Estimator, SequenceTransformer, Transformer, UnaryTransformer
from ...table import Column, FeatureTable
from ...types import (
    Binary, FeatureType, Integral, MultiPickList, OPVector, Real, RealNN, Text,
    TextList,
)
from ...vector_metadata import (
    NULL_INDICATOR, OTHER_INDICATOR, VectorColumnMetadata, VectorMetadata,
)


class TransmogrifierDefaults:
    """Default knobs (reference Transmogrifier.scala:52-90)."""
    TopK = 20
    MinSupport = 10
    FillValue = 0.0
    BinaryFillValue = False
    NumHashes = 512
    MaxNumOfFeatures = 16384
    MaxCardinality = 30          # SmartTextVectorizer pivot-vs-hash cutoff
    MinTokenLength = 1
    TrackNulls = True
    FillWithMean = True
    FillWithMode = True


def _meta_cols(feature: Feature, names_vals: Sequence[Tuple[Optional[str], Optional[str]]]
               ) -> List[VectorColumnMetadata]:
    return [VectorColumnMetadata(
        parent_feature_name=feature.name,
        parent_feature_type=feature.type_name,
        grouping=grouping, indicator_value=indicator)
        for grouping, indicator in names_vals]


class _VectorModelBase(Transformer):
    """Shared: produce an OPVector Column with attached VectorMetadata."""

    output_type = OPVector

    def _emit(self, mat: np.ndarray, meta_cols: List[VectorColumnMetadata]) -> Column:
        vm = VectorMetadata.of(self.get_output().name, meta_cols)
        return Column(OPVector, np.ascontiguousarray(mat, dtype=np.float32),
                      None, {"vector_meta": vm})

    def transform_row(self, row: Dict[str, Any]) -> Any:
        one = FeatureTable(
            {f.name: Column.of_values(f.feature_type, [row.get(f.name)])
             for f in self.input_features}, 1)
        return np.asarray(self.transform_column(one).values)[0].tolist()


# ---------------------------------------------------------------------------
# Numeric vectorizers
# ---------------------------------------------------------------------------

class RealVectorizer(Estimator):
    """Seq[Real] → OPVector: mean-fill + null indicators (reference
    RealVectorizer.scala:121 — fills with mean, tracks nulls)."""

    output_type = OPVector

    def __init__(self, fill_with_mean: bool = TransmogrifierDefaults.FillWithMean,
                 fill_value: float = TransmogrifierDefaults.FillValue,
                 track_nulls: bool = TransmogrifierDefaults.TrackNulls, uid=None):
        super().__init__("vecReal", uid)
        self.fill_with_mean = fill_with_mean
        self.fill_value = fill_value
        self.track_nulls = track_nulls
        self.mesh = None

    def set_mesh(self, mesh) -> "RealVectorizer":
        """Compute the mean fills over rows sharded on the mesh's 'data'
        axis (reference: per-partition aggregation of the fill statistics,
        SURVEY §2.10 P1)."""
        self.mesh = mesh
        return self

    def fit(self, table: FeatureTable) -> Transformer:
        mesh = getattr(self, "mesh", None)
        if self.fill_with_mean and mesh is not None and self.input_features:
            from ...parallel.sharded import sharded_col_stats
            cols = [table[f.name] for f in self.input_features]
            mask = np.stack([c.valid_mask() for c in cols], axis=1)
            vals64 = [np.asarray(c.values, dtype=np.float64).reshape(-1)
                      for c in cols]
            # anchor each column at a coarse host mean so the f32 device
            # reduction works on deviations (error ~ eps·std, matching the
            # f64 host path's fills to float precision even for columns with
            # mean >> std); invalid slots are zeroed, inf still propagates.
            # STRIDED sample — a head sample would misanchor sorted/trending
            # columns (ids, timestamps)
            def _anchor(v, m):
                mv = v[m]
                if not len(mv):
                    return 0.0
                return mv[::max(1, len(mv) // 1024)][:1024].mean()
            anchors = np.array(
                [_anchor(v, mask[:, i]) for i, v in enumerate(vals64)])
            X = np.stack(
                [np.where(mask[:, i], v - anchors[i], 0.0)
                 for i, v in enumerate(vals64)], axis=1).astype(np.float32)
            st = sharded_col_stats(X, mask, mesh)
            cnt = np.asarray(st.count)
            mean = np.asarray(st.mean)
            fills = [float(anchors[i] + mean[i]) if cnt[i] > 0
                     else self.fill_value for i in range(len(cols))]
        else:
            fills = []
            for f in self.input_features:
                col = table[f.name]
                vals = np.asarray(col.values, dtype=np.float64)
                m = col.valid_mask()
                if self.fill_with_mean:
                    fills.append(float(vals[m].mean()) if m.any()
                                 else self.fill_value)
                else:
                    fills.append(self.fill_value)
        model = RealVectorizerModel(fills=fills, track_nulls=self.track_nulls)
        return self._finalize_model(model)

    # -- streaming fit (OpWorkflow.train(stream=...), docs/streaming.md) -----
    def fit_streaming_prep(self, run):
        """Single-pass prep spec ``(pass_id, fold, extract, finish)`` —
        the trainer fuses independent specs from one DAG layer into one
        chunk sweep (streaming/trainer.py). ``None`` when constant fills
        need no pass at all."""
        if not self.fill_with_mean:
            return None
        from ...streaming.folds import ColStatsFold
        k = len(self.input_features)
        fold = ColStatsFold(k)

        def extract(table):
            cols = [table[f.name] for f in self.input_features]
            X = np.stack([np.asarray(c.values, dtype=np.float64).reshape(-1)
                          for c in cols], axis=1)
            mask = np.stack([c.valid_mask() for c in cols], axis=1)
            return X, mask

        def finish(state) -> Transformer:
            res = fold.finalize(state)
            fills = [float(res.mean[i]) if res.count[i] > 0
                     else self.fill_value for i in range(k)]
            model = RealVectorizerModel(fills=fills,
                                        track_nulls=self.track_nulls)
            return self._finalize_model(model)

        return "fills", fold, extract, finish

    def fit_streaming(self, run) -> Transformer:
        """Mean fills as one chunked col-stats fold: per-column (count, Σx)
        accumulate in exact f64 exactly like the in-core f64 host path, so
        the streamed fills agree with in-core fills to the last float
        rounding of the identical sum/count division."""
        spec = self.fit_streaming_prep(run)
        if spec is None:
            model = RealVectorizerModel(
                fills=[self.fill_value] * len(self.input_features),
                track_nulls=self.track_nulls)
            return self._finalize_model(model)
        pass_id, fold, extract, finish = spec
        return finish(run.fold(pass_id, fold, extract))


def _device_fill_blocks(input_features, fills, track_nulls, env):
    """Shared pure-jax fill+null-track dual used by the fused serve program
    (local/scoring.compiled_score_function): env maps input feature name →
    (values, mask-or-None) jnp arrays; ``fills`` yields one fill per input."""
    import jax.numpy as jnp
    blocks = []
    for f, fill in zip(input_features, fills):
        vals, mask = env[f.name]
        vals = vals.reshape(-1).astype(jnp.float32)
        m = jnp.ones(vals.shape, bool) if mask is None else mask
        blocks.append(jnp.where(m, vals, jnp.float32(fill)))
        if track_nulls:
            blocks.append((~m).astype(jnp.float32))
    return jnp.stack(blocks, axis=1), None


class RealVectorizerModel(_VectorModelBase):
    def __init__(self, fills: List[float], track_nulls: bool, uid=None):
        super().__init__("vecReal", uid)
        self.fills = fills
        self.track_nulls = track_nulls

    def device_columnar(self, env):
        return _device_fill_blocks(self.input_features, self.fills,
                                   self.track_nulls, env)

    def transform_column(self, table: FeatureTable) -> Column:
        blocks, meta = [], []
        for f, fill in zip(self.input_features, self.fills):
            col = table[f.name]
            vals = np.asarray(col.values, dtype=np.float32).reshape(-1)
            m = col.valid_mask()
            filled = np.where(m, vals, np.float32(fill))
            blocks.append(filled)
            meta.extend(_meta_cols(f, [(f.name, None)]))
            if self.track_nulls:
                blocks.append((~m).astype(np.float32))
                meta.extend(_meta_cols(f, [(f.name, NULL_INDICATOR)]))
        return self._emit(np.stack(blocks, axis=1), meta)


class IntegralVectorizer(Estimator):
    """Seq[Integral] → OPVector: mode-fill + null indicators (reference
    IntegralVectorizer.scala — fills with mode)."""

    output_type = OPVector

    def __init__(self, fill_with_mode: bool = TransmogrifierDefaults.FillWithMode,
                 fill_value: int = 0,
                 track_nulls: bool = TransmogrifierDefaults.TrackNulls, uid=None):
        super().__init__("vecIntegral", uid)
        self.fill_with_mode = fill_with_mode
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def fit(self, table: FeatureTable) -> Transformer:
        fills = []
        for f in self.input_features:
            col = table[f.name]
            vals = np.asarray(col.values).reshape(-1)
            m = col.valid_mask()
            if self.fill_with_mode and m.any():
                vv, cc = np.unique(vals[m], return_counts=True)
                # ties → smallest value (deterministic, matches modeFn min)
                fills.append(float(vv[np.argmax(cc)]))
            else:
                fills.append(float(self.fill_value))
        model = RealVectorizerModel(fills=fills, track_nulls=self.track_nulls)
        model.operation_name = "vecIntegral"
        return self._finalize_model(model)


class BinaryVectorizer(SequenceTransformer):
    """Seq[Binary] → OPVector: false-fill + null indicator (reference
    BinaryVectorizer.scala)."""

    output_type = OPVector

    def __init__(self, fill_value: bool = TransmogrifierDefaults.BinaryFillValue,
                 track_nulls: bool = TransmogrifierDefaults.TrackNulls, uid=None):
        super().__init__("vecBinary", transform_fn=None, output_type=OPVector, uid=uid)
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def device_columnar(self, env):
        fill = float(self.fill_value)
        return _device_fill_blocks(
            self.input_features, (fill for _ in self.input_features),
            self.track_nulls, env)

    def transform_column(self, table: FeatureTable) -> Column:
        blocks, meta = [], []
        for f in self.input_features:
            col = table[f.name]
            vals = np.asarray(col.values, dtype=np.float32).reshape(-1)
            m = col.valid_mask()
            blocks.append(np.where(m, vals, np.float32(float(self.fill_value))))
            meta.extend(_meta_cols(f, [(f.name, None)]))
            if self.track_nulls:
                blocks.append((~m).astype(np.float32))
                meta.extend(_meta_cols(f, [(f.name, NULL_INDICATOR)]))
        vm = VectorMetadata.of(self.get_output().name, meta)
        return Column(OPVector, np.stack(blocks, axis=1).astype(np.float32),
                      None, {"vector_meta": vm})

    def transform_row(self, row: Dict[str, Any]) -> Any:
        one = FeatureTable(
            {f.name: Column.of_values(f.feature_type, [row.get(f.name)])
             for f in self.input_features}, 1)
        return np.asarray(self.transform_column(one).values)[0].tolist()


class RealNNVectorizer(SequenceTransformer):
    """Seq[RealNN] → OPVector passthrough concat (reference RealNNVectorizer)."""

    output_type = OPVector

    def __init__(self, uid=None):
        super().__init__("vecRealNN", transform_fn=None, output_type=OPVector, uid=uid)

    def device_columnar(self, env):
        """Pure-jax dual for the fused serve program (see RealVectorizerModel)."""
        import jax.numpy as jnp
        return jnp.stack(
            [env[f.name][0].reshape(-1).astype(jnp.float32)
             for f in self.input_features], axis=1), None

    def transform_column(self, table: FeatureTable) -> Column:
        blocks, meta = [], []
        for f in self.input_features:
            col = table[f.name]
            blocks.append(np.asarray(col.values, dtype=np.float32).reshape(-1))
            meta.append(VectorColumnMetadata(f.name, f.type_name, f.name, None))
        vm = VectorMetadata.of(self.get_output().name, meta)
        return Column(OPVector, np.stack(blocks, axis=1), None, {"vector_meta": vm})

    def transform_row(self, row: Dict[str, Any]) -> Any:
        return [float(row.get(f.name) or 0.0) for f in self.input_features]


# ---------------------------------------------------------------------------
# Categorical pivot (one-hot) vectorizer
# ---------------------------------------------------------------------------

class OneHotVectorizer(Estimator):
    """Seq[Text-ish] → OPVector: top-K pivot with OTHER + null indicator
    (reference OpOneHotVectorizer.scala / OpTextPivotVectorizer — TopK by
    count with MinSupport, OTHER column, null-indicator column)."""

    output_type = OPVector

    def __init__(self, top_k: int = TransmogrifierDefaults.TopK,
                 min_support: int = TransmogrifierDefaults.MinSupport,
                 track_nulls: bool = TransmogrifierDefaults.TrackNulls, uid=None):
        super().__init__("pivot", uid)
        self.top_k = top_k
        self.min_support = min_support
        self.track_nulls = track_nulls

    def fit(self, table: FeatureTable) -> Transformer:
        vocabs: List[List[str]] = []
        for f in self.input_features:
            col = table[f.name]
            vals = np.asarray(col.values)
            m = col.valid_mask()
            if col.kind == "multipicklist":
                cnt = Counter(v for vs, ok in zip(vals, m) if ok for v in (vs or ()))
            else:
                cnt = Counter(str(v) for v, ok in zip(vals, m) if ok)
            top = [v for v, c in cnt.most_common() if c >= self.min_support]
            # deterministic: count desc then value asc
            top = sorted(top, key=lambda v: (-cnt[v], v))[: self.top_k]
            vocabs.append(top)
        model = OneHotVectorizerModel(vocabs=vocabs, track_nulls=self.track_nulls)
        return self._finalize_model(model)


class OneHotVectorizerModel(_VectorModelBase):
    def __init__(self, vocabs: List[List[str]], track_nulls: bool, uid=None):
        super().__init__("pivot", uid)
        self.vocabs = vocabs
        self.track_nulls = track_nulls

    def transform_column(self, table: FeatureTable) -> Column:
        n = table.num_rows
        blocks, meta = [], []
        for f, vocab in zip(self.input_features, self.vocabs):
            col = table[f.name]
            vals = np.asarray(col.values)
            m = col.valid_mask()
            k = len(vocab)
            block = np.zeros((n, k + 1 + (1 if self.track_nulls else 0)),
                             dtype=np.float32)
            index = {v: i for i, v in enumerate(vocab)}
            if col.kind == "multipicklist":
                for i, (vs, ok) in enumerate(zip(vals, m)):
                    if not ok:
                        continue
                    for v in (vs or ()):
                        j = index.get(v)
                        if j is None:
                            block[i, k] = 1.0
                        else:
                            block[i, j] = 1.0
            else:
                codes = np.full(n, -2, dtype=np.int64)  # -2 null, -1 OTHER
                svals = np.array([str(v) if ok else "" for v, ok in zip(vals, m)],
                                 dtype=object)
                for i_ok in np.nonzero(m)[0]:
                    codes[i_ok] = index.get(svals[i_ok], -1)
                rows = np.arange(n)
                hit = codes >= 0
                block[rows[hit], codes[hit]] = 1.0
                block[rows[codes == -1], k] = 1.0
            if self.track_nulls:
                block[~m, k + 1] = 1.0
            blocks.append(block)
            mc = [(f.name, v) for v in vocab] + [(f.name, OTHER_INDICATOR)]
            if self.track_nulls:
                mc.append((f.name, NULL_INDICATOR))
            meta.extend(_meta_cols(f, mc))
        return self._emit(np.concatenate(blocks, axis=1), meta)


# ---------------------------------------------------------------------------
# Text: tokenizer, hashing, smart vectorizer
# ---------------------------------------------------------------------------

_TOKEN_SPLIT = None


def tokenize_text(s: Optional[str], min_token_length: int = 1) -> List[str]:
    """Lowercase, split on non-alphanumeric (reference TextTokenizer.scala —
    Lucene analyzer approximated host-side; language detection is a later
    stage)."""
    global _TOKEN_SPLIT
    if s is None:
        return []
    if _TOKEN_SPLIT is None:
        import re
        _TOKEN_SPLIT = re.compile(r"[^\w]+", re.UNICODE)
    return [t for t in _TOKEN_SPLIT.split(s.lower()) if len(t) >= min_token_length]


def porter_stem(w: str) -> str:
    """Compact Porter-style English stemmer (the high-coverage rules of
    steps 1-2: plurals, -ed/-ing, common suffixes — the analog of the
    reference's Lucene per-language analyzers with stemming,
    LuceneTextAnalyzer.scala:203; full Porter fidelity is not the goal,
    stable feature collisions for inflected forms are)."""
    if len(w) <= 3:
        return w
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("s") and not w.endswith("ss") and len(w) > 3:
        w = w[:-1]
    for suf, rep in (("ational", "ate"), ("ization", "ize"),
                     ("fulness", "ful"), ("ousness", "ous"),
                     ("iveness", "ive"), ("tional", "tion"),
                     ("biliti", "ble"), ("entli", "ent"),
                     ("ation", "ate"), ("alism", "al"), ("aliti", "al"),
                     ("ness", ""), ("ment", "")):
        if w.endswith(suf) and len(w) - len(suf) >= 3:
            return w[: len(w) - len(suf)] + rep
    if w.endswith("ing") and len(w) > 5:
        w = w[:-3]
        if len(w) >= 3 and w[-1] == w[-2] and w[-1] not in "lsz":
            w = w[:-1]  # running -> run
        return w
    if w.endswith("ed") and len(w) > 4:
        w = w[:-2]
        if len(w) >= 3 and w[-1] == w[-2] and w[-1] not in "lsz":
            w = w[:-1]
        return w
    if w.endswith("ly") and len(w) > 4:
        return w[:-2]
    return w


def _strip_suffixes(w: str, suffixes, min_stem: int = 3) -> str:
    """Longest-match suffix strip with a minimum stem length — the shared
    skeleton of the light per-language stemmers below."""
    for suf, rep in suffixes:
        if w.endswith(suf) and len(w) - len(suf) >= min_stem:
            return w[: len(w) - len(suf)] + rep
    return w


#: light Snowball-style suffix strippers (reference: Lucene ships full
#: per-language Snowball analyzers, LuceneTextAnalyzer.scala:203; as with
#: porter_stem the goal is stable feature collisions for inflected forms,
#: not linguistic fidelity). Ordered longest-first so the longest suffix
#: wins.
_FR_SUFFIXES = [
    ("issements", ""), ("issement", ""), ("atrices", "ateur"),
    ("ateurs", "ateur"), ("ations", "ation"), ("logies", "logie"),
    ("ements", ""), ("amment", ""), ("emment", ""), ("ances", "ance"),
    ("ables", "able"), ("istes", "iste"), ("euses", "eux"),
    ("ments", "ment"), ("ation", "ation"), ("ance", "ance"),
    ("able", "able"), ("iste", "iste"), ("euse", "eux"), ("ités", "ité"),
    ("ement", ""), ("ives", "if"), ("ive", "if"), ("eaux", "eau"),
    ("aux", "al"), ("ité", "ité"), ("er", ""), ("es", ""), ("s", ""),
    ("e", ""),
]
_DE_SUFFIXES = [
    ("ungen", "ung"), ("heiten", "heit"), ("keiten", "keit"),
    ("lichen", "lich"), ("ischen", "isch"), ("erinnen", "er"),
    ("ern", ""), ("ung", "ung"), ("heit", "heit"),
    ("keit", "keit"), ("lich", "lich"), ("isch", "isch"), ("en", ""),
    ("er", ""), ("es", ""), ("em", ""), ("e", ""), ("s", ""), ("n", ""),
]
_ES_SUFFIXES = [
    ("amientos", ""), ("imientos", ""), ("aciones", "ación"),
    ("amiento", ""), ("imiento", ""), ("adoras", "ador"),
    ("adores", "ador"), ("ancias", "ancia"), ("idades", "idad"),
    ("encias", "encia"), ("amente", ""), ("mente", ""), ("ación", "ación"),
    ("adora", "ador"), ("ancia", "ancia"), ("encia", "encia"),
    ("idad", "idad"), ("istas", "ista"), ("ista", "ista"),
    ("ables", "able"), ("ibles", "ible"), ("able", "able"),
    ("ible", "ible"), ("osos", "oso"), ("osas", "oso"), ("osa", "oso"),
    ("oso", "oso"), ("es", ""), ("as", "a"), ("os", "o"), ("s", ""),
]


_IT_SUFFIXES = [
    ("azioni", "azione"), ("amenti", ""), ("imenti", ""),
    ("amento", ""), ("imento", ""), ("azione", "azione"),
    ("atrici", "atore"), ("atrice", "atore"), ("atori", "atore"),
    ("atore", "atore"), ("abili", "abile"), ("ibili", "ibile"),
    ("abile", "abile"), ("ibile", "ibile"), ("mente", ""),
    ("ista", "ista"), ("isti", "ista"), ("iste", "ista"),
    ("anza", "anza"), ("anze", "anza"), ("ità", "ità"),
    ("osi", "oso"), ("ose", "oso"), ("osa", "oso"), ("oso", "oso"),
    ("are", ""), ("ere", ""), ("ire", ""), ("ato", ""), ("ata", ""),
    ("ati", ""), ("ate", ""), ("i", ""), ("e", ""), ("a", ""), ("o", ""),
]
_PT_SUFFIXES = [
    ("amentos", ""), ("imentos", ""), ("adoras", "ador"),
    ("adores", "ador"), ("amento", ""), ("imento", ""),
    ("ações", "ação"), ("idades", "idade"), ("amente", ""),
    ("mente", ""), ("adora", "ador"), ("ação", "ação"),
    ("antes", "ante"), ("ância", "ância"), ("idade", "idade"),
    ("ismos", "ismo"), ("istas", "ista"), ("ismo", "ismo"),
    ("ista", "ista"), ("osos", "oso"), ("osas", "oso"), ("osa", "oso"),
    ("oso", "oso"), ("ivas", "ivo"), ("ivos", "ivo"), ("iva", "ivo"),
    ("ivo", "ivo"), ("ões", "ão"), ("ar", ""), ("er", ""), ("ir", ""),
    ("es", ""), ("as", "a"), ("os", "o"), ("s", ""),
]
_NL_SUFFIXES = [
    ("heden", "heid"), ("elijke", "elijk"), ("elijk", "elijk"),
    ("ingen", "ing"), ("aren", "aar"), ("eren", ""), ("ende", ""),
    ("tjes", ""), ("ing", "ing"), ("aar", "aar"), ("end", ""),
    ("ster", ""), ("je", ""), ("en", ""), ("er", ""), ("es", ""),
    ("s", ""), ("e", ""),
]
#: Russian: strip reflexive particle first, then the longest
#: verb/adjective/noun ending (RSLP-style ordering, Cyrillic)
_RU_REFLEXIVE = ("ся", "сь")
_RU_SUFFIXES = [
    ("ировать", ""), ("ованный", ""), ("ейший", ""),
    ("ениями", "ение"), ("ениях", "ение"),
    ("ениям", "ение"), ("ением", "ение"), ("ости", "ость"),
    ("остью", "ость"), ("ение", "ение"), ("ения", "ение"),
    ("ении", "ение"), ("ством", "ство"), ("ство", "ство"),
    ("ывать", ""), ("ивать", ""), ("овать", ""), ("аться", ""),
    ("иться", ""), ("ешься", ""), ("ется", ""), ("ители", "итель"),
    ("итель", "итель"), ("ами", ""), ("ями", ""), ("ого", ""),
    ("его", ""), ("ому", ""), ("ему", ""), ("ыми", ""), ("ими", ""),
    ("ая", ""), ("яя", ""), ("ой", ""), ("ый", ""), ("ий", ""),
    ("ем", ""), ("им", ""), ("ом", ""), ("ах", ""), ("ях", ""),
    ("ует", ""), ("ешь", ""), ("ете", ""), ("ает", ""), ("яет", ""),
    ("ить", ""), ("ать", ""),
    ("ять", ""), ("еть", ""), ("ал", ""), ("ил", ""), ("ыл", ""),
    ("ла", ""), ("ло", ""), ("ли", ""), ("ов", ""), ("ев", ""),
    ("ей", ""), ("ам", ""), ("ям", ""), ("ы", ""), ("и", ""),
    ("а", ""), ("я", ""), ("о", ""), ("е", ""), ("у", ""), ("ю", ""),
    ("ь", ""),
]


def french_stem(w: str) -> str:
    return _strip_suffixes(w, _FR_SUFFIXES) if len(w) > 4 else w


def german_stem(w: str) -> str:
    return _strip_suffixes(w, _DE_SUFFIXES, min_stem=4) if len(w) > 4 else w


def spanish_stem(w: str) -> str:
    return _strip_suffixes(w, _ES_SUFFIXES) if len(w) > 4 else w


def italian_stem(w: str) -> str:
    return _strip_suffixes(w, _IT_SUFFIXES) if len(w) > 4 else w


def portuguese_stem(w: str) -> str:
    return _strip_suffixes(w, _PT_SUFFIXES) if len(w) > 4 else w


def dutch_stem(w: str) -> str:
    return _strip_suffixes(w, _NL_SUFFIXES, min_stem=4) if len(w) > 4 else w


def russian_stem(w: str) -> str:
    if len(w) <= 4:
        return w
    for r in _RU_REFLEXIVE:
        if w.endswith(r) and len(w) - len(r) >= 3:
            w = w[: len(w) - len(r)]
            break
    return _strip_suffixes(w, _RU_SUFFIXES)


#: Scandinavian: sv/no/da share the -en/-et/-er/-ene noun machinery
_SV_SUFFIXES = [
    ("heterna", "het"), ("heten", "het"), ("heter", "het"),
    ("arna", ""), ("erna", ""), ("orna", ""), ("ande", ""), ("ende", ""),
    ("aste", ""), ("arne", ""), ("aren", ""), ("ades", ""), ("are", ""),
    ("ade", ""), ("at", ""), ("ad", ""), ("en", ""), ("ar", ""),
    ("er", ""), ("or", ""), ("et", ""), ("a", ""), ("e", ""), ("s", ""),
]
_NO_DA_SUFFIXES = [
    ("hetene", "het"), ("heten", "het"), ("heter", "het"),
    ("erne", ""), ("ende", ""), ("ene", ""), ("ane", ""), ("else", ""),
    ("ere", ""), ("est", ""), ("et", ""), ("en", ""), ("er", ""),
    ("ar", ""), ("te", ""), ("e", ""), ("s", ""),
]
#: Finnish: strip possessives then the most common case endings (a real
#: Snowball Finnish is far deeper; goal is stable collisions)
_FI_SUFFIXES = [
    ("issaan", ""), ("issään", ""), ("llaan", ""), ("llään", ""),
    ("ssaan", ""), ("ssään", ""), ("iensa", ""), ("iensä", ""),
    ("isiin", ""), ("ista", ""), ("istä", ""), ("ille", ""),
    ("illa", ""), ("illä", ""), ("issa", ""), ("issä", ""),
    ("lla", ""), ("llä", ""), ("ssa", ""), ("ssä", ""), ("sta", ""),
    ("stä", ""), ("lle", ""), ("lta", ""), ("ltä", ""), ("ksi", ""),
    ("tta", ""), ("ttä", ""), ("ien", ""), ("in", ""), ("it", ""),
    ("et", ""), ("at", ""), ("ät", ""), ("na", ""), ("nä", ""),
    ("a", ""), ("ä", ""), ("n", ""), ("t", ""),
]
#: Hungarian: case endings + plural
_HU_SUFFIXES = [
    ("jainak", ""), ("einek", ""), ("oknak", ""), ("eknek", ""),
    ("ságok", "ság"), ("ségek", "ség"), ("ság", "ság"), ("ség", "ség"),
    ("okat", ""), ("eket", ""), ("akat", ""), ("ban", ""), ("ben", ""),
    ("nak", ""), ("nek", ""), ("val", ""), ("vel", ""), ("ból", ""),
    ("ből", ""), ("hoz", ""), ("hez", ""), ("ról", ""), ("ről", ""),
    ("ok", ""), ("ek", ""), ("ak", ""), ("ot", ""), ("et", ""),
    ("at", ""), ("on", ""), ("en", ""), ("án", ""), ("én", ""),
    ("t", ""), ("k", ""),
]
#: Turkish: agglutinative chain simplified to the outermost layers
_TR_SUFFIXES = [
    ("larından", ""), ("lerinden", ""), ("larında", ""), ("lerinde", ""),
    ("larini", ""), ("lerini", ""), ("larına", ""), ("lerine", ""),
    ("ların", ""), ("lerin", ""), ("ları", ""), ("leri", ""),
    ("lardan", ""), ("lerden", ""), ("larda", ""), ("lerde", ""),
    ("lara", ""), ("lere", ""), ("lar", ""), ("ler", ""),
    ("ında", ""), ("inde", ""), ("undan", ""), ("ünden", ""),
    ("dan", ""), ("den", ""), ("tan", ""), ("ten", ""),
    ("da", ""), ("de", ""), ("ta", ""), ("te", ""),
    ("ın", ""), ("in", ""), ("un", ""), ("ün", ""),
    ("ı", ""), ("i", ""), ("u", ""), ("ü", ""), ("a", ""), ("e", ""),
]
#: Polish: declension + common verb endings
_PL_SUFFIXES = [
    ("owaniach", ""), ("owania", ""), ("owanie", ""), ("ościach", "ość"),
    ("ościami", "ość"), ("ości", "ość"), ("ość", "ość"),
    ("owych", "owy"), ("owymi", "owy"), ("owej", "owy"), ("owego", "owy"),
    ("owy", "owy"), ("owa", "owy"), ("owe", "owy"),
    ("ach", ""), ("ami", ""), ("iem", ""), ("em", ""), ("om", ""),
    ("ów", ""), ("ej", ""), ("ego", ""), ("emu", ""), ("ymi", ""),
    ("ych", ""), ("ą", ""), ("ę", ""), ("y", ""), ("i", ""), ("e", ""),
    ("a", ""), ("o", ""), ("u", ""),
]
#: Romanian: articles + plural/case
_RO_SUFFIXES = [
    ("iilor", ""), ("ilor", ""), ("ului", ""), ("elor", ""),
    ("ările", "are"), ("area", "are"), ("erea", "ere"), ("irea", "ire"),
    ("ări", "are"), ("uri", ""), ("ele", ""), ("ea", ""), ("ul", ""),
    ("ii", ""), ("le", ""), ("lui", ""), ("ă", ""), ("a", ""),
    ("e", ""), ("i", ""), ("u", ""),
]
#: Czech: declension
_CS_SUFFIXES = [
    ("ováním", "ování"), ("ování", "ování"), ("ostech", "ost"),
    ("ostem", "ost"), ("ostí", "ost"), ("osti", "ost"), ("ost", "ost"),
    ("ého", ""), ("ému", ""), ("ými", ""), ("ých", ""), ("ami", ""),
    ("emi", ""), ("ech", ""), ("ích", ""), ("ům", ""), ("em", ""),
    ("ou", ""), ("y", ""), ("i", ""), ("e", ""), ("é", ""),
    ("á", ""), ("í", ""), ("ý", ""), ("a", ""), ("o", ""), ("u", ""),
]


def swedish_stem(w: str) -> str:
    return _strip_suffixes(w, _SV_SUFFIXES) if len(w) > 4 else w


def norwegian_stem(w: str) -> str:
    return _strip_suffixes(w, _NO_DA_SUFFIXES) if len(w) > 4 else w


def danish_stem(w: str) -> str:
    return _strip_suffixes(w, _NO_DA_SUFFIXES) if len(w) > 4 else w


def finnish_stem(w: str) -> str:
    return _strip_suffixes(w, _FI_SUFFIXES) if len(w) > 5 else w


def hungarian_stem(w: str) -> str:
    return _strip_suffixes(w, _HU_SUFFIXES) if len(w) > 4 else w


def turkish_stem(w: str) -> str:
    if len(w) <= 4:
        return w
    # peel at most two agglutinated layers
    w1 = _strip_suffixes(w, _TR_SUFFIXES)
    return _strip_suffixes(w1, _TR_SUFFIXES) if len(w1) > 5 else w1


def polish_stem(w: str) -> str:
    return _strip_suffixes(w, _PL_SUFFIXES) if len(w) > 3 else w


def romanian_stem(w: str) -> str:
    return _strip_suffixes(w, _RO_SUFFIXES) if len(w) > 4 else w


def czech_stem(w: str) -> str:
    return _strip_suffixes(w, _CS_SUFFIXES) if len(w) > 4 else w


#: language → stemmer for TextTokenizer(stemming=True, language=...)
#: (reference: Lucene ships ~30 per-language Snowball analyzers,
#: LuceneTextAnalyzer.scala:203 — 17 light analogs here)
STEMMERS = {"en": porter_stem, "fr": french_stem, "de": german_stem,
            "es": spanish_stem, "it": italian_stem, "pt": portuguese_stem,
            "nl": dutch_stem, "ru": russian_stem,
            "sv": swedish_stem, "no": norwegian_stem, "da": danish_stem,
            "fi": finnish_stem, "hu": hungarian_stem, "tr": turkish_stem,
            "pl": polish_stem, "ro": romanian_stem, "cs": czech_stem}


class TextTokenizer(UnaryTransformer):
    """Text → TextList (reference TextTokenizer.scala:196). ``stemming``
    applies the ``language``'s stemmer to every token (reference Lucene
    analyzers stem per-language, LuceneTextAnalyzer.scala:203; en/fr/de/es
    here — other languages pass through untouched)."""

    def __init__(self, min_token_length: int = TransmogrifierDefaults.MinTokenLength,
                 stemming: bool = False, language: str = "en", uid=None):
        stem = STEMMERS.get(language, lambda t: t)

        def fn(v):
            toks = tokenize_text(v, min_token_length)
            return [stem(t) for t in toks] if stemming else toks
        super().__init__(
            "tokenize", transform_fn=fn,
            output_type=TextList, input_type=Text, uid=uid)
        self.min_token_length = min_token_length
        self.stemming = stemming
        self.language = language


def _hash_token(tok: str, num_hashes: int) -> int:
    """Stable token → bin (crc32; the reference uses MurmurHash3 via Spark's
    HashingTF — any stable uniform hash serves)."""
    return zlib.crc32(tok.encode("utf-8")) % num_hashes


def tokenize_hash_texts(docs: Sequence[Optional[str]], num_hashes: int,
                        min_token_length: int = 1,
                        binary: bool = False) -> np.ndarray:
    """Fused tokenize + hashing-trick counts for a document batch: the
    native C kernel handles ASCII docs (native/text_ops.cpp), the
    Unicode-aware Python tokenizer fills in the flagged rows — results are
    identical to tokenize_text + hash_token_lists by construction."""
    from ...utils.text_native import tokenize_hash_native
    res = tokenize_hash_native(docs, num_hashes, min_token_length, binary)
    if res is None:
        return hash_token_lists(
            [tokenize_text(d, min_token_length) for d in docs],
            num_hashes, binary)
    counts, needs_py = res
    if needs_py.any():
        idx = np.nonzero(needs_py)[0]
        counts[idx] = hash_token_lists(
            [tokenize_text(docs[i], min_token_length) for i in idx],
            num_hashes, binary)
    return counts


def hash_token_lists(token_lists: Sequence[Sequence[str]], num_hashes: int,
                     binary: bool = False) -> np.ndarray:
    from ...utils.text_native import hash_token_lists_native
    native = hash_token_lists_native(token_lists, num_hashes, binary)
    if native is not None:
        return native
    out = np.zeros((len(token_lists), num_hashes), dtype=np.float32)
    for i, toks in enumerate(token_lists):
        for t in toks or ():
            out[i, _hash_token(t, num_hashes)] += 1.0
    if binary:
        np.minimum(out, 1.0, out=out)
    return out


class HashingVectorizer(SequenceTransformer):
    """Seq[TextList] → OPVector via the hashing trick (reference
    OPCollectionHashingVectorizer.scala:398 — shared or separate hash space)."""

    output_type = OPVector

    def __init__(self, num_hashes: int = TransmogrifierDefaults.NumHashes,
                 shared_hash_space: bool = False, binary_freq: bool = False,
                 uid=None):
        super().__init__("vecHash", transform_fn=None, output_type=OPVector, uid=uid)
        self.num_hashes = num_hashes
        self.shared_hash_space = shared_hash_space
        self.binary_freq = binary_freq

    def transform_column(self, table: FeatureTable) -> Column:
        blocks, meta = [], []
        if self.shared_hash_space:
            n = table.num_rows
            block = np.zeros((n, self.num_hashes), dtype=np.float32)
            for f in self.input_features:
                vals = np.asarray(table[f.name].values)
                block += hash_token_lists(vals, self.num_hashes, self.binary_freq)
            blocks.append(block)
            meta.extend([VectorColumnMetadata(
                "+".join(fe.name for fe in self.input_features), "TextList",
                None, None, descriptor_value=f"hash_{j}")
                for j in range(self.num_hashes)])
        else:
            for f in self.input_features:
                vals = np.asarray(table[f.name].values)
                blocks.append(hash_token_lists(vals, self.num_hashes, self.binary_freq))
                meta.extend([VectorColumnMetadata(
                    f.name, f.type_name, f.name, None,
                    descriptor_value=f"hash_{j}") for j in range(self.num_hashes)])
        vm = VectorMetadata.of(self.get_output().name, meta)
        return Column(OPVector, np.concatenate(blocks, axis=1), None,
                      {"vector_meta": vm})

    def transform_row(self, row: Dict[str, Any]) -> Any:
        one = FeatureTable(
            {f.name: Column.of_values(f.feature_type, [row.get(f.name)])
             for f in self.input_features}, 1)
        return np.asarray(self.transform_column(one).values)[0].tolist()


class SmartTextVectorizer(Estimator):
    """Seq[Text] → OPVector: per-feature cardinality decides pivot vs hashing
    (reference SmartTextVectorizer.scala:260 — cardinality stats then ≤maxCard
    → one-hot pivot else hashing trick; tracks nulls either way)."""

    output_type = OPVector

    def __init__(self, max_cardinality: int = TransmogrifierDefaults.MaxCardinality,
                 top_k: int = TransmogrifierDefaults.TopK,
                 min_support: int = TransmogrifierDefaults.MinSupport,
                 num_hashes: int = TransmogrifierDefaults.NumHashes,
                 track_nulls: bool = TransmogrifierDefaults.TrackNulls,
                 uid=None):
        super().__init__("smartTxtVec", uid)
        self.max_cardinality = max_cardinality
        self.top_k = top_k
        self.min_support = min_support
        self.num_hashes = num_hashes
        self.track_nulls = track_nulls

    def fit(self, table: FeatureTable) -> Transformer:
        plans: List[Dict[str, Any]] = []
        for f in self.input_features:
            col = table[f.name]
            vals = np.asarray(col.values)
            m = col.valid_mask()
            cnt = Counter(str(v) for v, ok in zip(vals, m) if ok)
            if len(cnt) <= self.max_cardinality:
                top = [v for v, c in cnt.most_common() if c >= self.min_support]
                top = sorted(top, key=lambda v: (-cnt[v], v))[: self.top_k]
                plans.append({"kind": "pivot", "vocab": top})
            else:
                plans.append({"kind": "hash"})
        model = SmartTextVectorizerModel(
            plans=plans, num_hashes=self.num_hashes, track_nulls=self.track_nulls)
        return self._finalize_model(model)


class SmartTextVectorizerModel(_VectorModelBase):
    def __init__(self, plans: List[Dict[str, Any]], num_hashes: int,
                 track_nulls: bool, uid=None):
        super().__init__("smartTxtVec", uid)
        self.plans = plans
        self.num_hashes = num_hashes
        self.track_nulls = track_nulls

    def transform_column(self, table: FeatureTable) -> Column:
        n = table.num_rows
        blocks, meta = [], []
        for f, plan in zip(self.input_features, self.plans):
            col = table[f.name]
            vals = np.asarray(col.values)
            m = col.valid_mask()
            if plan["kind"] == "pivot":
                vocab = plan["vocab"]
                k = len(vocab)
                block = np.zeros((n, k + 1), dtype=np.float32)
                index = {v: i for i, v in enumerate(vocab)}
                for i in np.nonzero(m)[0]:
                    j = index.get(str(vals[i]), -1)
                    block[i, j if j >= 0 else k] = 1.0
                blocks.append(block)
                meta.extend(_meta_cols(
                    f, [(f.name, v) for v in vocab] + [(f.name, OTHER_INDICATOR)]))
            else:
                blocks.append(tokenize_hash_texts(
                    [v if ok else None for v, ok in zip(vals, m)],
                    self.num_hashes))
                meta.extend([VectorColumnMetadata(
                    f.name, f.type_name, f.name, None,
                    descriptor_value=f"hash_{j}") for j in range(self.num_hashes)])
            if self.track_nulls:
                blocks.append((~m).astype(np.float32)[:, None])
                meta.extend(_meta_cols(f, [(f.name, NULL_INDICATOR)]))
        return self._emit(np.concatenate(blocks, axis=1), meta)


# ---------------------------------------------------------------------------
# Combiner
# ---------------------------------------------------------------------------

class VectorsCombiner(SequenceTransformer):
    """Seq[OPVector] → OPVector concatenation with metadata flattening
    (reference VectorsCombiner.scala:89)."""

    output_type = OPVector

    def __init__(self, uid=None):
        super().__init__("combined", transform_fn=None, output_type=OPVector, uid=uid)
        self.mesh = None

    def set_mesh(self, mesh) -> "VectorsCombiner":
        """Upload the combined matrix row-sharded over the mesh's 'data'
        axis, so every downstream consumer reads an already-distributed
        buffer (SURVEY §2.10 P1)."""
        self.mesh = mesh
        return self

    def device_columnar(self, env):
        """Pure-jax dual for the fused serve program (see RealVectorizerModel)."""
        import jax.numpy as jnp
        blocks = []
        for f in self.input_features:
            vals, _ = env[f.name]
            blocks.append(vals[:, None] if vals.ndim == 1
                          else vals.astype(jnp.float32))
        return jnp.concatenate(blocks, axis=1), None

    def transform_column(self, table: FeatureTable) -> Column:
        blocks, metas = [], []
        for f in self.input_features:
            col = table[f.name]
            arr = np.asarray(col.values, dtype=np.float32)
            if arr.ndim == 1:
                arr = arr[:, None]
            blocks.append(arr)
            vm = col.metadata.get("vector_meta")
            if vm is None:
                vm = VectorMetadata.of(f.name, [
                    VectorColumnMetadata(f.name, f.type_name, None, None,
                                         descriptor_value=f"col_{j}")
                    for j in range(arr.shape[1])])
            metas.append(vm)
        vm = VectorMetadata.flatten(self.get_output().name, metas)
        mat = np.concatenate(blocks, axis=1)
        assert vm.size == mat.shape[1], (vm.size, mat.shape)
        # one host→device upload here; every downstream consumer
        # (SanityChecker, ModelSelector, scoring) reuses the device buffer
        import jax.numpy as jnp
        mesh = getattr(self, "mesh", None)
        if mesh is not None and mat.shape[0] % mesh.shape["data"] == 0:
            # row-sharded upload (only when rows split evenly — padding here
            # would change the table's row count; consumers that need exact
            # shards re-pad internally with masked rows, see shard_rows)
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            arr = jax.device_put(jnp.asarray(mat),
                                 NamedSharding(mesh, P("data", None)))
            return Column(OPVector, arr, None, {"vector_meta": vm})
        return Column(OPVector, jnp.asarray(mat), None, {"vector_meta": vm})

    def transform_row(self, row: Dict[str, Any]) -> Any:
        out: List[float] = []
        for f in self.input_features:
            v = row.get(f.name) or []
            out.extend(float(x) for x in (v if isinstance(v, (list, tuple)) else [v]))
        return out
