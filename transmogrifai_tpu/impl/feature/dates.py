"""Date / time feature stages: unit-circle encodings and date-list pivots.

TPU re-design of the reference date stages (reference:
core/.../impl/feature/DateToUnitCircleTransformer.scala:121 — sin/cos circular
encoding per time period; DateMapToUnitCircleVectorizer.scala:134;
DateListVectorizer.scala:309 — SinceFirst/SinceLast/ModeDay/ModeMonth/ModeHour
pivots; TimePeriodTransformer.scala). Epoch-millis int64 host columns are
converted with vectorized numpy datetime64 arithmetic, emitting dense float32
blocks for the device.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ...stages.base import SequenceTransformer, UnaryTransformer
from ...table import Column, FeatureTable
from ...types import Date, DateList, DateMap, Integral, OPVector
from ...vector_metadata import (
    NULL_INDICATOR, VectorColumnMetadata, VectorMetadata,
)
from .vectorizers import _VectorModelBase

#: period → (extractor over epoch-ms int64 array, cardinality, offset)
#: matches the reference's TimePeriod enum (joda semantics: Monday=1)
_DAY_MS = 86_400_000


def _dt_parts(ms: np.ndarray) -> Dict[str, np.ndarray]:
    dt = ms.astype("datetime64[ms]")
    days = dt.astype("datetime64[D]")
    months = dt.astype("datetime64[M]")
    years = dt.astype("datetime64[Y]")
    day_of_month = (days - months.astype("datetime64[D]")).astype(np.int64) + 1
    day_of_year = (days - years.astype("datetime64[D]")).astype(np.int64) + 1
    return {
        "HourOfDay": (ms // 3_600_000) % 24,
        "DayOfWeek": ((days.astype(np.int64) + 3) % 7) + 1,  # 1970-01-01 = Thu
        "DayOfMonth": day_of_month,
        "DayOfYear": day_of_year,
        "MonthOfYear": (months.astype(np.int64) % 12) + 1,
        "WeekOfMonth": ((day_of_month - 1) // 7) + 1,
        "WeekOfYear": ((day_of_year - 1) // 7) + 1,
    }


TIME_PERIODS: Dict[str, Dict[str, float]] = {
    "HourOfDay": {"period": 24.0, "offset": 0.0},
    "DayOfWeek": {"period": 7.0, "offset": 1.0},
    "DayOfMonth": {"period": 31.0, "offset": 1.0},
    "DayOfYear": {"period": 366.0, "offset": 1.0},
    "MonthOfYear": {"period": 12.0, "offset": 1.0},
    "WeekOfMonth": {"period": 5.0, "offset": 1.0},
    "WeekOfYear": {"period": 53.0, "offset": 1.0},
}


def time_period_values(ms: np.ndarray, period: str) -> np.ndarray:
    if period not in TIME_PERIODS:
        raise ValueError(
            f"unknown time period '{period}'; one of {sorted(TIME_PERIODS)}")
    return _dt_parts(np.asarray(ms, dtype=np.int64))[period]


def unit_circle(values: np.ndarray, period: str) -> np.ndarray:
    spec = TIME_PERIODS[period]
    radians = 2.0 * np.pi * (values - spec["offset"]) / spec["period"]
    return np.stack([np.sin(radians), np.cos(radians)], axis=1).astype(np.float32)


class TimePeriodTransformer(UnaryTransformer):
    """Date → Integral time period (reference TimePeriodTransformer.scala)."""

    def __init__(self, period: str = "DayOfWeek", uid=None):
        def fn(v):
            if v is None:
                return None
            return int(time_period_values(np.array([v]), period)[0])
        super().__init__(f"timePeriod{period}", transform_fn=fn,
                         output_type=Integral, input_type=Date, uid=uid)
        self.period = period

    def transform_column(self, table: FeatureTable) -> Column:
        col = table[self.input_features[0].name]
        vals = time_period_values(np.asarray(col.values, dtype=np.int64),
                                  self.period)
        return Column(Integral, vals.astype(np.int64),
                      None if col.mask is None else np.asarray(col.mask))


class TimePeriodListTransformer(UnaryTransformer):
    """DateList → OPVector of per-element time periods (reference
    TimePeriodListTransformer.scala — each timestamp maps to its extracted
    period value). The reference emits ragged per-row vectors; columnar
    arrays are rectangular here, so rows pad/truncate to ``width`` elements
    (pad value -1, never a real period value). With ``width=None`` the
    width is locked by the FIRST batch transformed — its longest list, or 1
    if it is all-empty — and reused for every later batch, so every batch
    emits the same column width; row-wise serving locks from the first ROW
    instead (thread-safe via a class lock). Pass an explicit ``width`` in
    production pipelines where the first batch/row may not be
    representative."""

    #: class-level (hence never serialized) lock guarding the width lock-in
    #: under concurrent serving threads
    _WIDTH_LOCK = threading.Lock()

    def __init__(self, period: str = "DayOfWeek",
                 width: Optional[int] = None, uid=None):
        def fn(v):
            if v is None:
                return None
            arr = np.asarray(list(v), dtype=np.int64)
            vals = [float(x) for x in time_period_values(arr, period)]
            # row path locks the width too (first row seen), so row-wise
            # serving before any columnar batch still emits a fixed width
            width = self._lock_width(len(vals))
            return (vals + [-1.0] * width)[:width]
        super().__init__(f"dateListToTimePeriod{period}", transform_fn=fn,
                         output_type=OPVector, input_type=DateList, uid=uid)
        self.period = period
        self.width = width

    def _lock_width(self, observed: int) -> int:
        if self.width is None:
            with self._WIDTH_LOCK:
                if self.width is None:
                    self.width = max(int(observed), 1)
        return self.width

    def transform_column(self, table: FeatureTable) -> Column:
        col = table[self.input_features[0].name]
        valid = col.valid_mask()
        if self.width is None:
            # lock on first use — even a degenerate all-empty batch, because
            # that batch's (n, 1) output is already emitted and later batches
            # must match it (explicit width exists for that case). Lock from
            # the raw list lengths BEFORE running transform_fn (which itself
            # pads to the locked width)
            lens = [len(col.values[i])
                    if valid[i] and col.values[i] is not None else 0
                    for i in range(len(col))]
            self._lock_width(max(lens, default=1))
        rows = [self.transform_fn(col.values[i]) if valid[i] else None
                for i in range(len(col))]
        width = self.width
        mat = np.full((len(rows), width), -1.0, np.float32)
        for i, r in enumerate(rows):
            if r:
                # rows from transform_fn are already padded once width is
                # locked; re-pad covers only the unlocked first batch
                mat[i, :width] = (r + [-1.0] * width)[:width]
        return Column(OPVector, mat, None)


class TimePeriodMapTransformer(UnaryTransformer):
    """DateMap → IntegralMap of per-key time periods (reference
    TimePeriodMapTransformer.scala)."""

    def __init__(self, period: str = "DayOfWeek", uid=None):
        def fn(v):
            if v is None:
                return None
            return {k: int(time_period_values(
                np.array([t], dtype=np.int64), period)[0])
                for k, t in v.items()}
        from ...types import IntegralMap
        super().__init__(f"dateMapToTimePeriod{period}", transform_fn=fn,
                         output_type=IntegralMap, input_type=DateMap, uid=uid)
        self.period = period


#: reference TransmogrifierDefaults.CircularDateRepresentations
DEFAULT_CIRCULAR_PERIODS = ("HourOfDay", "DayOfWeek", "DayOfMonth", "DayOfYear")


class DateToUnitCircleTransformer(SequenceTransformer):
    """Seq[Date] → OPVector of [sin, cos] per (feature, period) (reference
    DateToUnitCircleTransformer.scala — missing dates map to (0, 0), the
    off-circle marker; Transmogrifier defaults use four circular periods)."""

    output_type = OPVector

    def __init__(self, periods: Sequence[str] = ("HourOfDay",), uid=None):
        super().__init__("toUnitCircle", transform_fn=None,
                         output_type=OPVector, uid=uid)
        self.periods = tuple(periods)

    def transform_column(self, table: FeatureTable) -> Column:
        blocks, meta = [], []
        for f in self.input_features:
            col = table[f.name]
            ms = np.asarray(col.values, dtype=np.int64)
            m = col.valid_mask()
            for period in self.periods:
                block = unit_circle(time_period_values(ms, period), period)
                block[~m] = 0.0
                blocks.append(block)
                meta.extend([
                    VectorColumnMetadata(f.name, f.type_name, f.name, None,
                                         descriptor_value=f"{period}_sin"),
                    VectorColumnMetadata(f.name, f.type_name, f.name, None,
                                         descriptor_value=f"{period}_cos"),
                ])
        vm = VectorMetadata.of(self.get_output().name, meta)
        return Column(OPVector, np.concatenate(blocks, axis=1), None,
                      {"vector_meta": vm})



class DateMapToUnitCircleVectorizer(SequenceTransformer):
    """Seq[DateMap] → OPVector: sin/cos per map key (reference
    DateMapToUnitCircleVectorizer.scala). Key space is taken per batch; for a
    stable key space across train/score pass ``keys`` explicitly."""

    output_type = OPVector

    def __init__(self, period: str = "HourOfDay",
                 keys: Optional[Sequence[str]] = None, uid=None):
        super().__init__("mapToUnitCircle", transform_fn=None,
                         output_type=OPVector, uid=uid)
        self.period = period
        self.keys = list(keys) if keys is not None else None

    def transform_column(self, table: FeatureTable) -> Column:
        n = table.num_rows
        blocks, meta = [], []
        for f in self.input_features:
            col = table[f.name]
            valid = col.valid_mask()
            rows = [col.values[i] if valid[i] and col.values[i] is not None
                    else None for i in range(n)]
            keys = self.keys
            if keys is None:
                keys = sorted({str(k) for r in rows if r for k in r})
            for key in keys:
                ms = np.array([int(r[key]) if r and key in r and r[key] is not None
                               else 0 for r in rows], dtype=np.int64)
                present = np.array([bool(r and key in r and r[key] is not None)
                                    for r in rows])
                block = unit_circle(time_period_values(ms, self.period),
                                    self.period)
                block[~present] = 0.0
                blocks.append(block)
                meta.extend([
                    VectorColumnMetadata(f.name, f.type_name, key, None,
                                         descriptor_value=f"{self.period}_sin"),
                    VectorColumnMetadata(f.name, f.type_name, key, None,
                                         descriptor_value=f"{self.period}_cos"),
                ])
        vm = VectorMetadata.of(self.get_output().name, meta)
        mat = (np.concatenate(blocks, axis=1) if blocks
               else np.zeros((n, 0), dtype=np.float32))
        return Column(OPVector, mat, None, {"vector_meta": vm})



#: DateList pivot kinds (reference DateListPivot enum)
DATE_LIST_PIVOTS = ("SinceFirst", "SinceLast", "ModeDay", "ModeMonth", "ModeHour")


class DateListVectorizer(SequenceTransformer):
    """Seq[DateList] → OPVector with pivot encodings (reference
    DateListVectorizer.scala:309):

    * SinceFirst / SinceLast — days between ``reference_date`` and the
      first/last timestamp (+ null indicator);
    * ModeDay — one-hot(7) of the modal day-of-week;
    * ModeMonth — one-hot(12) of the modal month;
    * ModeHour — one-hot(24) of the modal hour.
    """

    output_type = OPVector

    def __init__(self, pivot: str = "SinceLast",
                 reference_date_ms: Optional[int] = None,
                 track_nulls: bool = True, uid=None):
        super().__init__(f"dateList{pivot}", transform_fn=None,
                         output_type=OPVector, uid=uid)
        if pivot not in DATE_LIST_PIVOTS:
            raise ValueError(f"pivot must be one of {DATE_LIST_PIVOTS}")
        self.pivot = pivot
        # pinned at construction so train/score agree (determinism; the
        # reference defaults to TransmogrifierDefaults.ReferenceDate "now")
        self.reference_date_ms = (int(_time.time() * 1000)
                                  if reference_date_ms is None
                                  else int(reference_date_ms))
        self.track_nulls = track_nulls

    _MODE_SPECS = {"ModeDay": ("DayOfWeek", 7, 1),
                   "ModeMonth": ("MonthOfYear", 12, 1),
                   "ModeHour": ("HourOfDay", 24, 0)}

    def transform_column(self, table: FeatureTable) -> Column:
        n = table.num_rows
        blocks, meta = [], []
        for f in self.input_features:
            col = table[f.name]
            valid = col.valid_mask()
            lists = [col.values[i] if valid[i] else None for i in range(n)]
            if self.pivot in ("SinceFirst", "SinceLast"):
                take = min if self.pivot == "SinceFirst" else max
                days = np.zeros(n, dtype=np.float32)
                nulls = np.zeros(n, dtype=np.float32)
                for i, lst in enumerate(lists):
                    if not lst:
                        nulls[i] = 1.0
                        continue
                    days[i] = (self.reference_date_ms - take(lst)) / _DAY_MS
                cols = [days]
                meta.append(VectorColumnMetadata(
                    f.name, f.type_name, f.name, None,
                    descriptor_value=self.pivot))
                if self.track_nulls:
                    cols.append(nulls)
                    meta.append(VectorColumnMetadata(
                        f.name, f.type_name, f.name, NULL_INDICATOR))
                blocks.append(np.stack(cols, axis=1))
            else:
                period, card, offset = self._MODE_SPECS[self.pivot]
                block = np.zeros((n, card), dtype=np.float32)
                for i, lst in enumerate(lists):
                    if not lst:
                        continue
                    vals = time_period_values(
                        np.asarray(lst, dtype=np.int64), period)
                    vv, cc = np.unique(vals, return_counts=True)
                    mode = int(vv[np.argmax(cc)])  # ties → smallest value
                    block[i, mode - offset] = 1.0
                blocks.append(block)
                meta.extend([VectorColumnMetadata(
                    f.name, f.type_name, f.name, f"{self.pivot}_{j + offset}")
                    for j in range(card)])
        vm = VectorMetadata.of(self.get_output().name, meta)
        return Column(OPVector, np.concatenate(blocks, axis=1), None,
                      {"vector_meta": vm})



# circular import avoidance: FeatureTable already imported at module top
