"""transmogrifai_tpu — a TPU-native AutoML framework for structured data.

A from-scratch JAX/XLA re-design of the capabilities of TransmogrifAI
(Salesforce's Scala/Spark AutoML library): a typed feature algebra, a lazy
transformer/estimator DAG, automated feature engineering (``transmogrify``),
automated feature validation (SanityChecker, RawFeatureFilter) and automated
model selection (ModelSelector with CV/TVS sweeps) — executed as jit-compiled
columnar kernels on TPU instead of Spark jobs, with hyperparameter sweeps
vmapped over the grid and sharded over a device mesh.
"""

from .types import *  # noqa: F401,F403
from .features import Feature, FeatureBuilder
from .table import Column, FeatureTable
from .vector_metadata import VectorColumnMetadata, VectorMetadata
from . import dsl  # noqa: F401  (attaches the rich feature syntax to Feature)

__version__ = "0.1.0"

#: lazily-imported public API (importing these eagerly would pull in jax
#: before the user has a chance to set platform flags)
_LAZY = {
    "OpWorkflow": ".workflow",
    "OpWorkflowModel": ".workflow",
    "SanityChecker": ".impl.preparators.sanity_checker",
    "BinaryClassificationModelSelector": ".impl.selector.factories",
    "MultiClassificationModelSelector": ".impl.selector.factories",
    "RegressionModelSelector": ".impl.selector.factories",
    "transmogrify": ".impl.feature.transmogrifier",
    "DataReaders": ".readers.readers",
    "Evaluators": ".evaluators.factory",
    "RetryPolicy": ".robustness.policy",
    "FaultReport": ".robustness.policy",
    "StreamingGBT": ".streaming.model",
    "TableChunkSource": ".streaming.source",
    "AvroChunkSource": ".streaming.source",
    "SyntheticChunkSource": ".streaming.source",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(_LAZY[name], __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
