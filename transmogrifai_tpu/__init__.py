"""transmogrifai_tpu — a TPU-native AutoML framework for structured data.

A from-scratch JAX/XLA re-design of the capabilities of TransmogrifAI
(Salesforce's Scala/Spark AutoML library): a typed feature algebra, a lazy
transformer/estimator DAG, automated feature engineering (``transmogrify``),
automated feature validation (SanityChecker, RawFeatureFilter) and automated
model selection (ModelSelector with CV/TVS sweeps) — executed as jit-compiled
columnar kernels on TPU instead of Spark jobs, with hyperparameter sweeps
vmapped over the grid and sharded over a device mesh.
"""

from .types import *  # noqa: F401,F403
from .features import Feature, FeatureBuilder
from .table import Column, FeatureTable
from .vector_metadata import VectorColumnMetadata, VectorMetadata

__version__ = "0.1.0"
