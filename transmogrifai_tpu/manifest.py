"""Atomic file writes + per-checkpoint-dir integrity manifest.

TPUs are preemptible: a ``train()`` can be killed at any instruction,
including mid-``np.savez``. The reference leans on RDD lineage + HDFS
rename-commit semantics for this (reference: Spark's
FileCommitProtocol / HadoopMapReduceCommitProtocol — task output goes to a
temporary attempt path and is renamed into place on commit); the JAX
rebuild writes plain files, so the same discipline is rebuilt here:

* :func:`atomic_write_bytes` / :func:`atomic_write_json` — write to
  ``<path>.tmp``, flush + fsync, then ``os.replace`` into place. A kill at
  any point leaves either the old file or the new file, never a torn one.
  Orphaned ``*.tmp`` files are the only possible debris.
* :class:`CheckpointManifest` — ``MANIFEST.json`` inside a checkpoint
  directory recording the format version, a per-file sha256 + size, and
  per-stage / per-sweep *completion records*. A file is only trustworthy if
  (a) its completion record exists and (b) its checksum matches — so
  corruption (truncated file, bit rot, a kill between two of a stage's
  files) is *detected* at load and reported, never silently used.

The manifest itself is rewritten atomically after every completion, so it
always describes a consistent prefix of the training run.
"""
from __future__ import annotations

import contextlib
import hashlib
import itertools
import json
import os
from typing import Any, Dict, List, Optional, Tuple

MANIFEST_FILE = "MANIFEST.json"
MANIFEST_VERSION = 1
#: run liveness sentinel (cross-process kill detection, docs/robustness.md
#: "Cross-process kill detection"): pid + coarse phase of the training run
#: that owns the checkpoint dir, written atomically, removed on clean exit
SENTINEL_FILE = "RUN_SENTINEL.json"


def sha256_bytes(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            b = fh.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


#: per-process tmp-name disambiguator: concurrent writers targeting the
#: SAME destination (two replicas populating one AOT program store, two
#: trainers sharing a checkpoint dir) must not share a tmp path — with a
#: fixed ``<path>.tmp`` one writer's rename deletes the other's staging
#: file mid-write (found by the programstore two-process race test)
_TMP_SEQ = itertools.count(1)


def atomic_write_bytes(path: str, data: bytes) -> str:
    """Write ``data`` to ``path`` via tmp + fsync + rename; returns the
    sha256 of what was written. The staging file is
    ``<path>.<pid>.<seq>.tmp`` (unique per writer, so concurrent
    processes targeting one destination race benignly — last rename
    wins, both files were complete); a kill mid-write leaves only
    ``*.tmp`` debris — the destination is either absent or complete."""
    tmp = f"{path}.{os.getpid()}.{next(_TMP_SEQ)}.tmp"
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        # best-effort: do not strand the staging file on a failed write
        # (a hard kill still can — that is the debris clean_tmp_debris
        # sweeps)
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    return sha256_bytes(data)


def atomic_write_json(path: str, obj: Any, **dump_kw) -> str:
    return atomic_write_bytes(
        path, json.dumps(obj, **dump_kw).encode("utf-8"))


def clean_tmp_debris(dirpath: str) -> List[str]:
    """Remove ``*.tmp`` files left by a process killed mid-atomic-write.
    They are by-construction incomplete; removing them is always safe."""
    removed: List[str] = []
    if not os.path.isdir(dirpath):
        return removed
    for fname in sorted(os.listdir(dirpath)):
        if fname.endswith(".tmp"):
            try:
                os.remove(os.path.join(dirpath, fname))
                removed.append(fname)
            except OSError:
                pass
    return removed


class CheckpointManifest:
    """The ``MANIFEST.json`` of one checkpoint directory.

    Schema (docs/robustness.md "Checkpoint manifest")::

        {
          "manifestVersion": 1,
          "formatVersion": 1,             // checkpoint payload format
          "files":  {"<fname>": {"sha256": "...", "size": 123}},
          "stages": {"<uid>":   {"files": ["<uid>.json", "<uid>.npz"]}},
          "sweeps": {"<owner>": {"file": "sweep_<owner>.json"}}
        }

    Only files reachable through a ``stages``/``sweeps`` completion record
    are ever loaded; everything else in the directory is debris from an
    interrupted write and is reported, not used.
    """

    def __init__(self, dirpath: str, format_version: int):
        self.dirpath = dirpath
        self.format_version = format_version
        self.files: Dict[str, Dict[str, Any]] = {}
        self.stages: Dict[str, Dict[str, Any]] = {}
        self.sweeps: Dict[str, Dict[str, Any]] = {}
        #: streaming fold states: per (stage, pass) completion records with
        #: the last committed chunk index (streaming/checkpoint.py; absent
        #: on pre-streaming manifests — loaders must tolerate that)
        self.streams: Dict[str, Dict[str, Any]] = {}
        #: optional warm-start hint for saved models: the serve-path plan
        #: schema fingerprint the registry pre-traces at load
        #: (serving/warmup.py; absent/empty on stage-checkpoint dirs and
        #: pre-serving manifests — loaders must tolerate that)
        self.serving: Dict[str, Any] = {}
        #: optional per-feature training-distribution baseline (streaming
        #: histogram sketch states + fill rates) the serving registry
        #: hands its DriftMonitor at load (serving/drift.py; absent on
        #: pre-drift manifests — loaders must tolerate that)
        self.drift: Dict[str, Any] = {}
        #: optional measured dispatch cost table: (segment fingerprint ×
        #: padding bucket) → {bytes, compileSeconds, executeSeconds},
        #: written at save/warmup time (observability/devicemem.py) —
        #: the artifact pre-flight admission control and the AOT compile
        #: store consume (ROADMAP items 1/2). Absent or corrupt sections
        #: load as {} — costs are advisory, never load-blocking.
        self.costs: Dict[str, Any] = {}
        #: optional AOT program-store index: serialized compiled-program
        #: entries keyed by (segment fingerprint × padding bucket), with
        #: the jaxlib version + device kind they were exported for and
        #: the covered plan identities (transmogrifai_tpu/programstore/;
        #: blobs live in the ``programs/`` subdirectory). Same tolerance
        #: contract as ``costs``: absent or corrupt sections load as {}
        #: — a garbled program index degrades to the trace path, never
        #: blocks a load.
        self.programs: Dict[str, Any] = {}

    @property
    def path(self) -> str:
        return os.path.join(self.dirpath, MANIFEST_FILE)

    # -- load / save ---------------------------------------------------------
    @classmethod
    def load(cls, dirpath: str, format_version: int
             ) -> Tuple["CheckpointManifest", Optional[str]]:
        """Read the directory's manifest. Returns ``(manifest, error)``:
        a fresh empty manifest (nothing trustworthy) plus the reason when
        the manifest is absent, unparsable, or of an unknown version."""
        m = cls(dirpath, format_version)
        path = m.path
        if not os.path.isfile(path):
            return m, None if not os.path.isdir(dirpath) else "missing"
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            return m, f"unreadable manifest: {type(e).__name__}: {e}"
        if doc.get("manifestVersion") != MANIFEST_VERSION:
            return m, (f"unsupported manifest version "
                       f"{doc.get('manifestVersion')!r}")
        if doc.get("formatVersion") != format_version:
            return m, (f"checkpoint format {doc.get('formatVersion')!r} != "
                       f"expected {format_version}")
        m.files = dict(doc.get("files", {}))
        m.stages = dict(doc.get("stages", {}))
        m.sweeps = dict(doc.get("sweeps", {}))
        m.serving = dict(doc.get("serving", {}))
        m.streams = dict(doc.get("streams", {}))
        m.drift = dict(doc.get("drift", {}))
        # advisory section: tolerate a corrupt/foreign costs value (a
        # garbled cost table must never block loading a good model)
        costs = doc.get("costs", {})
        m.costs = dict(costs) if isinstance(costs, dict) else {}
        programs = doc.get("programs", {})
        m.programs = dict(programs) if isinstance(programs, dict) else {}
        return m, None

    def save(self) -> None:
        os.makedirs(self.dirpath, exist_ok=True)
        doc = {
            "manifestVersion": MANIFEST_VERSION,
            "formatVersion": self.format_version,
            "files": self.files,
            "stages": self.stages,
            "sweeps": self.sweeps,
        }
        if self.serving:
            doc["serving"] = self.serving
        if self.streams:
            doc["streams"] = self.streams
        if self.drift:
            doc["drift"] = self.drift
        if self.costs:
            doc["costs"] = self.costs
        if self.programs:
            doc["programs"] = self.programs
        atomic_write_json(self.path, doc, indent=1)

    # -- recording -----------------------------------------------------------
    def record_file(self, fname: str, sha256: str, size: int) -> None:
        self.files[fname] = {"sha256": sha256, "size": size}

    def complete_stage(self, uid: str, fnames: List[str]) -> None:
        """Mark a stage checkpoint complete (all its files written +
        recorded). Call AFTER the files are durably in place; the manifest
        save that follows is the commit point."""
        self.stages[uid] = {"files": list(fnames)}

    def complete_sweep(self, owner_uid: str, fname: str) -> None:
        self.sweeps[owner_uid] = {"file": fname}

    def complete_stream(self, key: str, fname: str,
                        meta: Dict[str, Any]) -> None:
        """Commit a streaming fold state: ``key`` is ``<stage uid>/<pass>``,
        ``meta`` records the source fingerprint + last folded chunk. The
        manifest save that follows is the commit point — a kill before it
        leaves the previous committed chunk authoritative."""
        self.streams[key] = {"file": fname, **meta}

    def drop_streams(self, stage_uid: str) -> None:
        """Forget a stage's stream states (after its full stage checkpoint
        commits, the per-pass fold states are redundant)."""
        for key in [k for k in self.streams
                    if k.split("/", 1)[0] == stage_uid]:
            del self.streams[key]

    # -- verification --------------------------------------------------------
    def verify_file(self, fname: str) -> Optional[str]:
        """None when ``fname`` exists and matches its recorded checksum;
        otherwise a human-readable reason (missing record / missing file /
        size mismatch / checksum mismatch)."""
        rec = self.files.get(fname)
        path = os.path.join(self.dirpath, fname)
        if rec is None:
            return "file has no manifest record (incomplete write)"
        if not os.path.isfile(path):
            return "file recorded in manifest but missing on disk"
        size = os.path.getsize(path)
        if size != rec.get("size"):
            return (f"size mismatch: manifest says {rec.get('size')} bytes, "
                    f"file has {size}")
        actual = sha256_file(path)
        if actual != rec.get("sha256"):
            return (f"sha256 mismatch: manifest {rec.get('sha256')[:12]}..., "
                    f"file {actual[:12]}...")
        return None

    def verify_recorded(self) -> List[str]:
        """Verify every file reachable through a completion record
        (stages + sweeps); → list of '<file>: <reason>' problems. The
        campaign engine's checkpoint-integrity oracle — an empty list
        means everything a resume would trust actually verifies."""
        problems: List[str] = []
        fnames: List[str] = []
        for rec in self.stages.values():
            fnames.extend(rec.get("files", ()))
        for rec in self.sweeps.values():
            if rec.get("file"):
                fnames.append(rec["file"])
        for rec in self.streams.values():
            if rec.get("file"):
                fnames.append(rec["file"])
        for fname in sorted(set(fnames)):
            reason = self.verify_file(fname)
            if reason is not None:
                problems.append(f"{fname}: {reason}")
        return problems

    def unrecorded_files(self) -> List[str]:
        """Checkpoint payload files on disk with no completion record —
        debris from a write the process never committed."""
        if not os.path.isdir(self.dirpath):
            return []
        recorded = set(self.files)
        for rec in self.stages.values():
            recorded.update(rec.get("files", ()))
        for rec in self.sweeps.values():
            recorded.add(rec.get("file"))
        out = []
        for fname in sorted(os.listdir(self.dirpath)):
            # the run sentinel is liveness metadata, not checkpoint
            # payload; the AOT program store is indexed by the manifest
            # `programs` section, not per-file records
            if fname in (MANIFEST_FILE, SENTINEL_FILE, "programs") \
                    or fname.endswith(".tmp"):
                continue
            if fname not in recorded:
                out.append(fname)
        return out


# -- run sentinel: cross-process kill detection ------------------------------

class RunSentinel:
    """Pid + coarse-phase liveness marker for one training run
    (``RUN_SENTINEL.json`` in the checkpoint dir; docs/robustness.md
    "Cross-process kill detection").

    A preemption-safe resume can already survive a kill — but it could
    never *say* the previous process died, or what it was doing. The
    sentinel closes that gap for cross-process kills (the OOM killer,
    SIGKILL, a node loss): the training run writes ``{pid, phase}``
    atomically at start, updates ``phase`` only when it changes (one
    rename per transition, never per call), and removes the file on clean
    completion. A later ``train(resume=True)`` from a *different* process
    finding the file knows the previous owner exited uncleanly and records
    a FaultLog ``unclean_exit`` — with ``oomKillSuspected`` when the last
    phase was device work (``device_*``: a dispatch/upload is exactly
    where the OOM killer strikes). Same-pid re-runs (in-process simulated
    preemption, a retried train in one process) are not flagged — those
    recoveries are already accounted by the preemption machinery."""

    #: phases with this prefix mean the process was inside a device
    #: dispatch/upload when it last wrote — an OOM kill's favorite moment
    DEVICE_PHASE_PREFIX = "device"

    def __init__(self, dirpath: str):
        self.dirpath = dirpath
        self._phase: Optional[str] = None

    @property
    def path(self) -> str:
        return os.path.join(self.dirpath, SENTINEL_FILE)

    def start(self, phase: str = "start") -> None:
        os.makedirs(self.dirpath, exist_ok=True)
        self._phase = None
        self.set_phase(phase)

    def set_phase(self, phase: str) -> None:
        """Record the run's coarse phase; writes only on transition so
        hot paths can call this per dispatch at no recurring cost."""
        if phase == self._phase:
            return
        self._phase = phase
        atomic_write_json(self.path, {"pid": os.getpid(), "phase": phase})

    def clear(self) -> None:
        """Clean-exit commit: the run finished, no evidence to keep."""
        self._phase = None
        try:
            os.remove(self.path)
        except OSError:
            pass

    @staticmethod
    def read(dirpath: str) -> Optional[Dict[str, Any]]:
        path = os.path.join(dirpath, SENTINEL_FILE)
        if not os.path.isfile(path):
            return None
        try:
            with open(path) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            # atomic writes make a torn sentinel impossible; an unreadable
            # one is still evidence of *something* — report it as such
            return {"pid": None, "phase": "unreadable"}

    def read_stale(self) -> Optional[Dict[str, Any]]:
        """The previous owner's sentinel, when that owner was a different
        process (None for own/absent sentinels)."""
        doc = self.read(self.dirpath)
        if doc is None or doc.get("pid") == os.getpid():
            return None
        return doc

    @staticmethod
    def suspects_oom_kill(doc: Dict[str, Any]) -> bool:
        return str(doc.get("phase", "")).startswith(
            RunSentinel.DEVICE_PHASE_PREFIX)


#: the ambient sentinel a training run activates so deep code (plan
#: segments, the stream feed's producer THREAD, sweep dispatch) can hint
#: the current phase without threading the object through every signature.
#: A plain module global, not a contextvar: the feed producer runs on its
#: own thread and must see the trainer's sentinel; phase hints are
#: advisory, and concurrent trains (rare: a background drift refit) just
#: share the hint.
_ACTIVE_SENTINEL: Optional[RunSentinel] = None


@contextlib.contextmanager
def active_sentinel(sentinel: Optional[RunSentinel]):
    """Make ``sentinel`` the ambient phase-hint target for the block
    (no-op context when None)."""
    global _ACTIVE_SENTINEL
    prev = _ACTIVE_SENTINEL
    _ACTIVE_SENTINEL = sentinel
    try:
        yield sentinel
    finally:
        _ACTIVE_SENTINEL = prev


def sentinel_phase(phase: str) -> None:
    """Advisory phase hint onto the ambient run sentinel (inert when no
    training run owns one). Never raises — crash evidence must not crash
    the run it protects."""
    s = _ACTIVE_SENTINEL
    if s is not None:
        try:
            s.set_phase(phase)
        except OSError:
            pass
