"""AOT program store: zero-retrace cold start + a fleet-wide compile cache.

See :mod:`.store` (content-addressed artifact store, sessions, the
``aot.load`` chaos site) and :mod:`.aot` (jax.export serialize /
deserialize helpers). docs/serving.md "AOT cold start & the program
store" is the operator-facing contract.
"""
from .store import (  # noqa: F401
    AOT_ENV, PROGRAMS_DIR, ProgramStore, StoreEntryError, active_captures,
    aot_enabled, capture, close_sessions, enable_aot, lookup,
    offer_segment, open_env_session, open_model_session, plan_covered,
    populate_for_save, record_plan_hit, reset, sessions_active, snapshot,
    stats,
)
