"""jax.export glue: serialize a jitted program, rebuild a callable.

The only module in the package that touches jax. ``export_bytes`` traces
the jitted function once against the call's concrete arguments (shapes +
dtypes become the exported avals — exactly the shapes the padded dispatch
sites replay) and serializes the StableHLO artifact;
``load_callable`` deserializes and wraps the exported module in one thin
``jax.jit`` so repeated dispatches reuse the compiled executable instead
of re-staging the module per call.

What the round trip buys: a fresh process skips the Python trace of the
whole stage chain (the dominant cold-start cost at this repo's scale —
dozens of ``device_columnar`` stages per segment, plus the zero-row
probe-and-partition pass that ``plan.get_plan`` pays per schema). XLA
still compiles the deserialized StableHLO on first call; layered under
``utils/jax_cache.py``'s persistent XLA cache that compile is itself a
disk hit for unchanged modules. Outputs are bit-identical to the freshly
traced program — same StableHLO, same compiler, same device — which is
why the store keys on (jaxlib version × device kind) and refuses to
cross either boundary (docs/serving.md "AOT cold start & the program
store").
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

_SUPPORTED: Optional[bool] = None


def aot_supported() -> bool:
    """True when this jax build can export + deserialize programs
    (cached probe; False degrades every store path to a no-op)."""
    global _SUPPORTED
    if _SUPPORTED is None:
        try:
            from jax import export as _  # noqa: F401
            _SUPPORTED = True
        except Exception:
            _SUPPORTED = False
    return _SUPPORTED


def current_jaxlib() -> str:
    try:
        import jaxlib.version
        return str(jaxlib.version.__version__)
    except Exception:
        try:
            import jax
            return str(jax.__version__)
        except Exception:
            return "unknown"


def current_device_kind() -> str:
    """``<platform>/<device_kind>`` of the first local device — one half
    of the store key: an artifact exported for one backend must never
    deserialize onto another."""
    try:
        import jax
        d = jax.local_devices()[0]
        return f"{d.platform}/{getattr(d, 'device_kind', d.platform)}"
    except Exception:
        return "unknown"


def export_bytes(jitted_fn: Callable, args: Tuple[Any, ...]) -> bytes:
    """Serialize ``jitted_fn`` lowered at ``args``' avals (concrete
    arrays or ShapeDtypeStructs both work — export reads shapes/dtypes,
    never values)."""
    from jax import export as jexport
    return bytes(jexport.export(jitted_fn)(*args).serialize())


def load_callable(blob: bytes) -> Callable:
    """Deserialize an exported program into a dispatchable callable.
    Raises on any malformed/incompatible blob — the store turns that
    into a typed fallback, never an error on a request path."""
    import jax
    from jax import export as jexport
    exported = jexport.deserialize(bytearray(blob))
    return jax.jit(exported.call)
