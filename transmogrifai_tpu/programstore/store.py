"""Content-addressed AOT program store + sessions + the ``aot.load`` site.

ROADMAP item 1's surviving gap: warm start (PR 6) pre-traces at
``registry.load()``, so a fresh process still pays the full Python trace
+ XLA compile of every serve program at load — and PR 14's replica fleet
multiplied that by N. This module generalizes ``utils/jax_cache.py``
from a per-process XLA byte cache into a **framework-level artifact
shared across processes and replicas**: serialized ``jax.export``
programs (transform-plan segments — the serve scorer included — and the
fused sweep programs), keyed by

    (segment fingerprint x padding bucket x jaxlib version x device kind)

and stored content-addressed next to the model (``<model>/programs/``,
entries recorded in a ``programs`` section of ``MANIFEST.json``) or in a
cross-model store (``TG_AOT_STORE``). ``registry.load()`` opens a
*session* over the manifest entries before any trace is attempted; the
plan executor consults :func:`lookup` at each segment's first dispatch
per bucket and dispatches the deserialized program instead of tracing.
A fleet's replica 1 populates (its traced warm dispatches are *offered*
back through :func:`offer_segment` under a :func:`capture` scope);
replicas 2..N deserialize — the fleet compiles once total.

The fallback ladder is the contract (docs/serving.md "AOT cold start &
the program store"): a store hit dispatches bit-identically to the
traced program (same StableHLO, same compiler — asserted in
tests/test_programstore.py); **any** mismatch — absent entry, jaxlib or
device-kind drift, corrupt/truncated blob, deserialization failure, or
the deterministic ``aot.load`` chaos fault — degrades to the existing
trace path with a typed FaultLog ``aot_fallback`` record, a
``tg_aot_miss_total{reason}`` count, and the resulting build classified
``aot-miss`` in the compile ledger. Never an error on a request path.

Concurrency: every write goes through ``manifest.atomic_write_bytes``
(tmp + fsync + rename) and blobs are content-addressed by sha256, so
two replicas populating the same store race benignly — both write the
same bytes under the same name, the rename is atomic, and the manifest
merge is last-writer-wins over identical entries. The store is bounded:
:meth:`ProgramStore.gc` evicts oldest-first past ``TG_AOT_STORE_MAX``
entries / ``TG_AOT_STORE_MAX_BYTES``.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import aot as _aot

logger = logging.getLogger(__name__)

#: master switch: TG_AOT=0 disables every store path (lookup, capture,
#: save-time populate) process-wide
AOT_ENV = "TG_AOT"
#: save-time populate switch (default on): ``save_model`` drives the
#: serve scorer once under a capture scope so the saved model ships its
#: programs; TG_AOT_SAVE=0 defers population to the first warm load
AOT_SAVE_ENV = "TG_AOT_SAVE"
#: cross-model store directory (sweep programs at train time; also
#: consulted by plan lookups). Unset = model-dir stores only.
STORE_ENV = "TG_AOT_STORE"
#: store bounds (oldest-first GC past either)
STORE_MAX_ENV = "TG_AOT_STORE_MAX"
STORE_MAX_BYTES_ENV = "TG_AOT_STORE_MAX_BYTES"
DEFAULT_STORE_MAX = 128
DEFAULT_STORE_MAX_BYTES = 512 * 1024 * 1024

#: store subdirectory inside a model dir
PROGRAMS_DIR = "programs"
#: MANIFEST.json ``programs`` section format version
PROGRAMS_VERSION = 1

_FALSY = ("0", "false", "False", "no", "off")

_enabled_override: Optional[bool] = None


def aot_enabled() -> bool:
    """True when the AOT program store is active (default on;
    ``TG_AOT=0`` disables, :func:`enable_aot` overrides)."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get(AOT_ENV, "1") not in _FALSY


def enable_aot(on: Optional[bool]) -> None:
    """Force the store on/off from code (benches, tests); ``None`` hands
    control back to the ``TG_AOT`` environment switch."""
    global _enabled_override
    _enabled_override = None if on is None else bool(on)


def save_populate_enabled() -> bool:
    return (aot_enabled()
            and os.environ.get(AOT_SAVE_ENV, "1") not in _FALSY)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class StoreEntryError(RuntimeError):
    """A store entry failed integrity verification (missing blob, size or
    sha256 mismatch). Internal — always converted into a typed fallback,
    never surfaced to a request."""


def key_id(fingerprint: str, bucket: int) -> str:
    return f"{fingerprint}@{int(bucket)}"


class ProgramStore:
    """One on-disk store directory: content-addressed blobs
    (``<sha256[:32]>.bin``) plus one small JSON meta per entry
    (``<keyhash>.json``) carrying the full key, integrity fields and a
    best-effort hit count. All writes are atomic
    (``manifest.atomic_write_bytes``)."""

    def __init__(self, dirpath: str):
        self.dirpath = dirpath

    @staticmethod
    def _meta_name(kid: str) -> str:
        return hashlib.sha256(kid.encode("utf-8")).hexdigest()[:24] + ".json"

    def _meta_path(self, kid: str) -> str:
        return os.path.join(self.dirpath, self._meta_name(kid))

    # -- read ----------------------------------------------------------------
    def entries(self) -> Dict[str, Dict[str, Any]]:
        """``{keyId: meta}`` over every readable meta in the store
        (unreadable metas are skipped — debris, not errors)."""
        out: Dict[str, Dict[str, Any]] = {}
        if not os.path.isdir(self.dirpath):
            return out
        for fname in sorted(os.listdir(self.dirpath)):
            if not fname.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.dirpath, fname)) as fh:
                    meta = json.load(fh)
            except (OSError, ValueError):
                continue
            kid = meta.get("keyId") if isinstance(meta, dict) else None
            if kid:
                out[kid] = meta
        return out

    def get(self, kid: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._meta_path(kid)) as fh:
                meta = json.load(fh)
        except (OSError, ValueError):
            return None
        return meta if isinstance(meta, dict) else None

    def read_blob(self, meta: Dict[str, Any]) -> bytes:
        """The entry's verified program bytes; :class:`StoreEntryError`
        on any integrity problem (the caller's typed-fallback trigger)."""
        fname = meta.get("file")
        if not fname:
            raise StoreEntryError("entry has no blob file recorded")
        path = os.path.join(self.dirpath, str(fname))
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError as e:
            raise StoreEntryError(f"blob unreadable: {e}") from e
        if len(blob) != int(meta.get("size", -1)):
            raise StoreEntryError(
                f"blob size {len(blob)} != recorded {meta.get('size')} "
                f"(truncated artifact)")
        sha = hashlib.sha256(blob).hexdigest()
        if sha != meta.get("sha256"):
            raise StoreEntryError(
                f"blob sha256 {sha[:12]}... != recorded "
                f"{str(meta.get('sha256'))[:12]}... (corrupt artifact)")
        return blob

    # -- write ---------------------------------------------------------------
    def put(self, key: Dict[str, Any], blob: bytes) -> Dict[str, Any]:
        """Write one entry (idempotent: same key + same bytes land on the
        same names; the atomic rename makes concurrent writers benign).
        ``key`` must carry fingerprint/bucket/jaxlib/deviceKind/component;
        returns the persisted meta."""
        from ..manifest import atomic_write_bytes
        os.makedirs(self.dirpath, exist_ok=True)
        sha = hashlib.sha256(blob).hexdigest()
        kid = key_id(key["fingerprint"], key["bucket"])
        blob_name = sha[:32] + ".bin"
        meta = {
            "keyId": kid,
            "fingerprint": str(key["fingerprint"]),
            "bucket": int(key["bucket"]),
            "jaxlib": str(key["jaxlib"]),
            "deviceKind": str(key["deviceKind"]),
            "component": str(key.get("component", "plan-segment")),
            "identity": str(key.get("identity", "")),
            "planIdent": key.get("planIdent"),
            "sha256": sha,
            "size": len(blob),
            "file": blob_name,
            "createdUnix": time.time(),
            "hits": 0,
        }
        blob_path = os.path.join(self.dirpath, blob_name)
        # content-addressing makes an existing file *normally* skippable,
        # but a corrupted/truncated file at that name breaks the
        # assumption — the self-heal re-export would silently keep the
        # bad bytes. Skip only a verified match; rewrite otherwise.
        existing_ok = False
        try:
            if os.path.getsize(blob_path) == len(blob):
                with open(blob_path, "rb") as fh:
                    existing_ok = (hashlib.sha256(fh.read()).hexdigest()
                                   == sha)
        except OSError:
            existing_ok = False
        if not existing_ok:
            atomic_write_bytes(blob_path, blob)
        atomic_write_bytes(
            self._meta_path(kid),
            json.dumps(meta, indent=1).encode("utf-8"))
        return meta

    def touch(self, kid: str) -> None:
        """Best-effort hit-count bump (once per process per program — the
        deserialize moment, never the dispatch hot path)."""
        meta = self.get(kid)
        if meta is None:
            return
        meta["hits"] = int(meta.get("hits", 0)) + 1
        try:
            from ..manifest import atomic_write_bytes
            atomic_write_bytes(
                self._meta_path(kid),
                json.dumps(meta, indent=1).encode("utf-8"))
        except OSError:
            pass  # a read-only store still serves hits

    # -- maintenance ---------------------------------------------------------
    def verify(self) -> List[str]:
        """``['<keyId>: <reason>', ...]`` integrity problems over every
        entry (empty = clean). ``cli.py programs`` exits non-zero on any."""
        problems: List[str] = []
        for kid, meta in sorted(self.entries().items()):
            try:
                self.read_blob(meta)
            except StoreEntryError as e:
                problems.append(f"{kid}: {e}")
        return problems

    def total_bytes(self) -> int:
        return sum(int(m.get("size", 0)) for m in self.entries().values())

    def gc(self, max_entries: Optional[int] = None,
           max_bytes: Optional[int] = None) -> List[str]:
        """Evict oldest-first past the bounds (``TG_AOT_STORE_MAX`` /
        ``TG_AOT_STORE_MAX_BYTES`` defaults); returns evicted keyIds.
        Orphaned blobs (no surviving meta references them) are removed
        with their last meta."""
        max_entries = (max_entries if max_entries is not None
                       else _env_int(STORE_MAX_ENV, DEFAULT_STORE_MAX))
        max_bytes = (max_bytes if max_bytes is not None
                     else _env_int(STORE_MAX_BYTES_ENV,
                                   DEFAULT_STORE_MAX_BYTES))
        entries = self.entries()
        ordered = sorted(entries.items(),
                         key=lambda kv: kv[1].get("createdUnix", 0.0))
        removed: List[str] = []
        total = sum(int(m.get("size", 0)) for _, m in ordered)
        while ordered and (len(ordered) > max(1, max_entries)
                           or total > max(1, max_bytes)):
            kid, meta = ordered.pop(0)
            total -= int(meta.get("size", 0))
            removed.append(kid)
            try:
                os.remove(self._meta_path(kid))
            except OSError:
                pass
            blob = meta.get("file")
            if blob and not any(m.get("file") == blob for _, m in ordered):
                try:
                    os.remove(os.path.join(self.dirpath, str(blob)))
                except OSError:
                    pass
        return removed


# ---------------------------------------------------------------------------
# Sessions: the read side registry.load() opens before any trace
# ---------------------------------------------------------------------------

class _Session:
    """One opened store: verified-at-lookup entries + the plan identity
    hashes the store claims to cover (the plan-build zero-record gate)."""

    def __init__(self, store: ProgramStore, entries: Dict[str, Dict[str, Any]],
                 plan_idents: Tuple[str, ...], origin: str):
        self.store = store
        self.entries = dict(entries)
        self.plan_idents = set(plan_idents)
        self.origin = origin
        #: (keyId) -> deserialized callable, one per process
        self.loaded: Dict[str, Callable] = {}


_LOCK = threading.Lock()
_SESSIONS: Dict[str, _Session] = {}
_CAPTURES: List["_Capture"] = []
_STATS: Dict[str, Any] = {"hits": {}, "misses": {}, "exports": 0,
                          "exportErrors": 0}


def _bump(kind: str, label: str, n: int = 1) -> None:
    with _LOCK:
        bucket = _STATS[kind]
        bucket[label] = bucket.get(label, 0) + n


def stats() -> Dict[str, Any]:
    """Process-local accounting (always on, like ``faults.fired_counts``):
    ``{"hits": {component: n}, "misses": {reason: n}, "exports": n,
    "exportErrors": n}`` plus totals."""
    with _LOCK:
        out = {"hits": dict(_STATS["hits"]),
               "misses": dict(_STATS["misses"]),
               "exports": _STATS["exports"],
               "exportErrors": _STATS["exportErrors"]}
    out["hitsTotal"] = sum(out["hits"].values())
    out["missesTotal"] = sum(out["misses"].values())
    return out


def snapshot() -> Dict[str, Any]:
    """The post-mortem bundle's ``aot`` section + ``cli doctor``'s
    "programs" block source."""
    with _LOCK:
        sessions = [{"origin": s.origin, "dir": s.store.dirpath,
                     "entries": len(s.entries),
                     "planIdents": len(s.plan_idents),
                     "loaded": len(s.loaded)}
                    for s in _SESSIONS.values()]
        captures = len(_CAPTURES)
    return {"enabled": aot_enabled(), "supported": _aot.aot_supported(),
            "sessions": sessions, "captures": captures, "stats": stats()}


def sessions_active() -> bool:
    if _SESSIONS:
        return True
    return bool(os.environ.get(STORE_ENV)) and aot_enabled()


def active_captures() -> List[str]:
    with _LOCK:
        return [c.store.dirpath for c in _CAPTURES]


def close_sessions() -> None:
    with _LOCK:
        _SESSIONS.clear()


def reset() -> None:
    """Test isolation: drop sessions/captures/stats and any forced
    override (tests/conftest.py ``_no_programstore_leak``)."""
    global _enabled_override
    with _LOCK:
        _SESSIONS.clear()
        _CAPTURES.clear()
        _STATS["hits"] = {}
        _STATS["misses"] = {}
        _STATS["exports"] = 0
        _STATS["exportErrors"] = 0
    _enabled_override = None


def open_model_session(model_dir: str) -> Optional[_Session]:
    """Open (or refresh) the session over ``model_dir``'s manifest
    ``programs`` section — called by ``registry.load``/``swap`` BEFORE
    the warm pre-trace so every lookup can hit. Returns None (and opens
    nothing) when the store is disabled, unsupported, or the manifest
    carries no (or a corrupt) ``programs`` section — all of which simply
    mean the existing trace path runs."""
    if not aot_enabled() or not _aot.aot_supported():
        return None
    try:
        from ..manifest import CheckpointManifest
        from ..persistence import FORMAT_VERSION
        manifest, err = CheckpointManifest.load(model_dir, FORMAT_VERSION)
        if err is not None:
            return None
        section = manifest.programs
        entries = section.get("entries")
        if not isinstance(entries, dict) or not entries:
            return None
        idents = tuple(str(x) for x in section.get("planIdents", ())
                       if isinstance(x, str))
        store = ProgramStore(os.path.join(model_dir, PROGRAMS_DIR))
        sess = _Session(store, {str(k): dict(v)
                                for k, v in entries.items()
                                if isinstance(v, dict)},
                        idents, origin=model_dir)
        with _LOCK:
            _SESSIONS[store.dirpath] = sess
        from ..observability import blackbox as _blackbox
        _blackbox.record("aot.session", dir=model_dir,
                         entries=len(sess.entries))
        return sess
    except Exception as e:  # a bad store must never fail a model load
        logger.warning("AOT session open failed for %s (%s: %s); "
                       "serving will trace", model_dir,
                       type(e).__name__, e)
        return None


def open_env_session() -> Optional[_Session]:
    """The cross-model store pointed at by ``TG_AOT_STORE`` (sweep
    programs at train time live here; opened lazily on first use, entries
    read from the store metas — there is no manifest for it)."""
    d = os.environ.get(STORE_ENV)
    if not d or not aot_enabled() or not _aot.aot_supported():
        return None
    store = ProgramStore(d)
    with _LOCK:
        sess = _SESSIONS.get(store.dirpath)
    if sess is not None:
        return sess
    sess = _Session(store, store.entries(), (), origin="env")
    with _LOCK:
        _SESSIONS[store.dirpath] = sess
    return sess


# ---------------------------------------------------------------------------
# The read path: lookup + the fallback ladder
# ---------------------------------------------------------------------------

def _record_miss(kid: str, component: str, reason: str,
                 ledger_key: Optional[str], detail: Dict[str, Any],
                 fault: bool) -> None:
    """One rung of the fallback ladder: count it, classify the build the
    caller is about to pay as ``aot-miss``, and — for genuine artifact
    faults (mismatch / corrupt / deserialize / injected) — leave the
    typed FaultLog ``aot_fallback`` record the chaos oracles assert on.
    A plain ``absent`` miss is the populate path, not a fault."""
    _bump("misses", reason)
    from ..observability import blackbox as _blackbox
    from ..observability import ledger as _ledger
    from ..observability import metrics as _obs_metrics
    _obs_metrics.inc_counter(
        "tg_aot_miss_total", reason=reason, component=component,
        help="AOT program-store misses by reason (docs/serving.md "
        "'AOT cold start & the program store')")
    _ledger.note_aot_miss(ledger_key or kid, f"aot-miss ({reason})")
    _blackbox.record("aot.miss", key=kid, component=component,
                     reason=reason)
    if fault:
        from ..robustness.policy import FaultLog, FaultReport
        FaultLog.record(FaultReport(
            site="aot.load", kind="aot_fallback",
            detail={"key": kid, "component": component, "reason": reason,
                    **detail}))
        logger.warning("AOT artifact %s unusable (%s); falling back to "
                       "the trace path", kid, reason)


def lookup(fingerprint: str, bucket: int, component: str = "plan-segment",
           ledger_key: Optional[str] = None) -> Optional[Callable]:
    """Resolve one program from the open sessions. Returns the
    deserialized callable (bit-identical dispatch to the traced program)
    or None — in which case the caller traces, and the resulting ledger
    build (recorded under ``ledger_key``) classifies as ``aot-miss``
    when any session was active. Never raises on a request path."""
    if not aot_enabled():
        return None
    open_env_session()
    with _LOCK:
        sessions = list(_SESSIONS.values())
    if not sessions:
        return None
    kid = key_id(fingerprint, bucket)
    entry = None
    sess = None
    for s in sessions:
        cached = s.loaded.get(kid)
        if cached is not None:
            return cached
        e = s.entries.get(kid)
        if e is not None and entry is None:
            entry, sess = e, s
    if entry is None:
        _record_miss(kid, component, "absent", ledger_key,
                     {}, fault=False)
        return None
    try:
        # deterministic chaos entry: models a corrupt / truncated /
        # stale-jaxlib artifact discovered at load (docs/robustness.md)
        from ..robustness import faults
        faults.inject("aot.load", key=kid)
        want_jaxlib = _aot.current_jaxlib()
        if str(entry.get("jaxlib")) != want_jaxlib:
            _record_miss(kid, component, "jaxlib-mismatch", ledger_key,
                         {"entry": entry.get("jaxlib"),
                          "current": want_jaxlib}, fault=True)
            return None
        want_device = _aot.current_device_kind()
        if str(entry.get("deviceKind")) != want_device:
            _record_miss(kid, component, "device-kind-mismatch",
                         ledger_key,
                         {"entry": entry.get("deviceKind"),
                          "current": want_device}, fault=True)
            return None
        try:
            blob = sess.store.read_blob(entry)
        except StoreEntryError as e:
            _record_miss(kid, component, "corrupt", ledger_key,
                         {"error": str(e)[:200]}, fault=True)
            return None
        fn = _aot.load_callable(blob)
    except Exception as e:
        # injected faults land here too: any throw on the load path is
        # one typed fallback, never a request error
        _record_miss(kid, component, "deserialize-error", ledger_key,
                     {"error": f"{type(e).__name__}: {e}"[:200]},
                     fault=True)
        return None
    sess.loaded[kid] = fn
    _bump("hits", component)
    from ..observability import blackbox as _blackbox
    from ..observability import metrics as _obs_metrics
    _obs_metrics.inc_counter(
        "tg_aot_hits_total", component=component,
        help="AOT program-store hits (deserialized programs dispatched "
        "instead of traced; docs/serving.md)")
    _blackbox.record("aot.hit", key=kid, component=component,
                     bytes=entry.get("size"))
    sess.store.touch(kid)
    return fn


def plan_covered(plan_ident: str) -> bool:
    """True when any open session claims this plan identity — the plan's
    assembly is then an AOT hit, not a ledger build (plan.get_plan)."""
    if not aot_enabled():
        return False
    with _LOCK:
        return any(plan_ident in s.plan_idents for s in _SESSIONS.values())


def record_plan_hit(plan_ident: str) -> None:
    _bump("hits", "plan")
    from ..observability import blackbox as _blackbox
    from ..observability import metrics as _obs_metrics
    _obs_metrics.inc_counter(
        "tg_aot_hits_total", component="plan",
        help="AOT program-store hits (deserialized programs dispatched "
        "instead of traced; docs/serving.md)")
    _blackbox.record("aot.hit", key=plan_ident, component="plan")


def note_plan_miss(ledger_key: str) -> None:
    """A plan build with sessions active but no coverage: classify it
    ``aot-miss`` (plan.get_plan calls this right before record_build)."""
    _record_miss(ledger_key, "plan", "absent", ledger_key, {},
                 fault=False)


# ---------------------------------------------------------------------------
# The write path: capture scopes + offers
# ---------------------------------------------------------------------------

class _Capture:
    """One populate scope: offers export into ``store`` and, when the
    store lives inside a model dir, flush() commits the entries into the
    model's MANIFEST ``programs`` section (atomic rewrite)."""

    def __init__(self, store: ProgramStore, manifest_dir: Optional[str]):
        self.store = store
        self.manifest_dir = manifest_dir
        self.pending: Dict[str, Dict[str, Any]] = {}
        self.plan_idents: List[str] = []

    def flush(self) -> int:
        """Commit pending entries to the manifest + bound the store.
        Never raises — population is strictly best-effort."""
        try:
            self.store.gc()
            if self.manifest_dir is None or not self.pending:
                return len(self.pending)
            from ..manifest import CheckpointManifest
            from ..persistence import FORMAT_VERSION
            manifest, err = CheckpointManifest.load(self.manifest_dir,
                                                    FORMAT_VERSION)
            if err is not None:
                return 0
            section = manifest.programs if isinstance(
                manifest.programs, dict) else {}
            entries = dict(section.get("entries", {})
                           if isinstance(section.get("entries"), dict)
                           else {})
            entries.update(self.pending)
            idents = [str(x) for x in section.get("planIdents", ())
                      if isinstance(x, str)]
            for pi in self.plan_idents:
                if pi not in idents:
                    idents.append(pi)
            manifest.programs = {
                "version": PROGRAMS_VERSION,
                "jaxlib": _aot.current_jaxlib(),
                "deviceKind": _aot.current_device_kind(),
                "entries": entries,
                "planIdents": idents,
            }
            manifest.save()
            return len(self.pending)
        except Exception as e:
            logger.warning("AOT capture flush failed for %s (%s: %s)",
                           self.store.dirpath, type(e).__name__, e)
            return 0


@contextlib.contextmanager
def capture(model_dir: str):
    """Populate scope over ``model_dir``: traced first-bucket dispatches
    inside the block are exported into ``<model_dir>/programs/`` and
    committed into the manifest ``programs`` section on exit. No-op
    context when the store is disabled/unsupported."""
    if not aot_enabled() or not _aot.aot_supported():
        yield None
        return
    cap = _Capture(ProgramStore(os.path.join(model_dir, PROGRAMS_DIR)),
                   manifest_dir=model_dir)
    with _LOCK:
        _CAPTURES.append(cap)
    try:
        yield cap
    finally:
        with _LOCK:
            if cap in _CAPTURES:
                _CAPTURES.remove(cap)
        cap.flush()


def offer_segment(fingerprint: str, bucket: int, jitted_fn: Callable,
                  args: Tuple[Any, ...], component: str = "plan-segment",
                  identity: str = "", plan_ident: Optional[str] = None
                  ) -> int:
    """A dispatch site just *traced* a program the store did not have:
    export + persist it into every active capture scope (and the
    ``TG_AOT_STORE`` cross-model store when configured). One flag check
    when nothing is active; export failures are counted, never raised.
    Returns the number of stores written."""
    kid = key_id(fingerprint, bucket)
    with _LOCK:
        # a capture that already holds this key skips the (re-)export;
        # the env store is refreshed (overwriting heals stale-jaxlib
        # entries the lookup just refused)
        targets: List[Tuple[ProgramStore, Optional[_Capture]]] = [
            (c.store, c) for c in _CAPTURES if kid not in c.pending]
    env_sess = open_env_session() if os.environ.get(STORE_ENV) else None
    if env_sess is not None:
        targets.append((env_sess.store, None))
    if not targets or not aot_enabled() or not _aot.aot_supported():
        return 0
    key = {"fingerprint": fingerprint, "bucket": int(bucket),
           "jaxlib": _aot.current_jaxlib(),
           "deviceKind": _aot.current_device_kind(),
           "component": component, "identity": identity,
           "planIdent": plan_ident}
    try:
        blob = _aot.export_bytes(jitted_fn, args)
    except Exception as e:
        with _LOCK:
            _STATS["exportErrors"] += 1
        logger.warning("AOT export failed for %s (%s: %s); the program "
                       "stays process-local", kid, type(e).__name__, e)
        return 0
    written = 0
    for store, cap in targets:
        try:
            meta = store.put(key, blob)
        except OSError as e:
            logger.warning("AOT store write failed in %s (%s: %s)",
                           store.dirpath, type(e).__name__, e)
            continue
        written += 1
        if cap is not None:
            cap.pending[kid] = meta
            if plan_ident and plan_ident not in cap.plan_idents:
                cap.plan_idents.append(plan_ident)
        else:
            env_sess.entries[kid] = meta
    if written:
        with _LOCK:
            _STATS["exports"] += 1
        from ..observability import blackbox as _blackbox
        _blackbox.record("aot.export", key=kid, component=component,
                         bytes=len(blob), stores=written)
    return written


def offer_plan_ident(plan_ident: str) -> None:
    """Record a plan identity as covered in every active capture (called
    by plan.get_plan when a capture scope is active, so a populated
    manifest can suppress the plan-build ledger record next load)."""
    with _LOCK:
        for cap in _CAPTURES:
            if plan_ident not in cap.plan_idents:
                cap.plan_idents.append(plan_ident)


# ---------------------------------------------------------------------------
# Save-time population (persistence.save_model)
# ---------------------------------------------------------------------------

def serve_plan_for(model, rows: int):
    """The model's serve-path transform plan (built or fetched from the
    plan LRU with exactly the key ``compiled_score_function`` uses), or
    None when planning is off / infeasible."""
    from .. import plan as _plan
    from ..local.scoring import serve_table_builder
    table = serve_table_builder(model)([{} for _ in range(max(1, rows))])
    return _plan.get_plan(
        model.stages, table, keep_intermediates=False,
        extra_keep=[f.name for f in model.result_features], cat="score")


def populate_for_save(model, path: str, rows: Optional[int] = None) -> int:
    """Export the model's serve-path programs into ``<path>/programs/``
    + the manifest ``programs`` section at *save* time, so a fresh
    process's ``registry.load`` deserializes instead of tracing
    (``save_model`` calls this after the manifest commits; TG_AOT_SAVE=0
    defers population to the first warm load). The export reconstructs
    each segment's traced avals from the plan's zero-row probe — no
    dispatch, no device work. Returns segments exported; never raises."""
    if not save_populate_enabled() or not _aot.aot_supported():
        return 0
    try:
        from .. import plan as _plan
        from ..observability import ledger as _ledger
        from ..serving.warmup import _warm_rows
        with _ledger.subsystem_scope("serve"):
            p = serve_plan_for(model, _warm_rows(rows))
        if p is None:
            return 0
        with capture(path):
            return _plan.export_plan_programs(p)
    except Exception as e:
        logger.warning("AOT save-time populate failed for %s (%s: %s); "
                       "the first warm load will populate instead",
                       path, type(e).__name__, e)
        return 0
