"""Multi-host bootstrap over jax.distributed.

The reference scales out through Spark's driver/executor cluster (reference:
OpWorkflowRunner/OpApp submitting to a Spark master; shuffle + netty RPC as
the communication backend, SURVEY §2.10 P5). Here the cluster substrate is
``jax.distributed``: each host process calls :func:`initialize`, after which
``jax.devices()`` is the GLOBAL device list and the same ``Mesh``-based code
(mesh.py, sharded.py) spans hosts — XLA routes collectives over ICI within a
TPU slice and DCN across slices. Nothing else in the framework changes
between one chip and a multi-host pod: that is the point of the design.

Typical pod usage (one process per host)::

    from transmogrifai_tpu.parallel import distributed, make_mesh, MeshSpec
    distributed.initialize()              # env-driven on TPU pods
    mesh = make_mesh(MeshSpec(data=-1, model=4))
    workflow.with_mesh(mesh).train()
"""
from __future__ import annotations

import logging
import os
from typing import Optional

import jax

logger = logging.getLogger(__name__)


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join (or bootstrap) the multi-host runtime.

    On TPU pods all three arguments are discovered from the environment by
    ``jax.distributed.initialize`` (TPU metadata); on CPU/GPU clusters pass
    them explicitly or via ``JAX_COORDINATOR_ADDRESS`` /
    ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``. Idempotent: a second call in
    the same process is a no-op, and single-process runs (no coordinator
    discoverable) are left untouched."""
    # already-initialized check WITHOUT touching jax.process_count(): that
    # would initialize the XLA backend, after which jax.distributed refuses
    # to start (it must run before any backend init). jax>=0.4.34 exposes a
    # public probe; fall back to attempting init on older versions
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None and is_init():
        return  # already initialized
    coordinator_address = (coordinator_address
                           or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        # TPU pod: fully env-discovered; plain single process: nothing to do.
        # Failures here are LOGGED, not swallowed — a wedged pod bootstrap
        # must be visible even though single-process fallback is legitimate
        try:
            jax.distributed.initialize()
        except Exception as e:  # pragma: no cover - env specific
            logger.warning(
                "jax.distributed auto-discovery failed (%s: %s); continuing "
                "single-process. If this host is part of a pod, set "
                "JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / "
                "JAX_PROCESS_ID explicitly.", type(e).__name__, e)
        return
    # explicitly configured coordinator: fail loud — a typo'd address or a
    # missing peer must never silently degrade a pod job to one host. The
    # one exception keeps initialize() idempotent on jax versions without
    # is_initialized(): a repeat call surfaces as jax's own
    # "already initialized" RuntimeError, which is a successful no-op here
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:  # pragma: no cover - jax version drift
        # jax's double-init message: "distributed.initialize should only be
        # called once."; older variants say "already initialized"
        msg = str(e).lower()
        if is_init is None and ("only be called once" in msg
                                or "already initialized" in msg):
            return
        raise


def _count_transfer_bytes(arr, direction: str) -> None:
    """Fold one successful link crossing into the transfer accounting
    (tg_transfer_bytes_total{direction=h2d|d2h}) — zero-write when metrics
    are off, so the hot path pays nothing un-observed. Device→device
    re-placements count as h2d: on tunneled backends they ride the same
    link, and the packed-upload A/B wants every crossing visible."""
    from ..observability import metrics as _obs_metrics
    if not _obs_metrics.metrics_enabled():
        return
    nbytes = getattr(arr, "nbytes", None)
    if nbytes:
        _obs_metrics.inc_counter(
            "tg_transfer_bytes_total", float(nbytes), direction=direction,
            help="bytes moved across the host<->device link")


def fetch_to_host(arr, policy=None, site: str = "distributed.to_host"):
    """Device→host transfer guarded by a retry policy.

    On tunneled backends the host link is the flakiest hop of the training
    path (transient UNAVAILABLE / connection resets); a failed metric
    transfer used to abort the whole sweep even though the device result was
    intact and re-readable. Retries re-issue only the transfer — device
    state is untouched. Deterministic fault site: ``distributed.to_host``."""
    import numpy as np

    from ..robustness import faults
    from ..robustness.policy import RetryPolicy
    policy = policy or RetryPolicy(base_delay=0.01)

    def pull():
        faults.inject(site)
        return np.asarray(arr)

    out = policy.execute(pull, site=site)
    _count_transfer_bytes(out, "d2h")
    return out


def retrying_device_put(x, sharding=None, policy=None,
                        site: str = "distributed.device_put"):
    """Host→device placement guarded by a retry policy (the dual of
    :func:`fetch_to_host`). Fault site: ``distributed.device_put``."""
    from ..robustness import faults
    from ..robustness.policy import RetryPolicy
    policy = policy or RetryPolicy(base_delay=0.01)

    def put():
        faults.inject(site)
        return (jax.device_put(x, sharding) if sharding is not None
                else jax.device_put(x))

    out = policy.execute(put, site=site)
    _count_transfer_bytes(out, "h2d")
    return out


def is_primary() -> bool:
    """True on the process that should write models/metrics (the reference's
    driver role)."""
    return jax.process_index() == 0


def barrier(name: str = "sync") -> None:
    """Cross-host synchronization point (e.g. before reading a model another
    host just wrote)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
