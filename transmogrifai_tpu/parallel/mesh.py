"""Device-mesh construction.

Replaces the reference's Spark cluster topology (driver + executors, reference:
core/.../OpWorkflowRunner.scala, utils/.../spark/) with a named
``jax.sharding.Mesh``. Axis conventions:

* ``data``  — row axis of the FeatureTable (P1 in SURVEY §2.10): every
  per-row map and monoid reduce shards here; XLA turns reduces into psum
  over ICI.
* ``model`` — the hyperparameter × fold batch axis of ModelSelector sweeps
  (P2): each chip fits its slice of configurations independently.

Multi-host: under ``jax.distributed`` the same code sees the global device
list, ICI within a slice and DCN across slices — nothing here changes.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape; axes sized -1 absorb remaining devices."""
    data: int = -1
    model: int = 1

    def resolve(self, n_devices: int) -> Tuple[int, int]:
        data, model = self.data, self.model
        if data == -1 and model == -1:
            raise ValueError("only one mesh axis may be -1")
        if model == -1:
            model = n_devices // max(data, 1)
        if data == -1:
            data = n_devices // max(model, 1)
        if data * model != n_devices:
            raise ValueError(
                f"mesh {data}x{model} does not cover {n_devices} devices")
        return data, model


def make_mesh(spec: MeshSpec = MeshSpec(),
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    data, model = spec.resolve(len(devices))
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, axis_names=("data", "model"))


def default_mesh() -> Mesh:
    """All visible devices on the data axis (pure data parallelism)."""
    return make_mesh(MeshSpec(data=-1, model=1))


def data_parallel_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard axis 0 (rows) over 'data', replicate the rest."""
    return NamedSharding(mesh, P("data", *([None] * (ndim - 1))))
