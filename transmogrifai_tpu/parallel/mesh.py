"""Device-mesh construction.

Replaces the reference's Spark cluster topology (driver + executors, reference:
core/.../OpWorkflowRunner.scala, utils/.../spark/) with a named
``jax.sharding.Mesh``. Axis conventions:

* ``data``  — row axis of the FeatureTable (P1 in SURVEY §2.10): every
  per-row map and monoid reduce shards here; XLA turns reduces into psum
  over ICI.
* ``model`` — the hyperparameter × fold batch axis of ModelSelector sweeps
  (P2): each chip fits its slice of configurations independently.

Multi-host: under ``jax.distributed`` the same code sees the global device
list, ICI within a slice and DCN across slices — nothing here changes.
"""
from __future__ import annotations

import os

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: sweep-engagement cost model (docs/parallel.md "The downgrade cost
#: model"). Engaging the mesh prices in per-program collectives (psums over
#: every cross-row reduce of the fit), cross-device layout moves around the
#: config axis, and the GSPMD partitioner's fixed per-program overhead —
#: none of which shrink with the problem. Measured on the 8-virtual-device
#: CPU host (shared cores, so the ratio isolates overhead from parallel
#: win): at 8192 rows/chip the sharded sweep executes ~2.5x the
#: single-device fused wall; the overhead first falls inside run-to-run
#: noise above ~16k rows per chip and a handful of configs per model shard
#: (docs/benchmarks.md "Mesh cost model"). Below the thresholds the sweep
#: transparently downgrades to the single-device fused path — bit-identical
#: results, observable via tg_mesh_downgrade_total + span event.
MESH_MIN_ROWS_PER_CHIP_ENV = "TG_MESH_MIN_ROWS_PER_CHIP"
MESH_MIN_CONFIGS_PER_CHIP_ENV = "TG_MESH_MIN_CONFIGS_PER_CHIP"
MESH_FORCE_ENV = "TG_MESH_FORCE"
DEFAULT_MIN_ROWS_PER_CHIP = 16384
DEFAULT_MIN_CONFIGS_PER_CHIP = 4


def sweep_mesh_decision(mesh: Mesh, n_rows: int,
                        n_configs: int) -> Tuple[bool, Dict[str, object]]:
    """Engage-or-downgrade decision for a ``|configs| × rows`` sweep.

    Returns ``(engage, detail)``; ``detail`` carries the measured sizes and
    thresholds for the downgrade span event. ``TG_MESH_FORCE=1`` pins the
    mesh on regardless (bench A/B and mesh-path tests); setting either
    threshold env var to 0 disables that axis of the check."""
    if os.environ.get(MESH_FORCE_ENV, "") in ("1", "true"):
        return True, {"forced": True}
    min_rows = int(os.environ.get(MESH_MIN_ROWS_PER_CHIP_ENV,
                                  DEFAULT_MIN_ROWS_PER_CHIP))
    min_cfg = int(os.environ.get(MESH_MIN_CONFIGS_PER_CHIP_ENV,
                                 DEFAULT_MIN_CONFIGS_PER_CHIP))
    rows_per_chip = n_rows / max(mesh.shape.get("data", 1), 1)
    cfg_per_chip = n_configs / max(mesh.shape.get("model", 1), 1)
    detail = {
        "rowsPerChip": int(rows_per_chip), "minRowsPerChip": min_rows,
        "configsPerChip": int(cfg_per_chip), "minConfigsPerChip": min_cfg,
        "meshShape": dict(mesh.shape),
    }
    engage = rows_per_chip >= min_rows and cfg_per_chip >= min_cfg
    return engage, detail


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape; axes sized -1 absorb remaining devices."""
    data: int = -1
    model: int = 1

    def resolve(self, n_devices: int) -> Tuple[int, int]:
        data, model = self.data, self.model
        if data == -1 and model == -1:
            raise ValueError("only one mesh axis may be -1")
        if model == -1:
            model = n_devices // max(data, 1)
        if data == -1:
            data = n_devices // max(model, 1)
        if data * model != n_devices:
            raise ValueError(
                f"mesh {data}x{model} does not cover {n_devices} devices")
        return data, model


def make_mesh(spec: MeshSpec = MeshSpec(),
              devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    data, model = spec.resolve(len(devices))
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, axis_names=("data", "model"))


def default_mesh() -> Mesh:
    """All visible devices on the data axis (pure data parallelism)."""
    return make_mesh(MeshSpec(data=-1, model=1))


def data_parallel_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard axis 0 (rows) over 'data', replicate the rest."""
    return NamedSharding(mesh, P("data", *([None] * (ndim - 1))))
