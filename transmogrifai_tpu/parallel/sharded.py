"""Sharded execution of FeatureTable stats and ModelSelector sweeps.

The hot path (SURVEY §3.3): a ``|families| × |grid| × |folds|`` sweep. On one
chip it is a vmapped fit; across chips the batch axis shards over 'model' and
the row axis over 'data'. We annotate shardings with ``NamedSharding`` and let
pjit/XLA insert the psum collectives the reference got from Spark shuffles.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _pad_to(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def shard_table(table, mesh: Mesh):
    """Re-place every device-resident column row-sharded over 'data'.

    Rows are padded (with invalid/masked slots) to a multiple of the data-axis
    size so shards are equal — the analog of Spark repartitioning. Device-kind
    columns upload PACKED: all same-dtype columns stack into one (n_pad, W)
    block and all masks into one (n_pad, M) bool block, each transferred once
    with sharded layout (``P('data', None)``) and split back into per-column
    on-device views — O(dtypes) transfers instead of one 70–130 ms round trip
    per column on tunneled backends, and the shards land directly on their
    owning chips (no replicate-then-reshard hop).
    """
    from ..observability import metrics as _obs_metrics
    from ..table import Column, FeatureTable
    from ..utils.padding import pad_rows, padded_valid_mask
    from .distributed import retrying_device_put
    n_data = mesh.shape["data"]
    n = table.num_rows
    n_pad = _pad_to(max(n, n_data), n_data)
    pad = n_pad - n

    # gather the packable device-kind columns: per-dtype value planes
    # (width-1 columns count as width-1 planes) + one shared mask plane list
    by_dtype: dict = {}
    masked: list = []
    for name in table.column_names:
        col = table[name]
        if col.kind not in ("real", "binary", "vector", "prediction"):
            continue
        v = pad_rows(col.values, n_pad)
        by_dtype.setdefault(str(v.dtype), []).append(
            (name, v.reshape(n_pad, -1)))
        if pad or col.mask is not None:
            masked.append((name, padded_valid_mask(col.mask, n, n_pad)))

    # byte accounting (tg_transfer_bytes_total) happens once inside
    # retrying_device_put — only the upload COUNT is recorded here
    transfers = 0
    dev_vals: dict = {}
    for dt, parts in by_dtype.items():
        host = (np.concatenate([v for _, v in parts], axis=1)
                if len(parts) > 1 else parts[0][1])
        block = retrying_device_put(
            jnp.asarray(host),
            NamedSharding(mesh, P("data", None)), site="shard_table.upload")
        transfers += 1
        off = 0
        for name, v in parts:
            w = v.shape[1]
            dev_vals[name] = block[:, off:off + w]
            off += w
    dev_masks: dict = {}
    if masked:
        mhost = np.stack([m for _, m in masked], axis=1)     # (n_pad, M)
        mblock = retrying_device_put(
            jnp.asarray(mhost),
            NamedSharding(mesh, P("data", None)), site="shard_table.upload")
        transfers += 1
        for i, (name, _) in enumerate(masked):
            dev_masks[name] = mblock[:, i]
    if transfers:
        _obs_metrics.inc_counter(
            "tg_device_transfer_total", float(transfers),
            help="host→device uploads (packed: see docs/plan.md)")

    cols = {}
    for name in table.column_names:
        col = table[name]
        vals, mask = col.values, col.mask
        if name in dev_vals:
            v = np.asarray(col.values)
            vals = (dev_vals[name] if v.ndim > 1
                    else dev_vals[name].reshape(n_pad))
            mask = dev_masks.get(name)
        elif pad:
            vals = pad_rows(vals, n_pad)
            mask = padded_valid_mask(mask, n, n_pad)
        cols[name] = Column(col.feature_type, vals, mask, col.metadata)
    key = table.key
    if key is not None and pad:
        key = np.concatenate([key, np.full(pad, None, dtype=object)])
    return FeatureTable(cols, num_rows=n_pad, key=key)


def sharded_fit_batch(family, X, y, weights, grid: Dict[str, jnp.ndarray],
                      num_classes: int, mesh: Mesh):
    """Run ``family.fit_batch`` with the config batch sharded over 'model' and
    rows over 'data'. Returns (params, scores) both model-sharded.

    The B axis is padded to a multiple of the model-axis size with repeated
    configurations (harmless: they are discarded by the caller's argmax over
    the original B prefix)."""
    n_model = mesh.shape["model"]
    B, n = weights.shape
    B_pad = _pad_to(B, n_model)
    if B_pad != B:
        idx = jnp.arange(B_pad) % B  # wrap-around repeat covers reps > B
        weights = weights[idx]
        grid = {k: v[idx] for k, v in grid.items()}

    x_sh = NamedSharding(mesh, P("data", None))
    row_sh = NamedSharding(mesh, P("data"))
    w_sh = NamedSharding(mesh, P("model", "data"))
    g_sh = NamedSharding(mesh, P("model"))
    X = jax.device_put(X, x_sh)
    y = jax.device_put(y, row_sh)
    weights = jax.device_put(weights, w_sh)
    grid = {k: jax.device_put(v, g_sh) for k, v in grid.items()}

    params = family.fit_batch(X, y, weights, grid, num_classes)
    scores = family.predict_batch(params, X, num_classes)
    return params, scores, B  # B = original (unpadded) batch size


def shard_rows(X, mask, mesh: Mesh):
    """Row-shard (X, mask) over 'data', padding to an equal-shard length.

    Pad rows carry mask=False so every masked kernel ignores them; callers
    that had no mask get the synthetic validity mask back. Returns
    (X_sharded, mask_sharded, original_n)."""
    X = jnp.asarray(X)
    n = X.shape[0]
    n_data = mesh.shape["data"]
    n_pad = _pad_to(max(n, n_data), n_data)
    if mask is None:
        mask = jnp.ones((n,), bool)
    mask = jnp.asarray(mask)
    if n_pad != n:
        X = jnp.pad(X, ((0, n_pad - n),) + ((0, 0),) * (X.ndim - 1))
        mask = jnp.pad(mask, ((0, n_pad - n),)
                       + ((0, 0),) * (mask.ndim - 1))
    X = jax.device_put(X, NamedSharding(
        mesh, P("data", *([None] * (X.ndim - 1)))))
    mask = jax.device_put(mask, NamedSharding(
        mesh, P("data", *([None] * (mask.ndim - 1)))))
    return X, mask, n


def sharded_col_stats(X, mask, mesh: Mesh):
    """colStats over row-sharded data — the reference's
    ``mllib.stat.Statistics.colStats`` (SanityChecker.scala:574-576) as one
    pjit program whose sums psum over ICI."""
    from ..ops.stats import col_stats
    X, mask, _ = shard_rows(X, mask, mesh)
    return col_stats(X, mask)
