"""Sharded execution of FeatureTable stats and ModelSelector sweeps.

The hot path (SURVEY §3.3): a ``|families| × |grid| × |folds|`` sweep. On one
chip it is a vmapped fit; across chips the batch axis shards over 'model' and
the row axis over 'data'. We annotate shardings with ``NamedSharding`` and let
pjit/XLA insert the psum collectives the reference got from Spark shuffles.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _pad_to(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def shard_table(table, mesh: Mesh):
    """Re-place every device-resident column row-sharded over 'data'.

    Rows are padded (with invalid/masked slots) to a multiple of the data-axis
    size so shards are equal — the analog of Spark repartitioning.
    """
    from ..table import Column, FeatureTable
    n_data = mesh.shape["data"]
    n = table.num_rows
    n_pad = _pad_to(max(n, n_data), n_data)
    pad = n_pad - n
    cols = {}
    for name in table.column_names:
        col = table[name]
        vals, mask = col.values, col.mask
        if col.kind in ("real", "binary", "vector", "prediction"):
            v = np.asarray(vals)
            if pad:
                v = np.concatenate([v, np.zeros((pad,) + v.shape[1:], v.dtype)])
                m = np.zeros(n_pad, bool)
                m[:n] = True if mask is None else np.asarray(mask)
                mask = m
            sh = NamedSharding(mesh, P("data", *([None] * (v.ndim - 1))))
            vals = jax.device_put(jnp.asarray(v), sh)
            if mask is not None:
                mask = jax.device_put(jnp.asarray(mask),
                                      NamedSharding(mesh, P("data")))
        elif pad:
            v = np.asarray(vals)
            filler = np.zeros((pad,) + v.shape[1:], v.dtype) \
                if v.dtype != object else np.full(pad, None, dtype=object)
            vals = np.concatenate([v, filler])
            m = np.zeros(n_pad, bool)
            m[:n] = True if mask is None else np.asarray(mask)
            mask = m
        cols[name] = Column(col.feature_type, vals, mask, col.metadata)
    key = table.key
    if key is not None and pad:
        key = np.concatenate([key, np.full(pad, None, dtype=object)])
    return FeatureTable(cols, num_rows=n_pad, key=key)


def sharded_fit_batch(family, X, y, weights, grid: Dict[str, jnp.ndarray],
                      num_classes: int, mesh: Mesh):
    """Run ``family.fit_batch`` with the config batch sharded over 'model' and
    rows over 'data'. Returns (params, scores) both model-sharded.

    The B axis is padded to a multiple of the model-axis size with repeated
    configurations (harmless: they are discarded by the caller's argmax over
    the original B prefix)."""
    n_model = mesh.shape["model"]
    B, n = weights.shape
    B_pad = _pad_to(B, n_model)
    if B_pad != B:
        idx = jnp.arange(B_pad) % B  # wrap-around repeat covers reps > B
        weights = weights[idx]
        grid = {k: v[idx] for k, v in grid.items()}

    x_sh = NamedSharding(mesh, P("data", None))
    row_sh = NamedSharding(mesh, P("data"))
    w_sh = NamedSharding(mesh, P("model", "data"))
    g_sh = NamedSharding(mesh, P("model"))
    X = jax.device_put(X, x_sh)
    y = jax.device_put(y, row_sh)
    weights = jax.device_put(weights, w_sh)
    grid = {k: jax.device_put(v, g_sh) for k, v in grid.items()}

    params = family.fit_batch(X, y, weights, grid, num_classes)
    scores = family.predict_batch(params, X, num_classes)
    return params, scores, B  # B = original (unpadded) batch size


def shard_rows(X, mask, mesh: Mesh):
    """Row-shard (X, mask) over 'data', padding to an equal-shard length.

    Pad rows carry mask=False so every masked kernel ignores them; callers
    that had no mask get the synthetic validity mask back. Returns
    (X_sharded, mask_sharded, original_n)."""
    X = jnp.asarray(X)
    n = X.shape[0]
    n_data = mesh.shape["data"]
    n_pad = _pad_to(max(n, n_data), n_data)
    if mask is None:
        mask = jnp.ones((n,), bool)
    mask = jnp.asarray(mask)
    if n_pad != n:
        X = jnp.pad(X, ((0, n_pad - n),) + ((0, 0),) * (X.ndim - 1))
        mask = jnp.pad(mask, ((0, n_pad - n),)
                       + ((0, 0),) * (mask.ndim - 1))
    X = jax.device_put(X, NamedSharding(
        mesh, P("data", *([None] * (X.ndim - 1)))))
    mask = jax.device_put(mask, NamedSharding(
        mesh, P("data", *([None] * (mask.ndim - 1)))))
    return X, mask, n


def sharded_col_stats(X, mask, mesh: Mesh):
    """colStats over row-sharded data — the reference's
    ``mllib.stat.Statistics.colStats`` (SanityChecker.scala:574-576) as one
    pjit program whose sums psum over ICI."""
    from ..ops.stats import col_stats
    X, mask, _ = shard_rows(X, mask, mesh)
    return col_stats(X, mask)
