"""Thin collectives layer over XLA's ICI/DCN primitives.

The reference's distributed-communication backend is Spark shuffle + netty RPC
+ Kryo broadcast (reference: utils/.../kryo/OpKryoRegistrator.scala; monoid
``reduce``/``reduceByKey`` calls throughout, e.g. SanityChecker.scala:433-440).
Here every cross-row reduction is an XLA collective over the named mesh —
psum/all_gather ride ICI within a slice, DCN across slices — and "collect to
driver" becomes a host_gather of an already-small device array.

These wrappers are for use inside ``jax.shard_map``-mapped functions; under
plain ``pjit`` XLA inserts equivalent collectives automatically from sharding
annotations, which is the preferred path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def psum(x, axis_name: str = "data"):
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name: str = "data"):
    return jax.lax.pmean(x, axis_name)


def pmax(x, axis_name: str = "data"):
    return jax.lax.pmax(x, axis_name)


def all_gather(x, axis_name: str = "data", axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str = "data", scatter_dimension: int = 0):
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)


def host_gather(x) -> np.ndarray:
    """Fully replicate/gather a (small) device array back to the host — the
    analog of Spark ``collect()`` for summaries/vocabularies."""
    return np.asarray(jax.device_get(x))
