"""Thin collectives layer over XLA's ICI/DCN primitives.

The reference's distributed-communication backend is Spark shuffle + netty RPC
+ Kryo broadcast (reference: utils/.../kryo/OpKryoRegistrator.scala; monoid
``reduce``/``reduceByKey`` calls throughout, e.g. SanityChecker.scala:433-440).
Here every cross-row reduction is an XLA collective over the named mesh —
psum/all_gather ride ICI within a slice, DCN across slices — and "collect to
driver" becomes a host_gather of an already-small device array.

These wrappers are for use inside ``jax.shard_map``-mapped functions; under
plain ``pjit`` XLA inserts equivalent collectives automatically from sharding
annotations, which is the preferred path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# jax moved shard_map from jax.experimental to the top-level namespace
# (0.4.35 added jax.shard_map; the experimental path still exists but warns
# on newer releases). Export the resolved symbol so framework + tests bind
# one name across jax versions.
try:  # pragma: no cover - version dependent
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - version dependent
    from jax.experimental.shard_map import shard_map  # noqa: F401


def axis_size(axis_name: str) -> int:
    """STATIC size of a mapped mesh axis from inside ``shard_map`` (drives
    Python-level hop loops, so it must be a concrete int, not a traced
    ``psum(1)``). jax 0.4.38+ exposes ``jax.lax.axis_size``; fall back to
    the trace-env frame on older releases."""
    size = getattr(jax.lax, "axis_size", None)
    if size is not None:  # pragma: no cover - version dependent
        return size(axis_name)
    from jax._src import core as _core
    return int(_core.axis_frame(axis_name))  # returns the size directly


def psum(x, axis_name: str = "data"):
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name: str = "data"):
    return jax.lax.pmean(x, axis_name)


def pmax(x, axis_name: str = "data"):
    return jax.lax.pmax(x, axis_name)


def all_gather(x, axis_name: str = "data", axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str = "data", scatter_dimension: int = 0):
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)


def host_gather(x) -> np.ndarray:
    """Fully replicate/gather a (small) device array back to the host — the
    analog of Spark ``collect()`` for summaries/vocabularies."""
    return np.asarray(jax.device_get(x))


def ring_allreduce(x, axis_name: str = "data"):
    """Bandwidth-optimal ring all-reduce built from ``ppermute`` hops.

    The explicit form of what XLA's psum lowers to on an ICI ring (the
    scaling-book recipe): reduce-scatter around the ring (N−1 hops, each
    device accumulating one shard), then all-gather the reduced shards
    (N−1 more hops). Shard-count = axis size; the leading axis of ``x``
    must be divisible by it. Use inside ``shard_map``; prefer plain psum
    unless you need to overlap the hops with compute — this exists so the
    comm layer's semantics are testable against psum hop by hop.
    """
    n = axis_size(axis_name)
    if n == 1:
        return x
    idx = jax.lax.axis_index(axis_name)
    shards = jnp.reshape(x, (n,) + (x.shape[0] // n,) + x.shape[1:])
    right = [(i, (i + 1) % n) for i in range(n)]

    # reduce-scatter: after hop h, each device holds the running sum of
    # shard (idx - h) from its h left neighbors
    acc = shards
    send = shards[(idx - 0) % n]
    for h in range(1, n):
        recv = jax.lax.ppermute(send, axis_name, right)
        k = (idx - h) % n
        summed = acc[k] + recv
        acc = acc.at[k].set(summed)
        send = summed
    # device idx now owns the fully reduced shard (idx + 1) % n
    own = (idx + 1) % n
    # all-gather: circulate the reduced shards around the ring
    out = acc
    send = acc[own]
    for h in range(1, n):
        recv = jax.lax.ppermute(send, axis_name, right)
        k = (own - h) % n
        out = out.at[k].set(recv)
        send = recv
    return jnp.reshape(out, x.shape)


def reduce_by_key(values, keys, num_keys: int, axis_name: str = "data"):
    """Monoid ``reduceByKey`` over row-sharded data — the reference's
    contingency/vocabulary pattern (SanityChecker.scala:433-440): each
    device segment-sums its local rows by key, then one psum merges the
    per-key partials across the mesh. values: (rows_local, ...) with
    leading row axis; keys: (rows_local,) int32 in [0, num_keys)."""
    local = jax.ops.segment_sum(values, keys, num_segments=num_keys)
    return jax.lax.psum(local, axis_name)


def broadcast_from_primary(x, axis_name: str = "data"):
    """Value of ``x`` on device 0 of the axis, on every device — the analog
    of a Spark driver broadcast (fitted vocab/thresholds out to workers)."""
    idx = jax.lax.axis_index(axis_name)
    zeroed = jnp.where(idx == 0, x, jnp.zeros_like(x))
    return jax.lax.psum(zeroed, axis_name)
