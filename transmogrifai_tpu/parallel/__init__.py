"""Multi-chip parallelism: mesh construction, collectives, sharded sweeps.

The TPU re-expression of the reference's parallelism axes (SURVEY §2.10):
row data-parallelism (Spark RDD partitions) becomes row-axis sharding over the
'data' mesh axis; model×fold task-parallelism (Scala Futures, pool of 8)
becomes batch-axis sharding over the 'model' mesh axis. XLA inserts the
collectives (psum over ICI) that Spark's shuffle/treeAggregate did.
"""
from .mesh import (
    MeshSpec, make_mesh, default_mesh, data_parallel_sharding,
    sweep_mesh_decision,
)
from .collectives import (
    psum, pmean, pmax, all_gather, reduce_scatter, host_gather, shard_map,
)
from .sharded import shard_table, sharded_fit_batch, sharded_col_stats
from . import distributed

__all__ = [
    "MeshSpec", "make_mesh", "default_mesh", "data_parallel_sharding",
    "sweep_mesh_decision",
    "psum", "pmean", "pmax", "all_gather", "reduce_scatter", "host_gather",
    "shard_map",
    "shard_table", "sharded_fit_batch", "sharded_col_stats", "distributed",
]
