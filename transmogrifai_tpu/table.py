"""FeatureTable — the columnar, device-resident replacement for the reference's
Spark DataFrame substrate.

Where the reference materializes a row-oriented ``DataFrame`` and runs stages as
row lambdas inside Catalyst (reference: readers/.../DataReader.scala:173,
core/.../utils/stages/FitStagesUtil.scala:96-119), the TPU build keeps a dict of
*columns*. Numeric columns live as device arrays (values + validity mask) that
jitted kernels consume directly and that shard over the mesh row axis; string /
list / map columns stay host-side (numpy object arrays) until a vectorizer
encodes them into device arrays — strings never cross the host→device boundary.
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple, Type

import numpy as np

from .types import FeatureType

#: column kinds whose values are numeric arrays eligible for device residency
#: (integral/date stay host-side int64 — TPU x64 is off and vectorizers emit
#: float32 blocks from them anyway)
DEVICE_KINDS = frozenset({"real", "binary", "vector", "prediction"})
#: column kinds kept host-side (object arrays / int64) until vectorized
HOST_KINDS = frozenset({"text", "text_list", "date_list", "geolocation",
                        "multipicklist", "map", "date", "integral"})


def _np(values) -> np.ndarray:
    return np.asarray(values)


@dataclass(frozen=True)
class Column:
    """One feature column.

    values:
      * kind 'real'/'binary': float32 (n,) — invalid slots hold 0.0
      * kind 'integral': int32 (n,) — invalid slots hold 0
      * kind 'date': int64 host array (n,) (epoch millis exceed int32/float32)
      * kind 'vector': float32 (n, d) device array, no mask
      * kind 'prediction': float32 (n, k) + ``keys`` metadata entry
      * kind 'text'/'map'/lists: numpy object array (n,)
    mask: bool (n,) validity mask; None means all-valid.
    metadata: free-form provenance (e.g. vector metadata under 'vector_meta').
    """
    feature_type: Type[FeatureType]
    values: Any
    mask: Optional[Any] = None
    metadata: Mapping[str, Any] = field(default_factory=dict)

    @property
    def kind(self) -> str:
        return self.feature_type.column_kind

    def __len__(self) -> int:
        return int(self.values.shape[0])

    @property
    def width(self) -> int:
        return int(self.values.shape[1]) if self.values.ndim > 1 else 1

    def valid_mask(self) -> np.ndarray:
        if self.mask is None:
            return np.ones(len(self), dtype=bool)
        return np.asarray(self.mask)

    def with_metadata(self, **kv) -> "Column":
        md = dict(self.metadata)
        md.update(kv)
        return replace(self, metadata=md)

    def to_device(self) -> "Column":
        """Move numeric storage onto the default device as jax arrays."""
        if self.kind not in DEVICE_KINDS:
            return self
        import jax.numpy as jnp
        vals = jnp.asarray(self.values)
        mask = None if self.mask is None else jnp.asarray(self.mask)
        return replace(self, values=vals, mask=mask)

    def to_host(self) -> "Column":
        vals = np.asarray(self.values)
        mask = None if self.mask is None else np.asarray(self.mask)
        return replace(self, values=vals, mask=mask)

    def take(self, idx: np.ndarray) -> "Column":
        vals = self.values[idx]
        mask = None if self.mask is None else self.mask[idx]
        return replace(self, values=vals, mask=mask)

    # -- constructors --------------------------------------------------------
    @staticmethod
    def of_values(feature_type: Type[FeatureType], raw: Sequence[Any]) -> "Column":
        """Build a column from raw python values (None/NaN = missing)."""
        kind = feature_type.column_kind
        n = len(raw)
        if kind in ("real", "binary", "integral", "date"):
            missing = [_is_missing_scalar(v) for v in raw]
            mask = np.array([not m for m in missing], dtype=bool)
            if kind == "real":
                vals = np.array([0.0 if m else float(v)
                                 for v, m in zip(raw, missing)], dtype=np.float32)
            elif kind == "binary":
                vals = np.array([0.0 if m else float(bool(v))
                                 for v, m in zip(raw, missing)], dtype=np.float32)
            else:  # integral/date: reference semantics are Long → host int64
                vals = np.array([0 if m else int(v)
                                 for v, m in zip(raw, missing)], dtype=np.int64)
            return Column(feature_type, vals, mask)
        if kind == "vector":
            vals = np.stack([np.asarray([] if v is None else v, dtype=np.float32)
                             for v in raw]) if n else np.zeros((0, 0), dtype=np.float32)
            return Column(feature_type, vals, None)
        if kind == "prediction":
            keys = sorted({k for d in raw if d is not None for k in d})
            vals = np.array([[float(d.get(k, 0.0)) for k in keys]
                             if d is not None else [0.0] * len(keys)
                             for d in raw], dtype=np.float32).reshape(n, len(keys))
            return Column(feature_type, vals, None, {"keys": tuple(keys)})
        # host kinds
        arr = np.empty(n, dtype=object)
        for i, v in enumerate(raw):
            arr[i] = v
        mask = np.array([not _is_missing(v) for v in raw], dtype=bool)
        return Column(feature_type, arr, mask)


def column_of_scalars(feature_type: Type[FeatureType],
                      raw: Sequence[Any]) -> Optional[Column]:
    """Vectorized dual of ``Column.of_values`` for numeric scalar kinds:
    one ``np.asarray`` sweep instead of a python loop calling
    ``float()``/``int()`` per cell — the serve-time request→table hot path
    (local/scoring.serve_table_builder; docs/benchmarks.md "Serving
    runtime"). Returns None whenever the batch is not homogeneous numeric
    (a None, a string, a FeatureType wrapper) — the caller falls back to
    ``of_values``, so semantics are byte-identical by construction:
    NaN = missing, invalid slots hold 0, binary truth-tests, integral
    truncation all match the per-cell path."""
    kind = feature_type.column_kind
    if kind not in ("real", "binary", "integral", "date") or not len(raw):
        return None
    try:
        vals = np.asarray(raw, dtype=np.float64)
    except (TypeError, ValueError):
        return None
    if vals.shape != (len(raw),):
        return None
    mask = ~np.isnan(vals)
    if kind == "real":
        return Column(feature_type,
                      np.where(mask, vals, 0.0).astype(np.float32), mask)
    if kind == "binary":
        return Column(feature_type,
                      (np.where(mask, vals, 0.0) != 0.0).astype(np.float32),
                      mask)
    # integral/date → host int64 (reference Long semantics); float cells
    # truncate toward zero exactly like int(v)
    if kind == "integral" or kind == "date":
        with np.errstate(invalid="ignore"):
            ints = np.where(mask, vals, 0.0).astype(np.int64)
        return Column(feature_type, ints, mask)
    return None


def _is_missing_scalar(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and np.isnan(v):
        return True
    return False


def _is_missing(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and np.isnan(v):
        return True
    if isinstance(v, (list, set, dict, tuple)) and len(v) == 0:
        return True
    return False


class FeatureTable:
    """Immutable-ish columnar table: name → Column, plus an optional key column.

    The TPU-native analog of the materialized raw DataFrame produced by
    ``DataReader.generateDataFrame`` (reference DataReader.scala:173-197).
    """

    KEY = "key"

    def __init__(self, columns: Dict[str, Column], num_rows: int,
                 key: Optional[np.ndarray] = None):
        self._columns = dict(columns)
        self.num_rows = num_rows
        self.key = key
        for name, col in self._columns.items():
            if len(col) != num_rows:
                raise ValueError(
                    f"column '{name}' has {len(col)} rows, table has {num_rows}")

    # -- access --------------------------------------------------------------
    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def __getitem__(self, name: str) -> Column:
        return self._columns[name]

    def get(self, name: str) -> Optional[Column]:
        return self._columns.get(name)

    @property
    def column_names(self) -> List[str]:
        return list(self._columns)

    def __len__(self) -> int:
        return self.num_rows

    # -- functional updates --------------------------------------------------
    def with_column(self, name: str, col: Column) -> "FeatureTable":
        cols = dict(self._columns)
        cols[name] = col
        return FeatureTable(cols, self.num_rows, self.key)

    def with_columns(self, new: Mapping[str, Column]) -> "FeatureTable":
        cols = dict(self._columns)
        cols.update(new)
        return FeatureTable(cols, self.num_rows, self.key)

    def select(self, names: Sequence[str]) -> "FeatureTable":
        return FeatureTable({n: self._columns[n] for n in names}, self.num_rows, self.key)

    def drop(self, names: Sequence[str]) -> "FeatureTable":
        gone = set(names)
        return FeatureTable(
            {n: c for n, c in self._columns.items() if n not in gone},
            self.num_rows, self.key)

    def take(self, idx: np.ndarray) -> "FeatureTable":
        idx = np.asarray(idx)
        key = None if self.key is None else self.key[idx]
        return FeatureTable({n: c.take(idx) for n, c in self._columns.items()},
                            int(idx.shape[0]), key)

    def to_device(self) -> "FeatureTable":
        """Move every device-kind column onto the default device with O(1)
        host→device transfers: values pack into one stacked block per dtype
        and masks into one bool block, transfer once, and split back into
        per-column device views (cheap on-device slices). The per-column
        ``Column.to_device`` path costs one ~70-130 ms round-trip per column
        on tunneled backends — O(columns) where this is O(dtypes).
        """
        import jax.numpy as jnp

        from .observability import metrics as _obs_metrics
        todo = [(n, c) for n, c in self._columns.items()
                if c.kind in DEVICE_KINDS
                and isinstance(c.values, np.ndarray)]
        if not todo:
            return FeatureTable(
                {n: c.to_device() for n, c in self._columns.items()},
                self.num_rows, self.key)
        by_dtype: Dict[str, List[Tuple[str, np.ndarray]]] = {}
        masked: List[Tuple[str, np.ndarray]] = []
        for n, c in todo:
            by_dtype.setdefault(str(c.values.dtype), []).append(
                (n, np.ascontiguousarray(c.values).reshape(-1)))
            if c.mask is not None:
                masked.append((n, np.asarray(c.mask)))
        transfers = 0
        nbytes = 0
        flat_dev: Dict[str, Any] = {}
        for dt, parts in by_dtype.items():
            host = (np.concatenate([v for _, v in parts])
                    if len(parts) > 1 else parts[0][1])
            flat_dev[dt] = jnp.asarray(host)
            transfers += 1
            nbytes += host.nbytes
        mask_dev = None
        if masked:
            mhost = (np.concatenate([m for _, m in masked])
                     if len(masked) > 1 else masked[0][1])
            mask_dev = jnp.asarray(mhost)
            transfers += 1
            nbytes += mhost.nbytes
        _obs_metrics.inc_counter(
            "tg_device_transfer_total", float(transfers),
            help="host→device uploads (packed: see docs/plan.md)")
        _obs_metrics.inc_counter(
            "tg_transfer_bytes_total", float(nbytes), direction="h2d",
            help="bytes moved across the host<->device link")
        offs = {dt: 0 for dt in flat_dev}
        moff = 0
        mask_at: Dict[str, Any] = {}
        for n, m in masked:
            mask_at[n] = mask_dev[moff:moff + m.shape[0]]
            moff += m.shape[0]
        cols: Dict[str, Column] = {}
        for n, c in self._columns.items():
            if c.kind not in DEVICE_KINDS or not isinstance(c.values, np.ndarray):
                cols[n] = c.to_device()
                continue
            dt = str(c.values.dtype)
            size = int(c.values.size)
            vals = flat_dev[dt][offs[dt]:offs[dt] + size].reshape(
                c.values.shape)
            offs[dt] += size
            cols[n] = replace(c, values=vals, mask=mask_at.get(n))
        return FeatureTable(cols, self.num_rows, self.key)

    # -- row view (local scoring / tests) ------------------------------------
    def row(self, i: int) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, col in self._columns.items():
            valid = col.mask is None or bool(np.asarray(col.mask)[i])
            if not valid:
                out[name] = None
            else:
                v = np.asarray(col.values)[i]
                out[name] = v.tolist() if isinstance(v, np.ndarray) else (
                    v.item() if isinstance(v, np.generic) else v)
        return out

    def iter_rows(self) -> Iterator[Dict[str, Any]]:
        for i in range(self.num_rows):
            yield self.row(i)

    # -- constructors --------------------------------------------------------
    @staticmethod
    def from_columns(data: Mapping[str, Tuple[Type[FeatureType], Sequence[Any]]],
                     key: Optional[Sequence[str]] = None) -> "FeatureTable":
        cols = {name: Column.of_values(ft, vals) for name, (ft, vals) in data.items()}
        n = len(next(iter(cols.values()))) if cols else 0
        karr = None if key is None else np.asarray(key, dtype=object)
        return FeatureTable(cols, n, karr)
