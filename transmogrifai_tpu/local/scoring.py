"""Spark-free ("local") serve-time scoring.

The reference's `local` module folds a ``Map[String,Any]`` through each stage's
``transformKeyValue`` row lambda, converting Spark-wrapped models through MLeap
(reference: local/src/main/scala/com/salesforce/op/local/OpWorkflowModelLocal.scala:93-197).
Here every Transformer already exposes the row-level dual ``transform_row``, so
the scorer is simply a fold over the topologically-ordered fitted stages — no
model-conversion layer is needed. For serving at throughput, use
:func:`micro_batch_score_function`, which runs the columnar (jitted) path on
micro-batches — the TPU replacement for MLeap row scoring.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence

import numpy as np

from ..table import Column, FeatureTable


def score_function(model) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
    """Row-at-a-time scorer (reference OpWorkflowModelLocal.scoreFunction).

    Returns ``fn(raw_row) -> {result feature name: value}`` where ``raw_row``
    maps raw feature names to python values (None = missing).
    """
    stages = model.stages  # farthest-first layers == topological order
    result_names = [f.name for f in model.result_features]
    raw_gens = [(f.name, f.origin_stage) for f in model.raw_features]

    def score(row: Dict[str, Any]) -> Dict[str, Any]:
        # raw features come from each generator's extract_fn, exactly like the
        # batch reader path (DataReader.generateDataFrame row build)
        acc = {name: gen.extract(row) for name, gen in raw_gens}
        for stage in stages:
            out = stage.get_output()
            acc[out.name] = stage.transform_row(acc)
        return {name: acc[name] for name in result_names}

    return score


def micro_batch_score_function(model) -> Callable[[Sequence[Dict[str, Any]]], List[Dict[str, Any]]]:
    """Micro-batch scorer: builds a FeatureTable from a list of raw rows and
    runs the columnar/jitted DAG pass — the serving path that keeps the TPU
    busy (SURVEY §2.10 P4: streaming micro-batches)."""
    raw_features = model.raw_features
    result_features = model.result_features

    def score(rows: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
        cols = {
            f.name: Column.of_values(
                f.feature_type, [f.origin_stage.extract(r) for r in rows])
            for f in raw_features
        }
        table = FeatureTable(cols, len(rows))
        scored = model.score(table=table)
        out: List[Dict[str, Any]] = []
        for i in range(len(rows)):
            rec: Dict[str, Any] = {}
            for f in result_features:
                col = scored[f.name]
                valid = col.mask is None or bool(np.asarray(col.mask)[i])
                if not valid:
                    rec[f.name] = None
                    continue
                v = np.asarray(col.values)[i]
                if f.type_name == "Prediction":
                    keys = col.metadata.get("keys", ())
                    rec[f.name] = {k: float(x) for k, x in zip(keys, v)}
                else:
                    rec[f.name] = v.tolist() if isinstance(v, np.ndarray) else (
                        v.item() if isinstance(v, np.generic) else v)
            out.append(rec)
        return out

    return score
