"""Spark-free ("local") serve-time scoring.

The reference's `local` module folds a ``Map[String,Any]`` through each stage's
``transformKeyValue`` row lambda, converting Spark-wrapped models through MLeap
(reference: local/src/main/scala/com/salesforce/op/local/OpWorkflowModelLocal.scala:93-197).
Here every Transformer already exposes the row-level dual ``transform_row``, so
the scorer is simply a fold over the topologically-ordered fitted stages — no
model-conversion layer is needed. For serving at throughput, use
:func:`micro_batch_score_function`, which runs the columnar (jitted) path on
micro-batches — the TPU replacement for MLeap row scoring.
"""
from __future__ import annotations

import logging
import time

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import metrics as _obs_metrics
from ..observability.trace import span as _obs_span
from ..table import Column, FeatureTable

logger = logging.getLogger(__name__)

#: per-row error key emitted by micro-batch quarantine (the row could not be
#: scored; every result feature is None and this key carries the reason)
SCORE_ERROR_KEY = "__score_error__"


class ScoreSchemaError(ValueError):
    """Serve-time input does not match the fitted schema (missing column,
    unconvertible dtype, wrong vector width). Raised with the offending
    column and the expected-vs-actual detail *before* the data reaches the
    jitted program — an XLA trace/shape error names none of that."""


def score_function(model) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
    """Row-at-a-time scorer (reference OpWorkflowModelLocal.scoreFunction).

    Returns ``fn(raw_row) -> {result feature name: value}`` where ``raw_row``
    maps raw feature names to python values (None = missing).
    """
    stages = model.stages  # farthest-first layers == topological order
    result_names = [f.name for f in model.result_features]
    raw_gens = [(f.name, f.origin_stage) for f in model.raw_features]

    def score(row: Dict[str, Any]) -> Dict[str, Any]:
        # per-request latency: the O(1)-memory streaming histogram keeps
        # p50/p95/p99 live over unbounded request streams
        # (docs/observability.md "Scoring telemetry")
        t0 = (time.perf_counter()
              if _obs_metrics.metrics_enabled() else None)
        # raw features come from each generator's extract_fn, exactly like the
        # batch reader path (DataReader.generateDataFrame row build)
        acc = {name: gen.extract(row) for name, gen in raw_gens}
        for stage in stages:
            out = stage.get_output()
            acc[out.name] = stage.transform_row(acc)
        result = {name: acc[name] for name in result_names}
        if t0 is not None:
            _obs_metrics.observe(
                "tg_score_request_seconds", time.perf_counter() - t0,
                help="per-request scoring latency (row path)")
        return result

    return score


def compiled_score_function(model):
    """Fused serve path: ONE jitted XLA program per device-fusable segment.

    A thin consumer of the shared transform-plan compiler
    (``transmogrifai_tpu/plan.py``) — the TPU-first analog of the
    reference's layer fusion + MLeap serving (reference
    FitStagesUtil.applyOpTransformations:96-119,
    OpWorkflowModelLocal.scala:93-197). The planner partitions the fitted
    stage run into host waves (string pivots, tokenizers — eager) and
    device segments (numeric vectorizers → VectorsCombiner → SanityChecker
    keep-slice → traceable Prediction emission — one jit each, reused
    across micro-batches via row bucket padding). What this wrapper adds is
    the serve-time schema guard: descriptive :class:`ScoreSchemaError`
    *before* any data reaches a jitted program.

    Returns ``score(table: FeatureTable) -> FeatureTable`` with the result
    features plus every column the retained host stages produce; fused
    INTERMEDIATE columns not consumed downstream are not materialized —
    XLA dead-code-eliminates them (unlike ``model.score``'s
    keep-everything default).
    """
    from .. import plan as _plan

    stages = list(model.stages)
    result_names = [f.name for f in model.result_features]

    # the fitted column set: every column the serve pass reads that no
    # stage of the model produces must arrive in the input table — checked
    # up front with a descriptive error instead of a KeyError deep in a
    # host stage or a trace error inside XLA
    produced_all = {s.get_output().name for s in stages}
    required_external: List[str] = []
    for s in stages:
        # response features are train-only: scoring never reads the label
        names = (s.device_inputs() if hasattr(s, "device_inputs")
                 else [f.name for f in s.input_features if not f.is_response])
        for nm in names:
            if nm not in produced_all and nm not in required_external:
                required_external.append(nm)

    # fitted input schema for the fused program: per-column trailing shape
    # (vector width). Seeded from the training table when the model still
    # carries one; otherwise pinned by the first scored batch. Violations
    # raise ScoreSchemaError at the boundary instead of a shape/trace error
    # inside XLA (which would also silently recompile on every new width).
    expected_shapes: Dict[str, Tuple[int, ...]] = {}
    ttbl = getattr(model, "train_table", None)
    if ttbl is not None:
        for nm in required_external:
            if nm in ttbl.column_names:
                expected_shapes[nm] = tuple(np.shape(ttbl[nm].values)[1:])

    def _validated_input(tbl: FeatureTable, nm: str) -> Column:
        if nm not in tbl.column_names:
            raise ScoreSchemaError(
                f"input is missing column '{nm}' required by the fitted "
                f"serve program; table has {sorted(tbl.column_names)}")
        col = tbl[nm]
        try:
            v = np.asarray(col.values, dtype=np.float32)
        except (TypeError, ValueError) as e:
            dt = getattr(col.values, "dtype", type(col.values).__name__)
            raise ScoreSchemaError(
                f"column '{nm}': values of dtype {dt} cannot convert to "
                f"float32 for the fused serve program ({e})") from e
        want = expected_shapes.get(nm)
        if want is not None and tuple(v.shape[1:]) != want:
            raise ScoreSchemaError(
                f"column '{nm}': per-row shape {tuple(v.shape[1:])} does "
                f"not match the fitted schema {want}")
        expected_shapes.setdefault(nm, tuple(v.shape[1:]))
        return col

    def score(table: FeatureTable) -> FeatureTable:
        missing = [nm for nm in required_external
                   if nm not in table.column_names]
        if missing:
            raise ScoreSchemaError(
                f"input is missing column(s) {missing} required by the "
                f"fitted model; table has {sorted(table.column_names)}")
        plan = _plan.get_plan(stages, table, keep_intermediates=False,
                              extra_keep=result_names, cat="score")
        if plan is None:       # planning off / chaos / nothing to fuse
            return model.score(table=table)
        for nm in plan.device_table_inputs(table):
            # validate BEFORE any jit sees the batch
            _validated_input(table, nm)
        out = _plan.apply_planned(stages, table, keep_intermediates=False,
                                  extra_keep=result_names, cat="score")
        if out is None:        # planned run raised; recorded → eager
            return model.score(table=table)
        return out

    return score


def serve_table_builder(model) -> Callable[[Sequence[Dict[str, Any]]], FeatureTable]:
    """The serve-time table front: ``build(rows) -> FeatureTable`` running
    each raw feature's extract over the request rows. Shared by
    :func:`micro_batch_score_function`, the serving runtime
    (``serving/runtime.py``), and the warm-start plan fingerprint
    (``serving/warmup.py``) — all three must build byte-identical tables or
    the fingerprinted plan cache would miss on the first real request.

    Homogeneous numeric batches — the overwhelmingly common serve shape —
    take a vectorized path: plain-field extractors gather with one dict
    lookup per cell and convert through ``table.column_of_scalars`` (one
    numpy sweep) instead of ``Column.of_values``'s per-cell python loop;
    anything non-homogeneous (a None, a string, a custom extractor) falls
    back to the exact original path, so outputs are byte-identical
    (docs/benchmarks.md "Serving runtime" has the before/after)."""
    from ..readers.readers import _field_name_of
    from ..table import column_of_scalars
    raw_features = model.raw_features
    #: (feature, plain record field to gather, or None → stage.extract)
    extractors = [(f, _field_name_of(f.origin_stage.extract_fn))
                  for f in raw_features]

    def build(rows: Sequence[Dict[str, Any]]) -> FeatureTable:
        cols = {}
        dict_rows = all(isinstance(r, dict) for r in rows)
        for f, field in extractors:
            col = None
            if field is not None and dict_rows:
                # fast gather skips the FeatureType-unwrap extract() makes;
                # a wrapper (or any non-scalar) fails the numpy sweep and
                # re-extracts below, so semantics never diverge
                col = column_of_scalars(
                    f.feature_type, [r.get(field) for r in rows])
            if col is None:
                vals = [f.origin_stage.extract(r) for r in rows]
                col = column_of_scalars(f.feature_type, vals)
            if col is None:
                try:
                    col = Column.of_values(f.feature_type, vals)
                except (TypeError, ValueError) as e:
                    raise ScoreSchemaError(
                        f"raw feature '{f.name}' ({f.type_name}): value "
                        f"does not conform to the fitted schema "
                        f"({type(e).__name__}: {e})") from e
            cols[f.name] = col
        return FeatureTable(cols, len(rows))

    return build


def serve_record_builder(model) -> Callable[[FeatureTable, int], List[Dict[str, Any]]]:
    """``records(scored_table, n) -> [result dict]`` — the serve-time
    row-major view of a scored table (Prediction columns as {key: float}
    maps, masked slots as None)."""
    result_features = model.result_features

    def records(scored: FeatureTable, n: int) -> List[Dict[str, Any]]:
        # columnar → row-major in one ``tolist()`` C sweep per column
        # (identical python values: tolist() and .item() both produce the
        # nearest python float/int), instead of a numpy scalar indexing +
        # .item() round-trip per cell — with the table build, this was the
        # serve hot path (docs/benchmarks.md "Serving runtime")
        per_col: List[Tuple[str, Optional[list], list, Optional[Tuple]]] = []
        for f in result_features:
            col = scored[f.name]
            masks = None if col.mask is None else \
                np.asarray(col.mask).tolist()
            vals = np.asarray(col.values).tolist()
            keys = (tuple(col.metadata.get("keys", ()))
                    if f.type_name == "Prediction" else None)
            per_col.append((f.name, masks, vals, keys))
        out: List[Dict[str, Any]] = []
        for i in range(n):
            rec: Dict[str, Any] = {}
            for name, masks, vals, keys in per_col:
                if masks is not None and not masks[i]:
                    rec[name] = None
                elif keys is not None:
                    rec[name] = dict(zip(keys, vals[i]))
                else:
                    rec[name] = vals[i]
            out.append(rec)
        return out

    return records


class ServeStages:
    """Staged decomposition of :func:`micro_batch_score_function` for the
    pipelined serving dataplane (serving/runtime.py; docs/serving.md
    "Pipelined dataplane"). The monolithic scorer runs
    build → compile → flatten back-to-back on one thread; the pipeline
    needs the same three steps as separable stages so batch formation,
    device dispatch, and result resolution can overlap across flushes:

    * :meth:`gather` — request rows → FeatureTable, one columnar sweep
      per raw feature through **pooled per-bucket scratch blocks**: the
      per-flush gather list (``[r.get(field) for r in rows]``) is
      replaced by an object-dtype scratch array reused across flushes
      (grown to the enclosing power-of-two bucket, mirroring the plan
      padding buckets), so the steady state allocates nothing per flush.
      ``column_of_scalars`` reads the scratch through a numpy view and
      materializes fresh output arrays, so reuse is invisible; any
      non-homogeneous column falls back to the full
      :func:`serve_table_builder` path — byte-identical by construction.
    * :meth:`dispatch` — launch the compiled program. JAX dispatch is
      asynchronous: the returned table holds device arrays whose math may
      still be running, so the caller can start gathering the next flush.
    * :meth:`flatten` — block on the device results and produce exactly
      the records :func:`serve_record_builder` emits.

    Failure semantics stay with the caller: the serving runtime reproduces
    the monolithic scorer's quarantine fallback by re-scoring a failed
    flush through ``micro_batch_score_function`` itself, so pipelined
    records are bit-equal to serial ones on every path."""

    def __init__(self, model):
        from ..readers.readers import _field_name_of
        self._build = serve_table_builder(model)
        self.dispatch = compiled_score_function(model)
        self.flatten = serve_record_builder(model)
        self._extractors = [(f, _field_name_of(f.origin_stage.extract_fn))
                            for f in model.raw_features]
        #: per-feature pooled scratch (object dtype; single-thread use —
        #: the batcher owns the gather stage)
        self._scratch: Dict[str, np.ndarray] = {}

    def gather(self, rows: Sequence[Dict[str, Any]]) -> FeatureTable:
        from ..table import column_of_scalars
        n = len(rows)
        if not n or not all(isinstance(r, dict) for r in rows):
            return self._build(rows)
        cols: Dict[str, Column] = {}
        for f, field in self._extractors:
            col = None
            if field is not None:
                buf = self._scratch.get(f.name)
                if buf is None or buf.shape[0] < n:
                    # grow to the enclosing bucket so one block serves
                    # every flush size up to max_batch
                    cap = max(64, 1 << (n - 1).bit_length())
                    buf = np.empty(cap, dtype=object)
                    self._scratch[f.name] = buf
                for i, r in enumerate(rows):
                    buf[i] = r.get(field)
                col = column_of_scalars(f.feature_type, buf[:n])
            if col is None:
                # a wrapper/None/string (or a custom extractor) broke the
                # fast sweep: rebuild the WHOLE table through the original
                # path so the result is identical to the serial builder
                return self._build(rows)
            cols[f.name] = col
        return FeatureTable(cols, n)


def micro_batch_score_function(model) -> Callable[[Sequence[Dict[str, Any]]], List[Dict[str, Any]]]:
    """Micro-batch scorer: builds a FeatureTable from a list of raw rows and
    runs the columnar/jitted DAG pass — the serving path that keeps the TPU
    busy (SURVEY §2.10 P4: streaming micro-batches). The numeric transformer
    tail runs as one compiled XLA program per device-fusable segment,
    reused across micro-batch sizes via the schema-fingerprinted plan
    cache (compiled_score_function → plan.py; docs/plan.md). For driving
    this under concurrent load — continuous batching, deadlines, a
    circuit breaker — see ``transmogrifai_tpu/serving`` (docs/serving.md).

    Malformed input does not kill the batch: a batch that fails schema
    validation (a string where a number is expected, a wrong-width vector)
    falls back to per-row scoring, and only the offending rows are
    **quarantined** — their result features come back None with the reason
    under :data:`SCORE_ERROR_KEY` — while every valid row still scores."""
    result_features = model.result_features
    compiled = compiled_score_function(model)
    _build_table = serve_table_builder(model)
    _records = serve_record_builder(model)

    def score(rows: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
        t0 = time.perf_counter()
        quarantined = 0
        with _obs_span("score.micro_batch", cat="score",
                       rows=len(rows)) as sp:
            try:
                out = _records(compiled(_build_table(rows)), len(rows))
            except (ScoreSchemaError, TypeError, ValueError) as batch_err:
                # isolate the offenders: score each row alone; rows that
                # still fail are quarantined instead of poisoning the batch
                out = []
                for row in rows:
                    try:
                        out.append(
                            _records(compiled(_build_table([row])), 1)[0])
                    except (ScoreSchemaError, TypeError, ValueError) as e:
                        rec = {f.name: None for f in result_features}
                        rec[SCORE_ERROR_KEY] = str(e) or str(batch_err)
                        out.append(rec)
                        quarantined += 1
                sp.add_event("score.quarantine", rows=quarantined,
                             batchError=str(batch_err)[:200])
                logger.warning(
                    "micro-batch scoring quarantined %d/%d row(s) "
                    "(first batch error: %s)", quarantined, len(rows),
                    batch_err)
        if _obs_metrics.metrics_enabled():
            # per-micro-batch latency + row/quarantine counters: the serve
            # path's p50/p95/p99 surfaces in summary()["observability"]
            # and metrics.prom (docs/observability.md)
            _obs_metrics.observe(
                "tg_score_microbatch_seconds", time.perf_counter() - t0,
                help="per-micro-batch scoring latency (columnar path)")
            _obs_metrics.inc_counter(
                "tg_score_rows_total", float(len(rows)),
                help="rows submitted to micro-batch scoring")
            if quarantined:
                _obs_metrics.inc_counter(
                    "tg_score_quarantined_total", float(quarantined),
                    help="rows quarantined under __score_error__")
        return out

    return score
