from .scoring import (  # noqa: F401
    SCORE_ERROR_KEY, ScoreSchemaError, compiled_score_function,
    micro_batch_score_function, score_function, serve_record_builder,
    serve_table_builder,
)
