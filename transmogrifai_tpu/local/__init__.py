from .scoring import score_function, micro_batch_score_function  # noqa: F401
