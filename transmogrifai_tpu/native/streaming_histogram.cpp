// Streaming decision-tree histogram (Ben-Haim & Tom-Tov, "A Streaming
// Parallel Decision Tree Algorithm", JMLR 11, 2010).
//
// Native equivalent of the reference's single Java component
// (utils/src/main/java/com/salesforce/op/utils/stats/StreamingHistogram.java,
// 299 LoC): a fixed-size histogram sketch supporting single-pass update,
// mergeability (the monoid the distributed reduce rides on), interpolated
// cumulative sums, and uniform-mass bin boundaries. Used by the TPU build's
// RawFeatureFilter / distribution machinery for numeric feature sketches
// computed host-side in one pass while arrays stream to the device.
//
// C ABI so Python binds via ctypes (no pybind11 in the image).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Bin {
  double p;  // centroid position
  double m;  // mass
};

struct SHist {
  int max_bins;
  std::vector<Bin> bins;  // sorted by p
  double total = 0.0;
  double min_v = HUGE_VAL;
  double max_v = -HUGE_VAL;
};

// Merge the two adjacent bins with the smallest gap until <= max_bins remain.
void compress(SHist* h) {
  auto& b = h->bins;
  while (static_cast<int>(b.size()) > h->max_bins) {
    size_t best = 0;
    double best_gap = HUGE_VAL;
    for (size_t i = 0; i + 1 < b.size(); ++i) {
      double gap = b[i + 1].p - b[i].p;
      if (gap < best_gap) {
        best_gap = gap;
        best = i;
      }
    }
    double m = b[best].m + b[best + 1].m;
    b[best].p = (b[best].p * b[best].m + b[best + 1].p * b[best + 1].m) / m;
    b[best].m = m;
    b.erase(b.begin() + best + 1);
  }
}

void insert_point(SHist* h, double x, double w) {
  auto& b = h->bins;
  auto it = std::lower_bound(
      b.begin(), b.end(), x,
      [](const Bin& bin, double v) { return bin.p < v; });
  if (it != b.end() && it->p == x) {
    it->m += w;
  } else {
    b.insert(it, Bin{x, w});
  }
  h->total += w;
  h->min_v = std::min(h->min_v, x);
  h->max_v = std::max(h->max_v, x);
  compress(h);
}

}  // namespace

extern "C" {

SHist* sh_create(int max_bins) {
  auto* h = new SHist();
  h->max_bins = max_bins < 2 ? 2 : max_bins;
  h->bins.reserve(h->max_bins + 1);
  return h;
}

void sh_free(SHist* h) { delete h; }

void sh_update(SHist* h, const double* xs, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    double x = xs[i];
    if (!std::isnan(x)) insert_point(h, x, 1.0);
  }
}

void sh_update_weighted(SHist* h, const double* xs, const double* ws,
                        int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    if (!std::isnan(xs[i]) && ws[i] > 0) insert_point(h, xs[i], ws[i]);
  }
}

// Monoid merge (paper's Merge procedure): union of bins then compress.
void sh_merge(SHist* dst, const SHist* src) {
  std::vector<Bin> merged;
  merged.reserve(dst->bins.size() + src->bins.size());
  std::merge(dst->bins.begin(), dst->bins.end(), src->bins.begin(),
             src->bins.end(), std::back_inserter(merged),
             [](const Bin& a, const Bin& b) { return a.p < b.p; });
  // coalesce identical centroids
  std::vector<Bin> out;
  for (const Bin& bin : merged) {
    if (!out.empty() && out.back().p == bin.p) {
      out.back().m += bin.m;
    } else {
      out.push_back(bin);
    }
  }
  dst->bins = std::move(out);
  dst->total += src->total;
  dst->min_v = std::min(dst->min_v, src->min_v);
  dst->max_v = std::max(dst->max_v, src->max_v);
  compress(dst);
}

// Replace the sketch's whole state (checkpoint restore / host-normalized
// merge write-back). Bins must arrive sorted by centroid; compress() keeps
// the max_bins invariant if the caller hands more.
void sh_load(SHist* h, const double* centers, const double* masses, int64_t n,
             double total, double min_v, double max_v) {
  h->bins.clear();
  h->bins.reserve(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; ++i) h->bins.push_back(Bin{centers[i], masses[i]});
  h->total = total;
  h->min_v = min_v;
  h->max_v = max_v;
  compress(h);
}

int64_t sh_num_bins(const SHist* h) {
  return static_cast<int64_t>(h->bins.size());
}

double sh_total(const SHist* h) { return h->total; }
double sh_min(const SHist* h) { return h->min_v; }
double sh_max(const SHist* h) { return h->max_v; }

void sh_get_bins(const SHist* h, double* centers, double* masses) {
  for (size_t i = 0; i < h->bins.size(); ++i) {
    centers[i] = h->bins[i].p;
    masses[i] = h->bins[i].m;
  }
}

// Paper's Sum procedure: estimated number of points <= b (trapezoid
// interpolation between adjacent centroids).
double sh_sum(const SHist* h, double b) {
  const auto& bins = h->bins;
  if (bins.empty()) return 0.0;
  if (b >= bins.back().p) {
    double s = h->total - bins.back().m / 2.0;
    // beyond the last centroid, ramp the last half-bin up to max
    if (h->max_v > bins.back().p && b < h->max_v) {
      double frac = (b - bins.back().p) / (h->max_v - bins.back().p);
      return s + bins.back().m / 2.0 * frac;
    }
    return h->total;
  }
  if (b < bins.front().p) {
    if (h->min_v < bins.front().p && b >= h->min_v) {
      double frac = (b - h->min_v) / (bins.front().p - h->min_v);
      return bins.front().m / 2.0 * frac;
    }
    return 0.0;
  }
  size_t i = 0;
  while (i + 1 < bins.size() && bins[i + 1].p <= b) ++i;
  // s(b) = sum_{j<i} m_j + m_i/2 + (m_i + m_b)/2 * (b-p_i)/(p_{i+1}-p_i)
  double s = 0.0;
  for (size_t j = 0; j < i; ++j) s += bins[j].m;
  s += bins[i].m / 2.0;
  if (i + 1 < bins.size() && bins[i + 1].p > bins[i].p) {
    double pi = bins[i].p, pj = bins[i + 1].p;
    double mi = bins[i].m, mj = bins[i + 1].m;
    double frac = (b - pi) / (pj - pi);
    double mb = mi + (mj - mi) * frac;
    s += (mi + mb) / 2.0 * frac;
  }
  return s;
}

// Paper's Uniform procedure: B-1 interior boundaries splitting mass evenly.
void sh_uniform(const SHist* h, int num_bins, double* boundaries) {
  double step = h->total / num_bins;
  int out = 0;
  for (int k = 1; k < num_bins; ++k) {
    double target = step * k;
    // binary search over sh_sum via centroid positions
    double lo = h->min_v, hi = h->max_v;
    for (int it = 0; it < 60; ++it) {
      double mid = (lo + hi) / 2.0;
      if (sh_sum(h, mid) < target) {
        lo = mid;
      } else {
        hi = mid;
      }
    }
    boundaries[out++] = (lo + hi) / 2.0;
  }
}

}  // extern "C"
