// Native host-side text kernels: token hashing and fused tokenize+hash.
//
// The TPU build's equivalent of the reference's JVM text machinery (Lucene
// analyzers + Spark HashingTF running on executors — reference:
// core/.../impl/feature/TextTokenizer.scala, OPCollectionHashingVectorizer.scala,
// SmartTextVectorizer.scala). Strings never belong on the TPU: the hashing
// trick runs on the host, and this library keeps that path at C speed while
// the resulting count matrices go to the device for the MXU work.
//
// Parity contract with the Python fallback (impl/feature/vectorizers.py):
// - hashes are zlib crc32 over the token's UTF-8 bytes, mod num_hashes
//   (bit-identical: we link the same zlib);
// - tokenize_hash_count reproduces tokenize_text() for pure-ASCII docs
//   (lowercase, split on non-[A-Za-z0-9_], min token length) and flags
//   non-ASCII docs for the caller to handle with the Python tokenizer
//   (Python \w is unicode-aware; we do not re-implement Unicode here).
//
// Built by utils/text_native.py on first use (g++ -O2 -shared -lz), cached
// in native/_build/; everything degrades to the numpy/Python implementation
// when no toolchain is present.

#include <cstdint>
#include <cstring>
#include <zlib.h>

extern "C" {

// Hash pre-tokenized tokens into per-document count rows.
// buf: concatenated UTF-8 bytes of every token; tok_offs: (n_toks+1) byte
// offsets; doc_starts: (n_docs+1) token index boundaries per document.
// out: (n_docs * num_hashes) float32, zero-initialized by the caller.
void tg_hash_tokens(const char* buf, const int64_t* tok_offs, int64_t n_toks,
                    const int64_t* doc_starts, int64_t n_docs,
                    int32_t num_hashes, int32_t binary, float* out) {
    (void)n_toks;
    for (int64_t d = 0; d < n_docs; ++d) {
        float* row = out + d * num_hashes;
        for (int64_t t = doc_starts[d]; t < doc_starts[d + 1]; ++t) {
            const unsigned char* p =
                reinterpret_cast<const unsigned char*>(buf + tok_offs[t]);
            const int64_t len = tok_offs[t + 1] - tok_offs[t];
            const uint32_t h =
                static_cast<uint32_t>(crc32(0L, p, static_cast<uInt>(len)));
            row[h % static_cast<uint32_t>(num_hashes)] += 1.0f;
        }
        if (binary) {
            for (int32_t j = 0; j < num_hashes; ++j)
                if (row[j] > 1.0f) row[j] = 1.0f;
        }
    }
}

static inline bool word_byte(unsigned char c) {
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
           (c >= '0' && c <= '9') || c == '_';
}

// Fused tokenize(lowercase, split on non-word) + crc32 hash + count for
// packed documents. Non-ASCII documents are skipped with needs_py[d]=1 so
// the caller can run the Unicode-aware Python tokenizer on just those rows.
// buf: concatenated doc bytes; offs: (n_docs+1) byte offsets.
void tg_tokenize_hash_count(const char* buf, const int64_t* offs,
                            int64_t n_docs, int32_t num_hashes,
                            int32_t min_token_len, int32_t binary,
                            float* out, uint8_t* needs_py) {
    unsigned char tok[4096];
    for (int64_t d = 0; d < n_docs; ++d) {
        const unsigned char* p =
            reinterpret_cast<const unsigned char*>(buf + offs[d]);
        const int64_t len = offs[d + 1] - offs[d];
        bool ascii = true;
        for (int64_t i = 0; i < len; ++i) {
            if (p[i] >= 0x80) { ascii = false; break; }
        }
        if (!ascii) { needs_py[d] = 1; continue; }
        needs_py[d] = 0;
        float* row = out + d * num_hashes;
        int64_t i = 0;
        while (i < len) {
            while (i < len && !word_byte(p[i])) ++i;
            int64_t tl = 0;
            while (i < len && word_byte(p[i])) {
                unsigned char c = p[i];
                if (c >= 'A' && c <= 'Z') c = static_cast<unsigned char>(c + 32);
                if (tl < static_cast<int64_t>(sizeof(tok))) tok[tl] = c;
                ++tl;
                ++i;
            }
            if (tl > static_cast<int64_t>(sizeof(tok))) {
                // pathological >4 KB token: punt the whole doc to Python
                // rather than hash a truncation
                std::memset(row, 0, sizeof(float) * num_hashes);
                needs_py[d] = 1;
                break;
            }
            if (tl >= min_token_len) {
                const uint32_t h = static_cast<uint32_t>(
                    crc32(0L, tok, static_cast<uInt>(tl)));
                row[h % static_cast<uint32_t>(num_hashes)] += 1.0f;
            }
        }
        if (needs_py[d]) continue;
        if (binary) {
            for (int32_t j = 0; j < num_hashes; ++j)
                if (row[j] > 1.0f) row[j] = 1.0f;
        }
    }
}

}  // extern "C"
