"""Transform-plan compiler: one XLA program per device-fusable segment.

The paper's substrate swap is "jit-compiled kernels instead of Catalyst";
round 5 proved the shape of the win by fusing the per-family sweep glue into
single jitted programs (docs/benchmarks.md). This module applies the same
cure to the fit-and-transform DAG: instead of dispatching every transformer
as its own executable (each with a ~2.7 ms dispatch bubble, ~70-130 ms on
tunneled backends), a *plan* partitions a topologically-ordered run of
fitted/pure transformer stages into maximal device-fusable segments — stages
exposing a pure-jax ``device_columnar`` dual — separated by host stages
(object-array text/map fronts, row lambdas), and traces each segment into
ONE jitted program. XLA then fuses across stage boundaries and dead-code
eliminates intermediates nobody reads — the reference's
``applyOpTransformations`` layer fusion (FitStagesUtil.scala:96-119) and
whole-stage-codegen idea, landed on our side of the swap.

Consumers: ``fit_and_transform_dag`` (each layer's transformer run),
``apply_transformations_dag`` (→ ``OpWorkflow.score()``), and
``local/scoring.compiled_score_function`` (→ ``micro_batch_score_function``)
all call :func:`apply_planned`. Plans are cached in a bounded LRU
(``TG_PLAN_CACHE_MAX``, defaulting to the validators' ``_FUSED_CACHE``
bound) keyed by stage-uid sequence + input schema fingerprint.

Robustness interplay is part of the design, not an afterthought
(docs/plan.md "Fallback semantics"):

* planning is skipped outright when per-stage fault semantics are active —
  ``OpWorkflow.with_fault_policy()`` (the caller passes eager) or
  ``TG_CHAOS`` / armed non-``plan.*`` injection sites — so PR 1's per-stage
  retry/quarantine behavior is byte-for-byte preserved under chaos;
* a planned run that *raises* (including the ``plan.segment_execute``
  injection site) falls back to eager per-stage dispatch for that run, and
  the fallback is recorded as a FaultLog ``plan_fallback`` event + span
  event — never silent.

Observability: ``plan.compile`` / ``plan.execute`` / ``plan.segment`` spans,
the ``tg_dispatch_total`` counter (top-level device executable launches:
one per device-capable stage in eager mode, one per fused segment planned)
and ``tg_device_transfer_total`` (host→device uploads). All zero-write when
observability is off. Every plan build and every per-bucket first dispatch
is additionally reported to the compile ledger with a classified cause
(cold / schema-change / bucket-change / cache-eviction), and every segment
dispatch reports its shape-predicted device bytes to the memory
observatory (observability/ledger.py, observability/devicemem.py —
docs/observability.md "Compile & memory ledger").
"""
from __future__ import annotations

import logging
import os
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .observability import devicemem as _devicemem
from .observability import ledger as _ledger
from .observability import metrics as _obs_metrics
from .observability.trace import span as _obs_span
from .table import Column, FeatureTable

logger = logging.getLogger(__name__)

#: env switch: TG_PLAN=0 disables the planner process-wide (eager dispatch)
PLAN_ENV = "TG_PLAN"

_FALSY = ("", "0", "false", "False", "no")

_enabled_override: Optional[bool] = None

#: plan LRU: (stage identity seq, schema fp, options) → TransformPlan | None
#: (None caches "planning infeasible for this shape" so the probe cost is
#: paid once). Bounded like the validators' _FUSED_CACHE: each entry pins
#: jitted executables, so a long-lived server fitting many schemas must not
#: grow compiled-program memory without bound.
_PLAN_CACHE: "OrderedDict[Any, Optional[TransformPlan]]" = OrderedDict()
_PLAN_CACHE_MAX = int(os.environ.get(
    "TG_PLAN_CACHE_MAX", os.environ.get("TG_FUSED_CACHE_MAX", "32")))


def plan_enabled() -> bool:
    """True when the transform-plan compiler may be used (TG_PLAN, unless
    overridden programmatically)."""
    if _enabled_override is not None:
        return _enabled_override
    return os.environ.get(PLAN_ENV, "1") not in _FALSY


def enable_planning(on: Optional[bool]) -> None:
    """Force planning on/off from code (tests, A/B benches); ``None`` hands
    control back to the ``TG_PLAN`` environment switch."""
    global _enabled_override
    _enabled_override = None if on is None else bool(on)


def planning_applicable() -> bool:
    """Planning is allowed only when per-stage fault semantics are not in
    play: under ``TG_CHAOS`` or any armed non-``plan.*``/``serve.*``
    injection site the eager per-stage path runs so PR 1 retry/quarantine
    behavior is exactly preserved. Sites prefixed ``plan.`` target the
    planner itself and keep it active — they exercise the runtime
    fallback; sites prefixed ``serve.`` / ``drift.`` target the serving
    runtime and its drift monitor *above* the planner
    (serving/runtime.py, serving/drift.py), whose chaos tests must
    exercise the real planned dispatch path, not an eager stand-in;
    sites prefixed ``oom.`` inject resource exhaustion into the planned /
    serve / stream / sweep dispatch paths themselves — disabling the
    planner would disable exactly the path under test; sites prefixed
    ``fleet.`` target the replica front door a further layer up
    (serving/frontdoor.py) and keep the planner active for the same
    reason as ``serve.*``; the ``aot.load`` site targets the AOT
    program-store load path *inside* the planner's segment dispatch
    (programstore/store.py) — disabling the planner would disable
    exactly the fallback ladder under test; sites prefixed ``place.``
    target the fleet's model-placement layer (serving/placement.py),
    another floor above the planner, and keep it active like
    ``fleet.*``."""
    if not plan_enabled():
        return False
    from .robustness import faults
    if os.environ.get(faults.CHAOS_ENV):
        return False
    armed = faults.active_sites()
    if any(not s.startswith(("plan.", "serve.", "drift.", "oom.",
                             "fleet.", "aot.", "place."))
           for s in armed):
        return False
    return True


def clear_plan_cache() -> None:
    """Drop every cached plan (test isolation; see tests/conftest.py)."""
    _PLAN_CACHE.clear()


def cache_stats() -> Dict[str, int]:
    """{"entries", "max"} — surfaced in summary()["observability"]."""
    return {"entries": len(_PLAN_CACHE), "max": _PLAN_CACHE_MAX}


# ---------------------------------------------------------------------------
# Stage classification
# ---------------------------------------------------------------------------

def is_device_capable(stage: Any) -> bool:
    """A stage that exposes the pure-jax columnar dual and has not opted out
    dynamically (e.g. a SelectedModel whose family has no traceable
    predict)."""
    return (hasattr(stage, "device_columnar")
            and getattr(stage, "device_fusable", True))


def count_eager_dispatch(stage: Any) -> None:
    """Account one eager transform of a device-capable stage. Eager (unjitted)
    columnar execution launches at least one executable per input column
    chain — op-by-op dispatch never fuses across columns — so the counter
    adds ``max(1, |device inputs|)``: a conservative lower bound of the
    launches the fused segment replaces with ONE (docs/plan.md)."""
    if not is_device_capable(stage):
        return
    _obs_metrics.inc_counter(
        "tg_dispatch_total", float(max(1, len(_device_inputs(stage)))),
        kind="stage",
        help="top-level device executable launches on the transform path "
        "(docs/plan.md)")


def _device_inputs(stage: Any) -> List[str]:
    if hasattr(stage, "device_inputs"):
        return list(stage.device_inputs())
    return [f.name for f in stage.input_features]


def _host_inputs(stage: Any) -> List[str]:
    return [f.name for f in stage.input_features]


def _numeric_table_col(col: Column) -> bool:
    dt = getattr(col.values, "dtype", None)
    return dt is not None and np.dtype(dt).kind in "fiub"


# ---------------------------------------------------------------------------
# Plan structure
# ---------------------------------------------------------------------------

class _DeviceSegment:
    """One maximal run of device-fusable stages traced into one jitted
    program. ``in_names`` are the columns the program reads (external to the
    segment), ``out_names`` the columns it materializes."""

    __slots__ = ("stages", "in_names", "out_names", "chain", "out_meta",
                 "out_shape", "in_shape", "seen_buckets", "fp_key",
                 "pred_cache", "aot_progs")

    def __init__(self, stages: List[Any], in_names: List[str],
                 out_names: List[str]):
        self.stages = stages
        self.in_names = in_names
        self.out_names = out_names
        self.out_meta: Dict[str, Tuple[Any, Dict[str, Any]]] = {}
        #: output column (itemsize, trailing shape) from the zero-row
        #: probe — what the byte prediction needs (devicemem)
        self.out_shape: Dict[str, Tuple[int, Tuple[int, ...]]] = {}
        #: input column trailing shapes from the zero-row probe — enough
        #: to reconstruct the traced avals at any padding bucket (staged
        #: inputs are always f32 values + a bool mask), which is what
        #: AOT export needs without a live dispatch (programstore/)
        self.in_shape: Dict[str, Tuple[int, ...]] = {}
        #: padding buckets this segment's jitted chain has already been
        #: dispatched at: the first dispatch of a NEW bucket is an XLA
        #: compile, recorded in the compile ledger
        self.seen_buckets: set = set()
        #: lazily-computed segment fingerprint hash (the cost-table key;
        #: cached — the serving hot path dispatches this per flush)
        self.fp_key: Optional[str] = None
        #: bucket → predicted bytes (schema-fixed per plan, so one
        #: computation per bucket serves every later dispatch)
        self.pred_cache: Dict[int, int] = {}
        #: bucket → AOT-deserialized program (programstore/store.py):
        #: dispatched INSTEAD of tracing ``chain`` — the zero-retrace
        #: cold-start path (docs/serving.md "AOT cold start")
        self.aot_progs: Dict[int, Any] = {}
        import jax
        fused = list(stages)
        outs = list(out_names)

        @jax.jit
        def chain(vals_list, mask_list):
            env = {nm: (v, m)
                   for nm, v, m in zip(in_names, vals_list, mask_list)}
            for s in fused:
                env[s.get_output().name] = s.device_columnar(env)
            return tuple(env[nm] for nm in outs)

        self.chain = chain


class TransformPlan:
    """An executable schedule: alternating host waves (eager per-stage
    dispatch) and device segments (one jitted program each)."""

    def __init__(self, steps: List[Tuple[str, Any]], cat: str):
        self.steps = steps
        self.cat = cat
        #: stable program identity (stage-uid sequence) + JSON schema
        #: fingerprint, set by get_plan — the compile ledger's
        #: classification baseline (observability/ledger.py)
        self.ident: str = "plan"
        self.fp_json: Any = None
        #: process-independent hash of (ident × schema fingerprint) —
        #: the AOT program store's plan-coverage key (stage uids survive
        #: save/load, so a fresh process computes the same hash;
        #: programstore/store.py)
        self.ident_hash: Optional[str] = None

    @property
    def num_segments(self) -> int:
        return sum(1 for k, _ in self.steps if k == "device")

    @property
    def num_host_stages(self) -> int:
        return sum(len(p) for k, p in self.steps if k == "host")

    def device_table_inputs(self, table: FeatureTable) -> List[str]:
        """Segment inputs that come straight from the caller's table (the
        user-input surface serve-time schema guards validate)."""
        produced = {s.get_output().name
                    for k, p in self.steps
                    for s in (p if k == "host" else p.stages)}
        out: List[str] = []
        for k, p in self.steps:
            if k != "device":
                continue
            for nm in p.in_names:
                if nm not in produced and nm in table and nm not in out:
                    out.append(nm)
        return out

    # -- execution -----------------------------------------------------------
    def execute(self, table: FeatureTable) -> FeatureTable:
        with _obs_span("plan.execute", cat=self.cat, rows=table.num_rows,
                       segments=self.num_segments,
                       hostStages=self.num_host_stages):
            seg_idx = 0
            for kind, payload in self.steps:
                if kind == "host":
                    for s in payload:
                        # a device-capable stage demoted to host (non-
                        # numeric inputs) still launches eager programs
                        count_eager_dispatch(s)
                        with _obs_span("stage.transform", cat=self.cat,
                                       uid=getattr(s, "uid", "?"),
                                       stage=type(s).__name__, planned=True):
                            table = s.transform(table)
                else:
                    table = self._run_segment(payload, table, seg_idx)
                    seg_idx += 1
        return table

    def _predicted_bytes(self, seg: _DeviceSegment, table: FeatureTable,
                         n_pad: int) -> int:
        """Shape-predicted device bytes of one padded segment dispatch:
        every input column staged at the bucket (f32 + bool mask) plus
        every materialized output at its probe-captured shape — the
        number admission control can subtract from the device budget
        before dispatch (observability/devicemem.py)."""
        from .utils.padding import padded_bytes
        total = 0
        for nm in seg.in_names:
            v = table[nm].values
            total += padded_bytes(n_pad, tuple(np.shape(v)[1:]), 4)
        for nm in seg.out_names:
            itemsize, trailing = seg.out_shape.get(nm, (4, ()))
            total += padded_bytes(n_pad, trailing, itemsize)
        return total

    def _run_segment(self, seg: _DeviceSegment,
                     table: FeatureTable, seg_idx: int = 0) -> FeatureTable:
        import jax.numpy as jnp

        from .manifest import sentinel_phase
        from .robustness import faults
        from .utils.padding import bucket_for
        # crash evidence: if the process dies past this point the run
        # sentinel says it was inside a device dispatch (OOM-kill suspect)
        sentinel_phase("device_dispatch")
        # deterministic chaos entry: a fault here models an XLA runtime
        # error mid-plan; apply_planned catches it and falls back to eager
        faults.inject("plan.segment_execute", key=seg.stages[0].uid)
        # chaos: a RESOURCE_EXHAUSTED here models the padded segment not
        # fitting on the device; apply_planned bisects the row batch to
        # smaller padding buckets before falling back to eager
        faults.inject("oom.plan", key=seg.stages[0].uid)
        n = table.num_rows
        n_pad = bucket_for(n)
        t0 = (time.perf_counter()
              if _obs_metrics.metrics_enabled() else None)
        transfers = 0
        vals_list, mask_list = [], []
        for nm in seg.in_names:
            col = table[nm]
            v, m = col.values, col.mask
            if isinstance(v, np.ndarray):
                v = np.asarray(v, dtype=np.float32)
                if n_pad != n:
                    v = np.concatenate(
                        [v, np.zeros((n_pad - n,) + v.shape[1:], v.dtype)])
                m = self._pad_mask_host(m, n, n_pad)
                v, m = jnp.asarray(v), jnp.asarray(m)
                transfers += 2
            else:
                if v.dtype != jnp.float32:
                    v = v.astype(jnp.float32)
                if n_pad != n:
                    v = jnp.pad(v, ((0, n_pad - n),) + ((0, 0),) * (v.ndim - 1))
                if m is None:
                    m = self._pad_mask_host(None, n, n_pad)
                    m = jnp.asarray(m)
                    transfers += 1
                else:
                    m = jnp.asarray(m)
                    if n_pad != n:
                        m = jnp.pad(m, (0, n_pad - n))
            vals_list.append(v)
            mask_list.append(m)
        if t0 is not None:
            _obs_metrics.observe(
                "tg_plan_transfer_seconds", time.perf_counter() - t0,
                help="host→device input staging per planned segment")
            _obs_metrics.inc_counter(
                "tg_device_transfer_total", float(transfers),
                help="host→device uploads (packed: see docs/plan.md)")
        _obs_metrics.inc_counter(
            "tg_dispatch_total", kind="plan_segment",
            help="top-level device executable launches on the transform "
            "path (docs/plan.md)")
        # compile & memory observatory: shape-predicted bytes before the
        # dispatch, per-bucket first-call compiles into the ledger, the
        # (segment fingerprint x bucket) cost row after
        subsystem = _ledger.current_subsystem("plan")
        predicted = seg.pred_cache.get(n_pad)
        if predicted is None:
            # one shape computation per (plan, bucket): the plan's schema
            # is fixed by its cache key, so later dispatches reuse it
            predicted = self._predicted_bytes(seg, table, n_pad)
            seg.pred_cache[n_pad] = predicted
        _devicemem.record_dispatch(subsystem, predicted, bucket=n_pad,
                                   rows=n)
        first_bucket = n_pad not in seg.seen_buckets
        if seg.fp_key is None:
            seg.fp_key = _ledger.cache_key_hash(
                (self.ident, seg_idx, tuple(seg.in_names),
                 tuple(seg.out_names), self.fp_json))
        seg_fp = seg.fp_key
        # AOT program store: the first dispatch at a new bucket asks the
        # open store sessions for a deserialized program BEFORE tracing
        # the jitted chain — the zero-retrace cold-start path. Any miss
        # (absent / key mismatch / corrupt / injected) degrades to the
        # trace below with a typed record (programstore/store.py).
        aot_fn = seg.aot_progs.get(n_pad)
        if aot_fn is None and first_bucket:
            from .programstore import store as _pstore
            aot_fn = _pstore.lookup(seg_fp, n_pad,
                                    component="plan-segment",
                                    ledger_key=f"{seg_fp}@{n_pad}")
            if aot_fn is not None:
                seg.aot_progs[n_pad] = aot_fn
        pre_stats = _devicemem.memory_stats()
        t_disp = time.perf_counter()
        with _obs_span("plan.segment", cat=self.cat,
                       stages=len(seg.stages), rows=n,
                       inputs=len(seg.in_names), outputs=len(seg.out_names),
                       aot=aot_fn is not None):
            outs = (aot_fn or seg.chain)(tuple(vals_list),
                                         tuple(mask_list))
        disp_secs = time.perf_counter() - t_disp
        post_stats = _devicemem.sample_measured(subsystem)
        # cost bytes: measured allocation delta where the backend reports
        # live-buffer stats, shape-predicted otherwise (CPU)
        cost_bytes = predicted
        if pre_stats is not None and post_stats is not None:
            delta = (post_stats.get("bytes_in_use", 0)
                     - pre_stats.get("bytes_in_use", 0))
            if delta > 0:
                cost_bytes = delta
        if first_bucket:
            seg_ident = f"{self.ident}/seg{seg_idx}"
            seg.seen_buckets.add(n_pad)
            if aot_fn is not None:
                # AOT hit: nothing was traced — no ledger build. The
                # dispatch still lands a cost row (execute side) so the
                # admission table stays warm.
                _devicemem.record_cost(seg_fp, n_pad, cost_bytes,
                                       execute_s=disp_secs)
            else:
                # the first dispatch at a NEW padding bucket
                # traces+compiles a fresh XLA executable inside the
                # jitted chain — that IS a program build (cold for the
                # first bucket, bucket-change when row growth crossed a
                # bucket boundary, aot-miss when a store should have
                # served it)
                _ledger.record_build(
                    subsystem, identity=seg_ident,
                    key=f"{seg_fp}@{n_pad}", fingerprint=self.fp_json,
                    bucket=n_pad, seconds=disp_secs, rows=n,
                    stages=len(seg.stages), cat=self.cat)
                _devicemem.record_cost(seg_fp, n_pad, cost_bytes,
                                       compile_s=disp_secs)
                # populate: offer the freshly traced program to any
                # active capture scope / cross-model store so the NEXT
                # process (or replica) deserializes instead of tracing
                from .programstore import store as _pstore
                _pstore.offer_segment(
                    seg_fp, n_pad, seg.chain,
                    (tuple(vals_list), tuple(mask_list)),
                    component="plan-segment", identity=seg_ident,
                    plan_ident=self.ident_hash)
        else:
            _devicemem.record_cost(seg_fp, n_pad, cost_bytes,
                                   execute_s=disp_secs)
        new_cols: Dict[str, Column] = {}
        for nm, (arr, msk) in zip(seg.out_names, outs):
            # slice padding back off; keep values device-resident (exactly
            # what the eager fused-substrate stages hand downstream)
            msk_np = None if msk is None else np.asarray(msk)[:n]
            if msk_np is not None and msk_np.all():
                msk_np = None
            ftype, md = seg.out_meta[nm]
            new_cols[nm] = Column(ftype, arr[:n], msk_np, dict(md))
        return table.with_columns(new_cols)

    @staticmethod
    def _pad_mask_host(m, n: int, n_pad: int) -> np.ndarray:
        """Masks always materialize as bool arrays (padding rows False) so
        the traced program has one stable structure across batch sizes."""
        out = np.zeros(n_pad, dtype=bool)
        if m is None:
            out[:n] = True
        else:
            out[:n] = np.asarray(m)
        return out


# ---------------------------------------------------------------------------
# Plan construction
# ---------------------------------------------------------------------------

def _build_plan(stages: List[Any], table: FeatureTable,
                keep_intermediates: bool, extra_keep: Sequence[str],
                cat: str) -> Optional[TransformPlan]:
    """Partition ``stages`` (topological order) into host waves and device
    segments, trace each segment, and probe metadata. Returns None when the
    sequence has nothing worth fusing."""
    producer: Dict[str, Any] = {}      # column name → producing stage
    is_dev: Dict[int, bool] = {}
    numeric: Dict[str, bool] = {}      # column name → float32-convertible
    for nm in table.column_names:
        numeric[nm] = _numeric_table_col(table[nm])

    from .table import DEVICE_KINDS
    for s in stages:
        dev = is_device_capable(s)
        if dev:
            # demote to host when any runtime input is non-numeric for the
            # fused program (e.g. a vectorizer front over object arrays)
            for nm in _device_inputs(s):
                if not numeric.get(nm, False):
                    dev = False
                    break
        is_dev[id(s)] = dev
        out = s.get_output()
        producer[out.name] = s
        numeric[out.name] = (dev
                             or out.feature_type.column_kind in DEVICE_KINDS)
    if not any(is_dev[id(s)] for s in stages):
        return None        # nothing to fuse — eager is already minimal

    # wave assignment: host wave w runs before device segment w; a stage
    # lands in the earliest slot its producers allow, so device segments are
    # maximal (stages fuse across interleaved-but-independent host stages)
    wave: Dict[int, int] = {}
    for s in stages:
        dev = is_dev[id(s)]
        ins = _device_inputs(s) if dev else _host_inputs(s)
        w = 0
        for nm in ins:
            p = producer.get(nm)
            if p is None:
                continue
            pw = wave[id(p)]
            # host wave w runs before device segment w, so only the
            # device→host crossing forces the consumer into the next wave
            w = max(w, pw + 1 if (is_dev[id(p)] and not dev) else pw)
        wave[id(s)] = w

    max_wave = max(wave.values()) if wave else 0
    sched: List[Tuple[str, List[Any]]] = []
    for w in range(max_wave + 1):
        host = [s for s in stages if not is_dev[id(s)] and wave[id(s)] == w]
        if host:
            sched.append(("host", host))
        dev_stages = [s for s in stages if is_dev[id(s)] and wave[id(s)] == w]
        # fusion barriers (reduction-bearing stages like the winning
        # model's Prediction emission) trace into their OWN program: a
        # reduction's summation order is only reproducible when its operand
        # arrives as a program parameter, so fusing it mid-segment would
        # break the planned≡eager bit-exactness contract (docs/plan.md)
        run: List[Any] = []
        for s in dev_stages:
            if getattr(s, "device_fusion_barrier", False):
                if run:
                    sched.append(("dev", run))
                    run = []
                sched.append(("dev", [s]))
            else:
                run.append(s)
        if run:
            sched.append(("dev", run))

    steps: List[Tuple[str, Any]] = []
    for i, (kind, group) in enumerate(sched):
        if kind == "host":
            steps.append(("host", group))
            continue
        seg_out = {s.get_output().name for s in group}
        in_names: List[str] = []
        for s in group:
            for nm in _device_inputs(s):
                if nm not in seg_out and nm not in in_names:
                    in_names.append(nm)
        if keep_intermediates:
            out_names = [s.get_output().name for s in group]
        else:
            # materialize only what escapes the segment: XLA DCE's the rest
            ext = set(extra_keep)
            for _, later in sched[i + 1:]:
                for t in later:
                    ext.update(_device_inputs(t) if is_dev[id(t)]
                               else _host_inputs(t))
            out_names = [s.get_output().name for s in group
                         if s.get_output().name in ext]
            if not out_names:
                continue   # fully dead segment: plan-level DCE, skip it
        steps.append(("device", _DeviceSegment(group, in_names, out_names)))

    if not any(k == "device" for k, _ in steps):
        return None        # DCE dropped every segment — plan is all-host
    plan = TransformPlan(steps, cat)

    # zero-row probe: output feature types + metadata are data-independent
    # (fill/pivot/slice provenance comes from fitted state and input
    # *metadata*, never values), so one eager pass over an empty table
    # captures them without paying a real eager run
    read_names: List[str] = []
    produced = {s.get_output().name for s in stages}
    for s in stages:
        for nm in set(_host_inputs(s)) | set(_device_inputs(s)):
            if nm not in produced and nm in table and nm not in read_names:
                read_names.append(nm)
    probe_cols: Dict[str, Column] = {}
    for nm in read_names:
        col = table[nm]
        v = col.values
        dt = np.dtype(getattr(v, "dtype", object))
        trailing = tuple(int(x) for x in v.shape[1:])
        probe_cols[nm] = Column(
            col.feature_type, np.zeros((0,) + trailing, dtype=dt),
            None if col.mask is None else np.zeros(0, dtype=bool),
            dict(col.metadata))
    probe = FeatureTable(probe_cols, 0)
    for s in stages:
        probe = s.transform(probe)
    for kind, payload in plan.steps:
        if kind != "device":
            continue
        for nm in payload.out_names:
            col = probe[nm]
            payload.out_meta[nm] = (col.feature_type, dict(col.metadata))
            try:
                itemsize = int(np.dtype(
                    getattr(col.values, "dtype", np.float32)).itemsize)
            except TypeError:
                itemsize = 4
            payload.out_shape[nm] = (
                itemsize, tuple(int(x) for x in np.shape(col.values)[1:]))
        for nm in payload.in_names:
            payload.in_shape[nm] = tuple(
                int(x) for x in np.shape(probe[nm].values)[1:])
    return plan


def _schema_fingerprint(stages: List[Any],
                        table: FeatureTable) -> Optional[Tuple]:
    """Per-column (name, dtype, trailing shape, mask-presence) of everything
    the sequence reads from the table: a plan is reusable exactly when this
    matches (row count is free — padding buckets absorb it)."""
    produced = {s.get_output().name for s in stages}
    items: List[Tuple] = []
    seen = set()
    for s in stages:
        for nm in list(_host_inputs(s)) + list(_device_inputs(s)):
            if nm in produced or nm in seen:
                continue
            seen.add(nm)
            col = table.get(nm)
            if col is None:
                # response features are train-only; anything else missing
                # is the eager path's (descriptive) error to raise
                continue
            v = col.values
            items.append((nm, str(getattr(v, "dtype", "object")),
                          tuple(int(x) for x in v.shape[1:]),
                          col.mask is None))
    return tuple(items)


def schema_fingerprint(stages: Sequence[Any],
                       table: FeatureTable) -> List[List[Any]]:
    """Public, JSON-ready view of the plan cache's schema fingerprint:
    ``[[column, dtype, trailing shape, mask-is-None], ...]`` over every
    external column the stage sequence reads from ``table``. Row count is
    deliberately absent (padding buckets absorb it), so a fingerprint
    recorded at save time matches any request batch of the same schema —
    the contract the serving warm-start rides (serving/warmup.py)."""
    fp = _schema_fingerprint(list(stages), table) or ()
    return [[nm, dt, list(shape), bool(maskless)]
            for nm, dt, shape, maskless in fp]


def get_plan(stages: Sequence[Any], table: FeatureTable, *,
             keep_intermediates: bool = True,
             extra_keep: Sequence[str] = (),
             cat: str = "score",
             min_device_stages: int = 1) -> Optional[TransformPlan]:
    """Compile (or fetch from the LRU) the plan for this stage sequence ×
    input schema. Returns None when planning is off, chaos is active, or
    the sequence has fewer than ``min_device_stages`` fusable stages (the
    serve path plans even a single stage — padding + program reuse still
    pay; the per-layer train runs ask for ≥2 so a lone-stage layer skips
    the probe/compile cost fusion cannot repay)."""
    if not planning_applicable():
        return None
    stages = list(stages)
    if sum(1 for s in stages if is_device_capable(s)) < min_device_stages:
        return None
    fp = _schema_fingerprint(stages, table)
    key = (tuple((s.uid, id(s)) for s in stages),
           fp, keep_intermediates, tuple(sorted(extra_keep)))
    if key in _PLAN_CACHE:
        _PLAN_CACHE.move_to_end(key)
        return _PLAN_CACHE[key]
    t0 = time.perf_counter()
    with _obs_span("plan.compile", cat=cat, stages=len(stages)) as sp:
        try:
            plan = _build_plan(stages, table, keep_intermediates,
                               extra_keep, cat)
        except Exception as e:  # infeasible shape → cached eager fallback
            logger.warning("plan compile failed (%s: %s); falling back to "
                           "eager dispatch for this stage sequence",
                           type(e).__name__, e)
            sp.set_attr(failed=f"{type(e).__name__}: {e}"[:200])
            plan = None
        if plan is not None:
            sp.set_attr(segments=plan.num_segments,
                        hostStages=plan.num_host_stages)
    if plan is not None:
        # compile ledger: plan (re)builds are classified against the
        # stage sequence's previous build — a cache miss alone says
        # "rebuilt", the ledger says WHY (schema-change with the changed
        # column named, eviction, cold) — docs/observability.md
        plan.ident = "plan/" + ",".join(
            str(getattr(s, "uid", "?")) for s in stages)
        plan.fp_json = [[nm, dt, list(shape), bool(maskless)]
                        for nm, dt, shape, maskless in (fp or ())]
        plan.ident_hash = _ledger.cache_key_hash(
            (plan.ident, plan.fp_json, keep_intermediates,
             tuple(sorted(extra_keep))))
        # AOT program store: a plan whose identity an open store session
        # covers is an assembly step, not a build — its segments will
        # dispatch deserialized programs, so recording a ledger build
        # here would fail the zero-retrace gate for work that was never
        # traced. An active store that does NOT cover it classifies the
        # build aot-miss (programstore/store.py; docs/serving.md).
        from .programstore import store as _pstore
        if _pstore.plan_covered(plan.ident_hash):
            _pstore.record_plan_hit(plan.ident_hash)
        else:
            if _pstore.sessions_active():
                _pstore.note_plan_miss(_ledger.cache_key_hash(key))
            _pstore.offer_plan_ident(plan.ident_hash)
            _ledger.record_build(
                _ledger.current_subsystem("plan"),
                identity=(plan.ident
                          + f"/ki={int(keep_intermediates)}"
                          + f"/ek={','.join(sorted(extra_keep))}"),
                key=_ledger.cache_key_hash(key), fingerprint=plan.fp_json,
                seconds=time.perf_counter() - t0,
                segments=plan.num_segments, cat=cat)
    _PLAN_CACHE[key] = plan
    _PLAN_CACHE.move_to_end(key)
    while len(_PLAN_CACHE) > _PLAN_CACHE_MAX:
        evicted_key, _ = _PLAN_CACHE.popitem(last=False)
        _ledger.record_eviction(_ledger.cache_key_hash(evicted_key))
    return plan


def export_plan_programs(plan: TransformPlan,
                         bucket: Optional[int] = None) -> int:
    """Offer every device segment of ``plan`` to the AOT program store
    at ``bucket`` (default the minimum padding bucket — where every warm
    flush of up to 256 rows lands), WITHOUT dispatching anything: the
    traced avals are reconstructed from the zero-row probe's shapes
    (staged inputs are always f32 values padded to the bucket plus a
    bool validity mask — `_run_segment`'s staging contract). This is the
    save-time populate path (``programstore.populate_for_save``) and the
    first-replica fallback when warm dispatches were already traced
    in-process. Returns segments offered; no-op (0) outside a capture
    scope / env store."""
    from .programstore import store as _pstore
    from .utils.padding import _MIN_BUCKET
    if not _pstore.aot_enabled():
        return 0
    import jax
    import jax.numpy as jnp
    n_pad = int(bucket or _MIN_BUCKET)
    offered = 0
    seg_idx = 0
    for kind, seg in plan.steps:
        if kind != "device":
            continue
        if seg.fp_key is None:
            seg.fp_key = _ledger.cache_key_hash(
                (plan.ident, seg_idx, tuple(seg.in_names),
                 tuple(seg.out_names), plan.fp_json))
        vals = tuple(
            jax.ShapeDtypeStruct((n_pad,) + seg.in_shape.get(nm, ()),
                                 jnp.float32)
            for nm in seg.in_names)
        masks = tuple(jax.ShapeDtypeStruct((n_pad,), jnp.bool_)
                      for _ in seg.in_names)
        offered += 1 if _pstore.offer_segment(
            seg.fp_key, n_pad, seg.chain, (vals, masks),
            component="plan-segment",
            identity=f"{plan.ident}/seg{seg_idx}",
            plan_ident=plan.ident_hash) else 0
        seg_idx += 1
    return offered


def _concat_columns(a: Column, b: Column) -> Column:
    """Row-concatenate two halves of a bisected run. Device (jnp) values
    stay on device; host/object arrays concat with numpy. A mask present
    on either half materializes on both (None = all-valid)."""
    va, vb = a.values, b.values
    if isinstance(va, np.ndarray) and isinstance(vb, np.ndarray):
        vals = np.concatenate([va, vb])
    else:
        import jax.numpy as jnp
        vals = jnp.concatenate([jnp.asarray(va), jnp.asarray(vb)])
    if a.mask is None and b.mask is None:
        mask = None
    else:
        mask = np.concatenate([a.valid_mask(), b.valid_mask()])
    return Column(a.feature_type, vals, mask, dict(a.metadata))


def _concat_tables(a: FeatureTable, b: FeatureTable) -> FeatureTable:
    cols = {nm: _concat_columns(a[nm], b[nm]) for nm in a.column_names}
    key = (None if a.key is None or b.key is None
           else np.concatenate([a.key, b.key]))
    return FeatureTable(cols, a.num_rows + b.num_rows, key)


def _execute_adaptive(plan: TransformPlan, table: FeatureTable) -> FeatureTable:
    """Run the plan; on resource exhaustion bisect the row batch into
    smaller padding buckets and concatenate the halves — bit-equal by
    construction (every planned stage is a per-row map; padding rows carry
    zero weight, so a half padded to a smaller bucket produces the exact
    per-row values of the full batch). Below the minimum bucket a further
    bisect cannot shrink the padded program, so the error propagates to
    the existing eager fallback."""
    from .robustness import resources
    from .utils.padding import _MIN_BUCKET
    try:
        return plan.execute(table)
    except Exception as e:
        n = table.num_rows
        if resources.classify_exhaustion(e) is None or n <= _MIN_BUCKET:
            raise
        mid = n // 2
        resources.record_downshift(
            "oom.plan", rows=n, splitRows=[mid, n - mid],
            error=f"{type(e).__name__}: {e}"[:200])
        logger.warning(
            "planned transform run exhausted device memory at %d rows; "
            "bisecting to %d + %d", n, mid, n - mid)
        lo = _execute_adaptive(plan, table.take(np.arange(0, mid)))
        hi = _execute_adaptive(plan, table.take(np.arange(mid, n)))
        return _concat_tables(lo, hi)


def apply_planned(stages: Sequence[Any], table: FeatureTable, *,
                  keep_intermediates: bool = True,
                  extra_keep: Sequence[str] = (),
                  cat: str = "score",
                  min_device_stages: int = 1) -> Optional[FeatureTable]:
    """Run the stage sequence as a compiled plan. Returns the transformed
    table, or None when the caller should dispatch eagerly (planning off /
    chaos active / nothing to fuse / the planned run raised and fell back).

    The fallback contract: a raised planned run records a FaultLog
    ``plan_fallback`` report (+ span event + tg_faults_total counter) and
    returns None; the caller's eager loop then produces identical results —
    plans never transform the input table in place. Resource exhaustion
    gets one extra rung first: the run bisects its row batch into smaller
    padding buckets (``oom_downshift``; docs/robustness.md) and only falls
    back to eager when even the minimum bucket exhausts."""
    plan = get_plan(stages, table, keep_intermediates=keep_intermediates,
                    extra_keep=extra_keep, cat=cat,
                    min_device_stages=min_device_stages)
    if plan is None:
        return None
    try:
        return _execute_adaptive(plan, table)
    except Exception as e:
        from .robustness.policy import FaultLog, FaultReport
        FaultLog.record(FaultReport(
            site="plan.execute", kind="plan_fallback",
            detail={"error": f"{type(e).__name__}: {e}"[:300],
                    "segments": plan.num_segments,
                    "stages": [getattr(s, "uid", "?") for s in stages]}))
        logger.warning(
            "planned transform run failed (%s: %s); falling back to eager "
            "per-stage dispatch for this run", type(e).__name__, e)
        return None
